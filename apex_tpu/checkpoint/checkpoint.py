"""Sharded, precision-portable checkpoint save/restore.

Replaces the reference's checkpoint story (SURVEY.md §5.4) the TPU way:

- reference examples do plain ``torch.save(state_dict)`` per rank
  (examples/imagenet/main_amp.py:178-193); O2 state dicts are cast to fp32
  via ``O2StateDictHook`` so checkpoints are precision-portable
  (apex/amp/_initialize.py:133-142)
- amp scale state round-trips via ``amp.state_dict()``
  (apex/amp/frontend.py:361-400)
- FP16_Optimizer/DistributedFusedLAMB persist master weights + opt state
  (apex/fp16_utils/fp16_optimizer.py:209-271,
  contrib/optimizers/distributed_fused_lamb.py:140,530)

Here one checkpoint captures the whole train-state pytree at once:

- **Format**: per-step directory ``step_<N>/`` holding ``arrays.npz``
  (flat ``keystr(path) -> ndarray``) + ``manifest.json`` (per-leaf dtype /
  shape / partition spec, mesh axes, step). Atomic via tmp-dir + rename.
- **Precision portability**: half-precision leaves (bf16/fp16) are stored
  as fp32 on disk and restored to the target dtype, so a checkpoint written
  by an O2 run loads into an O0 run and vice versa (O2StateDictHook parity).
- **Topology portability**: leaves are saved as *full* (unsharded) arrays
  with their logical ``PartitionSpec`` recorded; restore takes any ``mesh``
  — including one of a different data-parallel size — and ``device_put``\\ s
  each leaf with ``NamedSharding(mesh, spec)``. This is the "restart on a
  different-size mesh" design SURVEY §5.3/§5.4 calls for, which the
  reference cannot do (its per-rank torch.save pins world size).

Multi-host note: save fetches fully-addressable values, so in a true
multi-host deployment only process 0 writes (guarded below); restores are
per-process and re-shard via device_put.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import shutil
import time
import zlib
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from apex_tpu.multi_tensor import flat as _flat

_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"
_PACK = "arrays.pack"
_LATEST = "latest"
_PACK_ALIGN = 64


def shard_file(rank: int) -> str:
    """On-disk name of one shard's array file in a format-3 (single-axis)
    sharded checkpoint."""
    return f"shard_{int(rank):05d}.npz"


def shard_file_coords(coords) -> str:
    """On-disk name of one mesh coordinate's array file in a format-4
    (multi-axis) sharded checkpoint: ``shard_<c0>_<c1>_..._<ck>.npz``
    with one coordinate per mesh axis, in the manifest ``topology``'s
    ``mesh_axes`` order."""
    return "shard_" + "_".join(str(int(c)) for c in coords) + ".npz"


def _coord_key(coords) -> str:
    """Manifest key of one shard coordinate (per-leaf ``crc32_shards``
    dict): the leaf's own lead-axis coordinates joined with ``_``."""
    return "_".join(str(int(c)) for c in coords)


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint on disk failed integrity verification (missing files,
    unreadable archive, truncated arrays, or CRC32 digest mismatch)."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential-backoff retry for storage I/O during save.

    Transient storage faults (GCS 5xx, NFS hiccups, full-but-recovering
    disks) should not kill a training run mid-save; each save attempt
    rewrites its tmp dir from scratch, so retrying is idempotent."""

    max_attempts: int = 3
    base_delay: float = 0.05  # seconds; doubles per attempt
    max_delay: float = 2.0
    retryable: tuple = (OSError,)

    def delay(self, attempt: int) -> float:
        return min(self.base_delay * (2.0 ** attempt), self.max_delay)


# Test-only fault-injection point (see apex_tpu.resilience.chaos). When set,
# called as hook(event, path) at each storage operation; it may raise to
# simulate a write failure or sleep to simulate slow storage.  Events:
# "write_arrays", "write_shard" (once per rank file of a sharded save),
# "write_manifest", "commit", "read_arrays".
_fault_hook: Optional[Callable[[str, str], None]] = None


def set_fault_hook(hook: Optional[Callable[[str, str], None]]):
    """Install (or clear, with None) the storage fault hook. Returns the
    previous hook so tests can restore it."""
    global _fault_hook
    prev, _fault_hook = _fault_hook, hook
    return prev


def _fault(event: str, path: str) -> None:
    if _fault_hook is not None:
        _fault_hook(event, path)

# dtypes stored as fp32 on disk for precision portability (O2StateDictHook
# parity, _initialize.py:133-142)
_HALF_DTYPES = ("bfloat16", "float16")


def _keystr(path) -> str:
    return jax.tree_util.keystr(path)


def _path_parts(path) -> list:
    """Structured path components (dict keys / attr names / indices as
    strings) — stored in the manifest so ``target=None`` restore does not
    have to re-parse ``keystr`` output (which mangles keys containing
    quotes or brackets)."""
    parts = []
    for e in path:
        if isinstance(e, jax.tree_util.DictKey):
            parts.append(str(e.key))
        elif isinstance(e, jax.tree_util.SequenceKey):
            parts.append(str(e.idx))
        elif isinstance(e, jax.tree_util.GetAttrKey):
            parts.append(str(e.name))
        elif isinstance(e, jax.tree_util.FlattenedIndexKey):
            parts.append(str(e.key))
        else:  # unknown key type: best-effort string
            parts.append(str(e))
    return parts


def _is_spec_leaf(x) -> bool:
    return x is None or isinstance(x, (PartitionSpec, NamedSharding))


def _spec_map(shardings, tree) -> dict:
    """Flatten a ``shardings`` pytree that may be a *structure prefix* of
    ``tree`` into ``{structured-path-tuple: PartitionSpec}`` (a prefix
    spec applies to every leaf under its subtree — same broadcast rule
    as pjit in_shardings).  Keyed by structured path, not keystr, so
    spec association survives keystr mangling/collisions."""
    flat_specs: list = []

    def _collect(spec, subtree):
        if isinstance(spec, NamedSharding):
            spec = spec.spec
        n = len(jax.tree_util.tree_leaves(subtree))
        flat_specs.extend([spec] * n)

    jax.tree_util.tree_map(_collect, shardings, tree, is_leaf=_is_spec_leaf)
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    if len(paths) != len(flat_specs):
        raise ValueError("shardings tree is not a structure prefix of the checkpoint tree")
    return {
        tuple(_path_parts(path)): spec
        for (path, _), spec in zip(paths, flat_specs)
        if spec is not None
    }


def _spec_to_json(spec) -> Optional[list]:
    if spec is None:
        return None
    out = []
    for part in spec:
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append(list(part))
        else:
            out.append(part)
    return out


def _spec_from_json(parts) -> PartitionSpec:
    if parts is None:
        return PartitionSpec()
    return PartitionSpec(*[tuple(p) if isinstance(p, list) else p for p in parts])


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step_{int(step):010d}")


def _complete_steps(ckpt_dir: str) -> list:
    """Steps with a complete (renamed, manifest-bearing) directory. Tolerant
    of crash artifacts: ``step_N.tmp`` leftovers and junk names are skipped."""
    steps = []
    if os.path.isdir(ckpt_dir):
        for name in os.listdir(ckpt_dir):
            if not name.startswith("step_") or name.endswith(".tmp"):
                continue
            digits = name[len("step_"):]
            # int() alone is too permissive ("+3", "1_0", " 3" all parse) and
            # str.isdigit alone accepts Unicode digits int() may reject ("³")
            # — only the exact zero-padded ASCII-decimal form
            # save_checkpoint writes counts as a checkpoint
            if not (digits.isascii() and digits.isdecimal()):
                continue
            s = int(digits)
            if os.path.isfile(os.path.join(ckpt_dir, name, _MANIFEST)):
                steps.append(s)
    return sorted(steps)


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest complete checkpoint step in ``ckpt_dir``, or None."""
    marker = os.path.join(ckpt_dir, _LATEST)
    if os.path.exists(marker):
        try:
            with open(marker) as f:
                s = int(f.read().strip())
        except ValueError:
            s = None  # truncated marker from a crashed save — fall through
        if s is not None and os.path.exists(
            os.path.join(step_dir(ckpt_dir, s), _MANIFEST)
        ):
            return s
    steps = _complete_steps(ckpt_dir)
    return max(steps) if steps else None


def save_checkpoint(
    ckpt_dir: str,
    tree: Any,
    *,
    step: int,
    shardings: Any = None,
    keep: Optional[int] = None,
    fp32_portable: bool = True,
    packed: bool = False,
    blocking: bool = True,
    retry: Optional[RetryPolicy] = None,
    shard_axis: Optional[str] = None,
    shard_axes: Optional[Any] = None,
    data_state: Optional[dict] = None,
) -> str:
    """Write ``tree`` as checkpoint ``step`` under ``ckpt_dir``.

    ``shardings`` — optional pytree of ``PartitionSpec`` (or leaves carrying
    ``.spec``, e.g. ``NamedSharding``) matching ``tree``'s structure prefix;
    recorded in the manifest so :func:`restore_checkpoint` can re-shard onto
    any mesh. ``keep`` — if set, delete all but the newest ``keep`` steps.
    ``packed`` — store leaves in one flat superblock file gathered by the
    native threaded pack (apex_C-parity host runtime,
    :mod:`apex_tpu._native`) instead of npz zip framing; restore
    auto-detects either format.

    ``blocking=False`` — return as soon as the tree is snapshotted to host
    memory; disk serialization runs on a background writer thread
    (:mod:`apex_tpu.resilience.async_checkpoint`) so the train loop keeps
    stepping during the write (the snapshot means later donation/mutation
    of the device buffers cannot corrupt the save).  Any save — async or
    blocking — first *fences* on a still-in-flight async write, as does
    interpreter exit; a failed background write (after retries) re-raises
    at that fence.  ``retry`` — :class:`RetryPolicy` for transient storage
    errors (each attempt rewrites the tmp dir from scratch).

    Every array's CRC32 digest is recorded in ``manifest.json`` for
    restore-side integrity verification (:func:`verify_checkpoint`).

    ``shard_axis`` — name of the mesh axis ZeRO state is sharded over
    (e.g. ``"data"``).  Leaves whose ``shardings`` spec LEADS with that
    axis are treated as a stack of per-rank partitions along axis 0:
    each rank's slice goes to its own ``shard_<r>.npz`` file with its
    own CRC32 digest (``crc32_shards`` in the manifest), and the
    manifest gains a top-level ``topology`` record (axis name, shard
    count, mesh shape when recoverable).  Restore understands the
    format transparently — including onto a mesh of a *different* shard
    count (see :func:`restore_checkpoint`'s reshard notes).  Replicated
    leaves (spec not led by ``shard_axis``) are stored once, exactly as
    in the unsharded format.  Sharded saves require ``shardings`` and
    are npz-only (``packed=True`` is rejected).

    ``shard_axes`` — the multi-axis generalization (**format 4**): an
    *ordered* mapping of mesh axis name → size (e.g. ``{"data": 4,
    "pipeline": 1, "tensor": 2}``).  Leaves whose spec LEADS with one or
    more of those axis names (one name per leading dim, in dim order)
    are stacks of per-coordinate partitions; each mesh coordinate's
    slice goes to ``shard_<c0>_<c1>_..._<ck>.npz`` (coordinates in
    ``shard_axes`` order; axes a leaf is not sharded over sit at 0) with
    a per-coordinate CRC32 digest (``crc32_shards`` dict keyed by the
    leaf's own lead coordinates).  The manifest's ``topology`` record
    carries the full ``mesh_axes`` shape, and restore re-partitions
    across any N→M reshape of the mesh (``docs/resilience.md`` "3D
    topologies").  Mutually exclusive with ``shard_axis``; format-3
    checkpoints keep restoring through the same path.

    ``data_state`` — optional compact JSON record of the input
    pipeline's position (the checkpointable-iterator protocol's
    ``state_dict()``, docs/data.md).  Stored under the manifest's
    ``data_state`` key — atomically with the arrays, through the async
    writer too — and read back via :func:`load_data_state`, so model
    state and iterator position can never land in different steps.

    Returns the checkpoint directory path.
    """
    # Only process 0 writes; the guard precedes any device_get so non-writing
    # hosts pay no host transfer. (Globally-sharded multi-host arrays would
    # need an all_gather-to-host first — out of scope like the reference's
    # per-rank torch.save, SURVEY §5.4.)
    if jax.process_index() != 0:
        return step_dir(ckpt_dir, step)

    # fence: at most one write in flight; a prior async save must land (or
    # surface its error) before this one starts
    from apex_tpu.resilience import async_checkpoint as _async

    _async.wait_for_save()

    if shard_axis is not None and shard_axes is not None:
        raise ValueError("pass shard_axis (format 3) or shard_axes "
                         "(format 4), not both")
    if (shard_axis is not None or shard_axes is not None) \
            and shardings is None:
        raise ValueError(
            "shard_axis/shard_axes requires shardings: the PartitionSpec "
            "tree is what identifies which leaves are per-rank partitions")
    if (shard_axis is not None or shard_axes is not None) and packed:
        raise ValueError("sharded checkpoints are npz-only (packed=False)")
    if shard_axes is not None:
        shard_axes = {str(a): int(n) for a, n in dict(shard_axes).items()}
        if not shard_axes or any(n < 1 for n in shard_axes.values()):
            raise ValueError(f"invalid shard_axes {shard_axes!r}: need at "
                             "least one axis, every size >= 1")

    if data_state is not None:
        try:
            data_state = json.loads(json.dumps(data_state))
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"data_state must be JSON-serializable (it rides the "
                f"manifest): {e}") from e

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    spec_map = _spec_map(shardings, tree) if shardings is not None else {}

    # _path_parts stringifies key components, so exotic pytrees can alias
    # (DictKey('0') vs SequenceKey(0), int key 0 vs str '0').  An aliased
    # path tuple would silently bind the wrong sharding spec or restore
    # leaf — refuse at save time instead (ADVICE r3).
    seen_paths = {}
    for path, _ in leaves:
        pt = tuple(_path_parts(path))
        if pt in seen_paths:
            raise ValueError(
                f"checkpoint path collision: {_keystr(path)} and "
                f"{seen_paths[pt]} both map to path tuple {pt} — rename "
                "the colliding keys (e.g. avoid int and str keys that "
                "stringify identically)")
        seen_paths[pt] = _keystr(path)

    manifest = {"step": int(step), "format": 1, "leaves": {}}
    arrays = {}
    n_shards: Optional[int] = None
    mesh_shape: Optional[dict] = None
    shard_arrays: list = []
    # format 4: mesh-coordinate tuple (over ALL shard_axes, in order) ->
    # {leaf key: partition}; populated only for multi-axis saves
    shard_maps: dict = {}
    any_multi = False
    for path, leaf in leaves:
        # (None leaves never appear here: tree_flatten treats None as an
        # empty subtree, so None-valued fields are simply absent and
        # reappear from the target's structure on restore)
        key = _keystr(path)
        # keystr can collide for keys containing quotes/brackets; the
        # structured "path" is the identity — disambiguate the flat key
        # (it is only a storage label once "path" exists)
        if key in manifest["leaves"]:
            i = 2
            while f"{key}#{i}" in manifest["leaves"]:
                i += 1
            key = f"{key}#{i}"
        if mesh_shape is None:
            try:  # best-effort topology evidence for the manifest
                mesh_shape = dict(leaf.sharding.mesh.shape)
            except (AttributeError, TypeError):
                # numpy leaves have no .sharding and single-device
                # shardings no .mesh — those are the documented
                # "no topology" cases.  Anything else must surface
                # (EX001: the broad except here would also have
                # swallowed a genuinely broken mesh mid-save)
                pass
        val = np.asarray(jax.device_get(leaf))
        entry = {"kind": "array", "dtype": str(val.dtype),
                 "shape": list(val.shape), "path": _path_parts(path)}
        if str(val.dtype) in _HALF_DTYPES:
            if fp32_portable:
                val = val.astype(np.float32)
                entry["stored_dtype"] = "float32"
            else:
                # npz can't round-trip ml_dtypes natively: store the raw bits
                val = val.view(np.uint16)
                entry["stored_dtype"] = "uint16_bits"
        ptuple = tuple(entry["path"])
        spec = spec_map.get(ptuple)
        if spec is not None:
            entry["spec"] = _spec_to_json(spec)
        if shard_axes is not None:
            lead = _flat.spec_lead_axes(spec, shard_axes)
            if lead:
                if val.ndim < len(lead):
                    raise ValueError(
                        f"leaf {key} has spec leading with {len(lead)} "
                        f"mesh axes {lead} but only {val.ndim} dims to "
                        "partition")
                for i, ax in enumerate(lead):
                    if val.shape[i] != shard_axes[ax]:
                        raise ValueError(
                            f"leaf {key} dim {i} has size {val.shape[i]} "
                            f"but its spec shards it over {ax!r} "
                            f"(size {shard_axes[ax]})")
                entry["shard_axes"] = lead
                entry["replicated_shards"] = _flat.is_replicated_stack(
                    val, len(lead))
                any_multi = True
                for c in itertools.product(
                        *(range(shard_axes[a]) for a in lead)):
                    fullc = _leaf_full_coord(entry, c, shard_axes)
                    shard_maps.setdefault(fullc, {})[key] = val[c]
                manifest["leaves"][key] = entry
                continue
            manifest["leaves"][key] = entry
            arrays[key] = val
            continue
        if shard_axis is not None and _spec_leads_with(spec, shard_axis):
            if val.ndim == 0:
                raise ValueError(
                    f"leaf {key} has spec leading with {shard_axis!r} but "
                    "no leading axis to partition")
            if n_shards is None:
                n_shards = int(val.shape[0])
                shard_arrays = [dict() for _ in range(n_shards)]
            elif val.shape[0] != n_shards:
                raise ValueError(
                    f"inconsistent shard counts in one save: leaf {key} "
                    f"has leading axis {val.shape[0]}, earlier sharded "
                    f"leaves have {n_shards}")
            entry["shard_axis"] = shard_axis
            # a per-rank REPLICATED stack must re-broadcast on reshard,
            # not concat.  Only 1-D [n_shards] stacks (per-rank scalars
            # like the broadcast opt step counter) qualify: a >=2-D
            # stack is by contract a flat-buffer partition, even when
            # its content happens to be rank-identical (a fresh ZeRO
            # init's all-zero moments must reshard by concat, and the
            # cheap per-scalar compare keeps the foreground snapshot
            # phase free of O(bytes) work)
            entry["replicated_shards"] = bool(
                val.ndim == 1
                and all(np.array_equal(val[r], val[0])
                        for r in range(1, n_shards)))
            for r in range(n_shards):
                shard_arrays[r][key] = val[r]
        else:
            manifest["leaves"][key] = entry
            arrays[key] = val
            continue
        manifest["leaves"][key] = entry
    if n_shards is not None:
        manifest["format"] = 3
        manifest["topology"] = {"shard_axis": shard_axis,
                                "n_shards": n_shards}
        if mesh_shape is not None:
            manifest["topology"]["mesh_shape"] = mesh_shape
    elif any_multi:
        manifest["format"] = 4
        manifest["topology"] = {"mesh_axes": dict(shard_axes)}
        if mesh_shape is not None:
            manifest["topology"]["mesh_shape"] = mesh_shape
    if data_state is not None:
        manifest["data_state"] = data_state

    # everything below is pure host/disk work on the snapshot — safe to run
    # on the background writer thread
    if blocking:
        _write_checkpoint_files(ckpt_dir, step, manifest, arrays,
                                packed=packed, keep=keep, retry=retry,
                                shard_arrays=shard_arrays,
                                shard_maps=shard_maps,
                                shard_axes=shard_axes)
    else:
        _async.submit_save(
            lambda: _write_checkpoint_files(ckpt_dir, step, manifest, arrays,
                                            packed=packed, keep=keep,
                                            retry=retry,
                                            shard_arrays=shard_arrays,
                                            shard_maps=shard_maps,
                                            shard_axes=shard_axes),
            label=f"{ckpt_dir}:step_{int(step)}")
    return step_dir(ckpt_dir, step)


def _spec_leads_with(spec, axis: str) -> bool:
    """True when PartitionSpec ``spec``'s FIRST dimension entry names
    ``axis`` (directly or inside a tuple) — the test for "this leaf is a
    stack of per-rank partitions along axis 0"."""
    if spec is None or len(spec) == 0:
        return False
    head = spec[0]
    if isinstance(head, (tuple, list)):
        return axis in head
    return head == axis


def _leaf_full_coord(entry: dict, coords, shard_axes: dict) -> tuple:
    """Full mesh coordinate of one leaf shard: the leaf's own lead-axis
    ``coords`` placed at their axes' positions in ``shard_axes`` order,
    zeros elsewhere (the format-4 file-location rule)."""
    lead = entry["shard_axes"]
    return tuple(coords[lead.index(a)] if a in lead else 0
                 for a in shard_axes)


def _write_checkpoint_files(ckpt_dir: str, step: int, manifest: dict,
                            arrays: dict, *, packed: bool,
                            keep: Optional[int],
                            retry: Optional[RetryPolicy],
                            shard_arrays: Optional[list] = None,
                            shard_maps: Optional[dict] = None,
                            shard_axes: Optional[dict] = None) -> str:
    """Disk phase of a save: tmp dir -> arrays + manifest -> atomic rename ->
    latest marker -> keep-GC.  Retries the whole tmp-dir write on transient
    storage errors (each attempt starts from a fresh tmp dir)."""
    # CRC32 digests of the bytes as STORED (what restore-side verification
    # re-hashes off disk).  Hashed here — on the writer thread for async
    # saves — so ``blocking=False`` returns after the device snapshot alone,
    # without a per-leaf hash + tobytes copy stalling the train loop.
    for k, entry in manifest["leaves"].items():
        if k in arrays:
            entry["crc32"] = zlib.crc32(arrays[k].tobytes()) & 0xFFFFFFFF
        elif "shard_axes" in entry:  # format 4: digest per mesh coordinate
            entry["crc32_shards"] = {
                _coord_key(c): zlib.crc32(
                    shard_maps[_leaf_full_coord(entry, c, shard_axes)][k]
                    .tobytes()) & 0xFFFFFFFF
                for c in itertools.product(
                    *(range(shard_axes[a]) for a in entry["shard_axes"]))}
        else:  # format 3: one digest per rank's partition
            entry["crc32_shards"] = [
                zlib.crc32(sh[k].tobytes()) & 0xFFFFFFFF
                for sh in shard_arrays]
    retry = retry or RetryPolicy(max_attempts=1)
    final = step_dir(ckpt_dir, step)
    last_err = None
    for attempt in range(retry.max_attempts):
        try:
            _write_step_dir_once(ckpt_dir, step, manifest, arrays,
                                 packed=packed, shard_arrays=shard_arrays,
                                 shard_maps=shard_maps)
            break
        except retry.retryable as e:
            last_err = e
            shutil.rmtree(final + ".tmp", ignore_errors=True)
            if attempt + 1 >= retry.max_attempts:
                raise
            time.sleep(retry.delay(attempt))
    else:  # pragma: no cover — loop always breaks or raises
        raise last_err

    with open(os.path.join(ckpt_dir, _LATEST), "w") as f:
        f.write(str(int(step)))

    if keep is not None:
        # prune by write recency, never the checkpoint just written — a
        # rollback-resume that saves a *lower* step than what's on disk must
        # not delete its own output
        others = [
            s for s in _complete_steps(ckpt_dir) if s != int(step)
        ]
        others.sort(key=lambda s: os.path.getmtime(step_dir(ckpt_dir, s)))
        for s in others[: max(0, len(others) - (keep - 1))]:
            shutil.rmtree(step_dir(ckpt_dir, s), ignore_errors=True)
    return final


def _write_step_dir_once(ckpt_dir: str, step: int, manifest: dict,
                         arrays: dict, *, packed: bool,
                         shard_arrays: Optional[list] = None,
                         shard_maps: Optional[dict] = None) -> None:
    """One attempt at writing + committing ``step_<N>/``."""
    os.makedirs(ckpt_dir, exist_ok=True)
    final = step_dir(ckpt_dir, step)
    tmp = final + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    if shard_arrays:
        # per-rank partition files; each gets its own fault event so the
        # chaos tier can kill a save mid-shard-set (the commit is still
        # atomic: nothing is visible until the rename below)
        for r, sh in enumerate(shard_arrays):
            p = os.path.join(tmp, shard_file(r))
            _fault("write_shard", p)
            np.savez(p, **sh)
    if shard_maps:
        # format 4: per-mesh-coordinate partition files, same fault
        # event and same atomic-commit guarantee
        for coords in sorted(shard_maps):
            p = os.path.join(tmp, shard_file_coords(coords))
            _fault("write_shard", p)
            np.savez(p, **shard_maps[coords])
    if packed:
        from apex_tpu import _native

        manifest["format"] = 2
        names = list(arrays)
        offsets, off = [], 0
        contig = []
        for k in names:
            a = np.ascontiguousarray(arrays[k])
            contig.append(a)
            manifest["leaves"][k]["offset"] = off
            offsets.append(off)
            off += -(-a.nbytes // _PACK_ALIGN) * _PACK_ALIGN
        buf = _native.pack_host(contig, offsets, off)
        _fault("write_arrays", os.path.join(tmp, _PACK))
        buf.tofile(os.path.join(tmp, _PACK))
    else:
        _fault("write_arrays", os.path.join(tmp, _ARRAYS))
        np.savez(os.path.join(tmp, _ARRAYS), **arrays)
    _fault("write_manifest", os.path.join(tmp, _MANIFEST))
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)
    _fault("commit", final)
    if os.path.exists(final):
        shutil.rmtree(final)
    os.rename(tmp, final)


def _stored_dtype(entry: dict):
    """On-disk dtype of a manifest leaf (the single owner of the
    stored_dtype decode — the chaos harness reuses it to locate leaf
    bytes)."""
    sd = entry.get("stored_dtype")
    return jnp.dtype(sd if sd == "float32"
                     else "uint16" if sd == "uint16_bits"
                     else entry["dtype"])


def _load_manifest_and_data(d: str, *, verify: bool):
    """Read manifest + raw stored arrays from checkpoint dir ``d``.

    ``verify=True`` treats every read/parse failure as corruption (raising
    :class:`CheckpointCorruptionError`) and checks each array's stored
    bytes against the manifest's CRC32 digest.  ``verify=False`` preserves
    the historical raw exceptions."""
    try:
        _fault("read_arrays", d)
        with open(os.path.join(d, _MANIFEST)) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        if verify:
            raise CheckpointCorruptionError(
                f"unreadable manifest in {d}: {e}") from e
        raise
    pack_path = os.path.join(d, _PACK)
    shard_data: list = []
    coord_maps: dict = {}
    mesh_axes = dict(manifest.get("topology", {}).get("mesh_axes") or {})
    try:
        if os.path.exists(pack_path):  # format 2: flat superblock
            buf = np.fromfile(pack_path, np.uint8)
            data = {}
            for k, e in manifest["leaves"].items():
                cnt = int(np.prod(e["shape"])) if e["shape"] else 1
                data[k] = np.frombuffer(buf, _stored_dtype(e), cnt,
                                        e["offset"]).reshape(e["shape"])
        else:
            data = {}
            if os.path.exists(os.path.join(d, _ARRAYS)):
                with np.load(os.path.join(d, _ARRAYS)) as npz:
                    data = {k: npz[k] for k in npz.files}
            for r in range(manifest.get("topology", {}).get("n_shards", 0)):
                with np.load(os.path.join(d, shard_file(r))) as npz:
                    shard_data.append({k: npz[k] for k in npz.files})
            if mesh_axes:  # format 4: per-mesh-coordinate files
                needed = set()
                for e in manifest["leaves"].values():
                    if "shard_axes" not in e:
                        continue
                    for c in itertools.product(
                            *(range(mesh_axes[a]) for a in e["shard_axes"])):
                        needed.add(_leaf_full_coord(e, c, mesh_axes))
                for fullc in sorted(needed):
                    with np.load(os.path.join(
                            d, shard_file_coords(fullc))) as npz:
                        coord_maps[fullc] = {k: npz[k] for k in npz.files}
    except Exception as e:
        # truncated pack (frombuffer ValueError), truncated/garbled npz
        # (zipfile.BadZipFile, EOFError, OSError, KeyError), missing
        # shard file — with verify, all of these are one condition: a
        # corrupt checkpoint
        if verify:
            raise CheckpointCorruptionError(
                f"unreadable arrays in {d}: {type(e).__name__}: {e}") from e
        raise
    problems = []
    for k, e in manifest["leaves"].items():
        if "shard_axis" not in e:
            continue
        # reassemble the logical [n_shards, ...] stack; per-shard CRC
        # runs while each partition's bytes are in hand
        parts = []
        for r, sh in enumerate(shard_data):
            if k not in sh:
                problems.append(f"missing {k!r} in shard {r}")
                continue
            if verify and "crc32_shards" in e:
                got = zlib.crc32(np.asarray(sh[k]).tobytes()) & 0xFFFFFFFF
                want = e["crc32_shards"][r]
                if got != want:
                    problems.append(
                        f"CRC32 mismatch for {k!r} shard {r}: stored "
                        f"digest {want}, bytes on disk hash to {got}")
            parts.append(sh[k])
        if len(parts) == len(shard_data):
            data[k] = np.stack(parts)
    for k, e in manifest["leaves"].items():
        if "shard_axes" not in e:
            continue
        # format 4: reassemble [n_a, n_b, ..., *content] from the
        # per-coordinate files (coordinates iterate in C-order over the
        # leaf's lead axes, so stack+reshape inverts the save split)
        try:
            lead_shape = tuple(mesh_axes[a] for a in e["shard_axes"])
        except KeyError as exc:
            # valid-JSON but damaged manifest: a leaf names a shard axis
            # absent from topology.mesh_axes — under verify this is a
            # corrupt checkpoint (so restore_resilient's fallback walk
            # can move on to an older intact step), not a raw KeyError
            if verify:
                raise CheckpointCorruptionError(
                    f"checkpoint at {d}: leaf {k!r} is sharded over axis "
                    f"{exc} missing from topology mesh_axes "
                    f"{sorted(mesh_axes)}") from exc
            raise
        parts = []
        for c in itertools.product(*(range(n) for n in lead_shape)):
            sh = coord_maps.get(_leaf_full_coord(e, c, mesh_axes), {})
            if k not in sh:
                problems.append(f"missing {k!r} at mesh coordinate {c}")
                continue
            if verify and "crc32_shards" in e:
                got = zlib.crc32(np.asarray(sh[k]).tobytes()) & 0xFFFFFFFF
                want = e["crc32_shards"].get(_coord_key(c))
                if got != want:
                    problems.append(
                        f"CRC32 mismatch for {k!r} at mesh coordinate "
                        f"{c}: stored digest {want}, bytes on disk hash "
                        f"to {got}")
            parts.append(sh[k])
        if len(parts) == int(np.prod(lead_shape)):
            data[k] = np.stack(parts).reshape(
                lead_shape + tuple(parts[0].shape))
    if verify:
        for k, e in manifest["leaves"].items():
            if k not in data:
                if "shard_axis" not in e and "shard_axes" not in e:
                    problems.append(f"missing stored array {k!r}")
                continue
            want = e.get("crc32")
            if want is None:
                continue  # pre-digest/sharded manifest: checked above
            got = zlib.crc32(np.asarray(data[k]).tobytes()) & 0xFFFFFFFF
            if got != want:
                problems.append(
                    f"CRC32 mismatch for {k!r}: stored digest {want}, "
                    f"bytes on disk hash to {got}")
        if problems:
            raise CheckpointCorruptionError(
                f"checkpoint at {d} failed integrity verification: "
                + "; ".join(problems))
    elif problems:
        raise KeyError(
            f"sharded checkpoint at {d} is incomplete: " + "; ".join(problems))
    return manifest, data


def verify_checkpoint(ckpt_dir: str, step: Optional[int] = None) -> int:
    """Check integrity of checkpoint ``step`` (default: latest) under
    ``ckpt_dir``: files readable, every manifest leaf present, CRC32
    digests match the bytes on disk.  Returns the verified step, or raises
    :class:`CheckpointCorruptionError` / :class:`FileNotFoundError`."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {ckpt_dir}")
    _load_manifest_and_data(step_dir(ckpt_dir, step), verify=True)
    return step


def load_data_state(ckpt_dir: str,
                    step: Optional[int] = None) -> Optional[dict]:
    """The ``data_state`` record saved with checkpoint ``step``
    (default: latest), or None when that checkpoint was saved without
    one.  The restore-side half of exactly-once resume: restore the
    model tree with :func:`restore_checkpoint` / ``restore_resilient``
    at step N, then feed this record to the iterator's
    ``load_state_dict`` — both came from ONE atomic manifest, so they
    cannot disagree about the position."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {ckpt_dir}")
    with open(os.path.join(step_dir(ckpt_dir, step), _MANIFEST)) as f:
        return json.load(f).get("data_state")


def restore_checkpoint(
    ckpt_dir: str,
    target: Any = None,
    *,
    step: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    shardings: Any = None,
    verify: bool = False,
):
    """Restore a checkpoint into (optionally) ``target``'s structure.

    - ``target`` given: every leaf path of ``target`` must exist in the
      checkpoint; restored leaves are cast back to the target leaf's dtype
      (precision portability) and the result has ``target``'s exact treedef
      (NamedTuples, dataclasses, optimizer states all round-trip).
    - ``target=None``: rebuilds a nested dict keyed by path components
      (dict keys / attribute names / sequence indices as strings).
    - ``mesh`` given: each leaf is ``device_put`` with
      ``NamedSharding(mesh, spec)`` where ``spec`` comes from ``shardings``
      (a pytree of PartitionSpec) or, failing that, from the manifest. The
      mesh may differ in size/shape from the one that saved — this is how
      restore-on-a-different-dp-size works.
    - ``verify=True``: re-hash every stored array against the manifest's
      CRC32 digests before materializing, and surface any read failure as
      :class:`CheckpointCorruptionError` (see
      :func:`apex_tpu.resilience.restore_resilient` for automatic fallback
      to the newest intact older checkpoint).

    **Cross-topology reshard**: leaves saved with ``shard_axis`` (see
    :func:`save_checkpoint`) are stacks of per-rank flat-buffer
    partitions.  When the target leaf's leading axis differs from the
    saved shard count (an N-device save restoring onto an M-device mesh,
    including the M=1 debug restore), the stack is re-partitioned by
    flat-buffer semantics: concatenate the N saved partitions, re-split
    into M.  Size differences can come only from the flat schema's
    topology-dependent tail padding (``total_multiple_of = 128·N``), so
    growth zero-fills and shrinkage requires the dropped tail to be all
    zeros (anything else raises — that would silently lose optimizer
    state).  1-D stacks of per-rank scalars recorded as
    ``replicated_shards`` (the broadcast step counter) re-broadcast
    rank 0 instead of concatenating.

    Returns ``(tree, step)``.
    """
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {ckpt_dir}")
    d = step_dir(ckpt_dir, step)
    manifest, data = _load_manifest_and_data(d, verify=verify)

    if shardings is not None and target is not None:
        spec_map = _spec_map(shardings, target)
    elif shardings is not None:
        # no target to broadcast a prefix against: shardings must be
        # leaf-exact here
        spec_map = {
            tuple(_path_parts(path)): (s.spec if isinstance(s, NamedSharding)
                                       else s)
            for path, s in jax.tree_util.tree_flatten_with_path(
                shardings, is_leaf=_is_spec_leaf
            )[0]
            if s is not None
        }
    else:
        spec_map = {}

    def _materialize(key: str, entry: dict, want_dtype=None,
                     want_shape=None):
        val = data[key]
        if (want_shape is not None
                and ("shard_axis" in entry or "shard_axes" in entry)
                and tuple(val.shape) != tuple(want_shape)):
            val = _reshard_stack(val, entry, tuple(want_shape), key)
        if entry.get("stored_dtype") == "uint16_bits":
            val = val.view(jnp.dtype(entry["dtype"]))
        dtype = want_dtype if want_dtype is not None else jnp.dtype(entry["dtype"])
        arr = jnp.asarray(val).astype(dtype)
        if mesh is not None:
            ptuple = (tuple(entry["path"]) if "path" in entry
                      else tuple(_parse_keystr(key)))
            spec = spec_map.get(ptuple)
            if spec is None and entry.get("spec") is not None:
                spec = _spec_from_json(entry["spec"])
            if spec is None:
                spec = PartitionSpec()
            # drop axis names the new mesh doesn't have (e.g. restoring a
            # dp-sharded save onto a single-axis mesh); tuple entries keep
            # whichever of their axes still exist
            spec = PartitionSpec(*[_filter_spec_entry(p, mesh) for p in spec])
            arr = jax.device_put(arr, NamedSharding(mesh, spec))
        return arr

    if target is None:
        nested: dict = {}
        for key, entry in manifest["leaves"].items():
            # manifests carry structured path components (format >= 1 with
            # "path"); older ones fall back to parsing the keystr
            parts = entry.get("path") or _parse_keystr(key)
            node = nested
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = _materialize(key, entry)
        return nested, step

    paths, treedef = jax.tree_util.tree_flatten_with_path(target)
    # primary lookup by structured path (collision-free); keystr is the
    # fallback for manifests written before the "path" field existed
    by_path = {tuple(e["path"]): k for k, e in manifest["leaves"].items()
               if "path" in e}
    # collect ALL missing leaves up front: a target/checkpoint structure
    # mismatch should name everything wrong with it, not die on the first key
    missing = []
    for path, _ in paths:
        key = by_path.get(tuple(_path_parts(path)), _keystr(path))
        if key not in manifest["leaves"]:
            missing.append(key)
    if missing:
        present = sorted(manifest["leaves"])
        shown = ", ".join(repr(k) for k in present[:8])
        if len(present) > 8:
            shown += f", ... ({len(present)} total)"
        raise KeyError(
            f"checkpoint at {d} is missing {len(missing)} leaves required "
            f"by the restore target: {missing} — the checkpoint holds "
            f"[{shown}]. The target's structure does not match what was "
            "saved (wrong checkpoint dir, or the model/optimizer definition "
            "changed since the save).")
    leaves = []
    for path, tleaf in paths:
        key = by_path.get(tuple(_path_parts(path)), _keystr(path))
        want = shape = None
        if tleaf is not None and hasattr(tleaf, "dtype"):
            want = tleaf.dtype
        if tleaf is not None and hasattr(tleaf, "shape"):
            shape = tleaf.shape
        leaves.append(_materialize(key, manifest["leaves"][key],
                                   want_dtype=want, want_shape=shape))
    return jax.tree_util.tree_unflatten(treedef, leaves), step


def _reshard_stack(val: np.ndarray, entry: dict, want_shape: tuple,
                   key: str) -> np.ndarray:
    """Re-partition a sharded leaf's stored stack to the target's layout
    (restore_checkpoint's "cross-topology reshard" contract; operates on
    the STORED dtype, before any precision-portability cast).  Format-3
    leaves carry one lead axis, format-4 leaves one per mesh axis named
    in ``shard_axes``; both route through ONE implementation
    (:func:`apex_tpu.multi_tensor.flat.reshard_stack` — C-order flatten
    + the repartition_flat pad/trim contract, replicated stacks
    re-broadcast coordinate 0), shared with the in-memory
    reshard_zero_state/reshard_tree so on-disk and live semantics
    cannot diverge."""
    n_lead = len(entry["shard_axes"]) if "shard_axes" in entry else 1
    return _flat.reshard_stack(val, n_lead, want_shape,
                               replicated=bool(entry.get("replicated_shards")),
                               label=f"sharded leaf {key!r}")


def _filter_spec_entry(part, mesh: Mesh):
    """Keep only the axis names present in ``mesh`` for one PartitionSpec
    dimension entry (None / name / tuple-of-names)."""
    if part is None:
        return None
    if isinstance(part, (tuple, list)):
        kept = tuple(n for n in part if n in mesh.axis_names)
        return kept if kept else None
    return part if part in mesh.axis_names else None


def _parse_keystr(key: str) -> list:
    """Back-compat path recovery for manifests without structured "path"
    entries: parse ``['a'][0].b`` keystrs.  Best-effort — keys containing
    quotes/brackets need the structured form."""
    import re

    token = re.compile(r"\[\'([^\']*)\'\]|\[(\d+)\]|\.([A-Za-z_][A-Za-z_0-9]*)")
    parts = [m.group(1) or m.group(2) or m.group(3)
             for m in token.finditer(key)]
    return parts or [key]
