"""apex_tpu.models — reference workloads (ResNet for the imagenet/amp path,
Megatron GPT/BERT re-exported from transformer.testing)."""

from apex_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNetConfig,
    resnet18_config,
    resnet50_config,
)
from apex_tpu.transformer.testing import (  # noqa: F401
    BertConfig,
    BertModel,
    GPTConfig,
    GPTModel,
)
