"""ResNet (v1.5) — the reference's canonical amp+DDP workload.

TPU-native implementation of the model behind
``examples/imagenet/main_amp.py`` (the reference trains torchvision
ResNet-50; its L1 tier cross-products opt-levels over it, SURVEY.md §4).

TPU-first choices: NHWC layout (channels-last is the native TPU conv
layout — the reference gains the same from ``--channels-last``),
``lax.conv_general_dilated`` onto the MXU with fp32 accumulation, BN as
:func:`apex_tpu.parallel.sync_batch_norm` so the same model runs
single-chip or data-parallel (SyncBN over the mesh "data" axis =
``--sync_bn``).  Functional init/apply with explicit BN state.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import sync_batch_norm


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    block_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    num_classes: int = 1000
    bn_axis_name: Optional[str] = None  # "data" => SyncBN over the DP axis
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5


def resnet50_config(**kw) -> ResNetConfig:
    return ResNetConfig(block_sizes=(3, 4, 6, 3), **kw)


def resnet18_config(**kw) -> ResNetConfig:
    # basic-block resnets use the bottleneck path with expansion 1
    return ResNetConfig(block_sizes=(2, 2, 2, 2), **kw)


def _conv_init(key, shape):
    # he-normal fan_out (torchvision default for resnets)
    fan_out = shape[0] * shape[1] * shape[3]
    std = jnp.sqrt(2.0 / fan_out)
    return jax.random.normal(key, shape) * std


def _conv(x, w, stride=1, padding="SAME"):
    # no preferred_element_type: the MXU accumulates bf16 convs in fp32
    # anyway, and a widened output dtype breaks the conv transpose rule
    # (fp32 cotangent vs bf16 weights) under jax.grad
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ResNet:
    """Functional ResNet with bottleneck blocks (v1.5: stride on the 3x3)."""

    expansion = 4

    def __init__(self, cfg: ResNetConfig):
        self.cfg = cfg

    # -- init ----------------------------------------------------------------

    def _bn_init(self, c):
        return ({"weight": jnp.ones((c,)), "bias": jnp.zeros((c,))},
                {"mean": jnp.zeros((c,), jnp.float32),
                 "var": jnp.ones((c,), jnp.float32)})

    def init(self, key, dtype=jnp.float32) -> Tuple[Dict, Dict]:
        """Returns (params, bn_state)."""
        cfg = self.cfg
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        key, k = jax.random.split(key)
        params["conv1"] = {"w": _conv_init(k, (7, 7, 3, cfg.width)).astype(dtype)}
        params["bn1"], state["bn1"] = self._bn_init(cfg.width)

        in_c = cfg.width
        for stage, n_blocks in enumerate(cfg.block_sizes):
            mid = cfg.width * (2 ** stage)
            out_c = mid * self.expansion
            stride = 1 if stage == 0 else 2
            blocks = []
            bstates = []
            for b in range(n_blocks):
                key, k1, k2, k3, k4 = jax.random.split(key, 5)
                blk: Dict[str, Any] = {
                    "conv1": {"w": _conv_init(k1, (1, 1, in_c, mid)).astype(dtype)},
                    "conv2": {"w": _conv_init(k2, (3, 3, mid, mid)).astype(dtype)},
                    "conv3": {"w": _conv_init(k3, (1, 1, mid, out_c)).astype(dtype)},
                }
                bst: Dict[str, Any] = {}
                blk["bn1"], bst["bn1"] = self._bn_init(mid)
                blk["bn2"], bst["bn2"] = self._bn_init(mid)
                blk["bn3"], bst["bn3"] = self._bn_init(out_c)
                # zero-init the last BN gamma (torchvision zero_init_residual
                # improves early training; harmless otherwise)
                blk["bn3"]["weight"] = jnp.zeros_like(blk["bn3"]["weight"])
                if b == 0 and (stride != 1 or in_c != out_c):
                    blk["downsample"] = {
                        "w": _conv_init(k4, (1, 1, in_c, out_c)).astype(dtype)}
                    blk["bn_ds"], bst["bn_ds"] = self._bn_init(out_c)
                blocks.append(blk)
                bstates.append(bst)
                in_c = out_c
            params[f"layer{stage + 1}"] = blocks
            state[f"layer{stage + 1}"] = bstates

        key, k = jax.random.split(key)
        params["fc"] = {
            "w": (jax.random.normal(k, (in_c, cfg.num_classes)) / jnp.sqrt(in_c)
                  ).astype(dtype),
            "b": jnp.zeros((cfg.num_classes,), dtype),
        }
        return params, state

    # -- apply ---------------------------------------------------------------

    def _bn(self, p, s, x, training):
        cfg = self.cfg
        y, rm, rv = sync_batch_norm(
            x, p["weight"], p["bias"], s["mean"], s["var"],
            axis_name=cfg.bn_axis_name if training else None,
            training=training, momentum=cfg.bn_momentum, eps=cfg.bn_eps,
            channel_axis=-1)
        new_s = {"mean": rm, "var": rv} if rm is not None else s
        return y, new_s

    def _block(self, p, s, x, stride, training):
        new_s = {}
        h, new_s["bn1"] = self._bn(p["bn1"], s["bn1"],
                                   _conv(x, p["conv1"]["w"]), training)
        h = jax.nn.relu(h)
        h, new_s["bn2"] = self._bn(p["bn2"], s["bn2"],
                                   _conv(h, p["conv2"]["w"], stride), training)
        h = jax.nn.relu(h)
        h, new_s["bn3"] = self._bn(p["bn3"], s["bn3"],
                                   _conv(h, p["conv3"]["w"]), training)
        if "downsample" in p:
            sc, new_s["bn_ds"] = self._bn(
                p["bn_ds"], s["bn_ds"],
                _conv(x, p["downsample"]["w"], stride), training)
        else:
            sc = x
        return jax.nn.relu(h + sc), new_s

    def apply(self, params, state, x, *, training: bool = True):
        """x: [N, H, W, 3] NHWC.  Returns (logits, new_bn_state)."""
        new_state: Dict[str, Any] = {}
        h = _conv(x, params["conv1"]["w"], stride=2)
        h, new_state["bn1"] = self._bn(params["bn1"], state["bn1"], h, training)
        h = jax.nn.relu(h)
        h = jax.lax.reduce_window(
            h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            [(0, 0), (1, 1), (1, 1), (0, 0)])

        for stage in range(len(self.cfg.block_sizes)):
            blocks = params[f"layer{stage + 1}"]
            bstates = state[f"layer{stage + 1}"]
            new_bstates = []
            for b, (bp, bs) in enumerate(zip(blocks, bstates)):
                stride = (1 if stage == 0 else 2) if b == 0 else 1
                h, ns = self._block(bp, bs, h, stride, training)
                new_bstates.append(ns)
            new_state[f"layer{stage + 1}"] = new_bstates

        h = jnp.mean(h, axis=(1, 2))  # global average pool
        logits = (h.astype(jnp.float32) @ params["fc"]["w"].astype(jnp.float32)
                  + params["fc"]["b"].astype(jnp.float32))
        return logits, new_state

    __call__ = apply
