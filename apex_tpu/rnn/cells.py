"""RNN cells as pure functions (reference apex/RNN/cells.py + the torch
cell functions apex/RNN/models.py imports).

Each cell is ``cell(params, x, hidden) -> new_hidden`` with ``hidden`` a
tuple of ``n_hidden_states`` arrays and ``new_hidden[0]`` the output — the
contract the reference backend assumes (RNNBackend.py:87 "assumes
hidden_state[0] ... is output hidden state").

The reference fuses the gate pointwise math via ``rnnFusedPointwise``
(cells.py:64-66); XLA fuses the same expressions automatically, and the two
gate GEMMs per step stay on the MXU. Gate parameter layout matches torch:
``w_ih (gate_multiplier*hidden, input)``, gates ordered i, f, g, o for LSTM
and r, z, n for GRU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _linear(x, w, b=None):
    y = x @ w.T
    return y + b if b is not None else y


def lstm_cell(params, x, hidden):
    """torch ``LSTMCell`` parity; hidden = (h, c)."""
    hx, cx = hidden
    gates = _linear(x, params["w_ih"], params.get("b_ih")) + _linear(
        hx, params["w_hh"], params.get("b_hh"))
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    cy = f * cx + i * g
    hy = o * jnp.tanh(cy)
    return (hy, cy)


def mlstm_cell(params, x, hidden):
    """Multiplicative LSTM (reference cells.py:56-84): an elementwise
    product of input/hidden projections modulates the hidden gates."""
    hx, cx = hidden
    m = _linear(x, params["w_mih"]) * _linear(hx, params["w_mhh"])
    gates = _linear(x, params["w_ih"], params.get("b_ih")) + _linear(
        m, params["w_hh"], params.get("b_hh"))
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    cy = f * cx + i * g
    hy = o * jnp.tanh(cy)
    return (hy, cy)


def gru_cell(params, x, hidden):
    """torch ``GRUCell`` parity; hidden = (h,)."""
    (hx,) = hidden
    gi = _linear(x, params["w_ih"], params.get("b_ih"))
    gh = _linear(hx, params["w_hh"], params.get("b_hh"))
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return ((1.0 - z) * n + z * hx,)


def rnn_relu_cell(params, x, hidden):
    (hx,) = hidden
    return (jax.nn.relu(
        _linear(x, params["w_ih"], params.get("b_ih"))
        + _linear(hx, params["w_hh"], params.get("b_hh"))),)


def rnn_tanh_cell(params, x, hidden):
    (hx,) = hidden
    return (jnp.tanh(
        _linear(x, params["w_ih"], params.get("b_ih"))
        + _linear(hx, params["w_hh"], params.get("b_hh"))),)
