"""Stacked / bidirectional RNNs over scan (reference apex/RNN/).

Re-design of ``stackedRNN`` / ``bidirectionalRNN`` / ``RNNCell``
(apex/RNN/RNNBackend.py:25-365) and the model factories
(apex/RNN/models.py:19-54: LSTM, GRU, ReLU, Tanh, mLSTM): the reference
iterates timesteps in Python holding mutable per-module hidden state; here
the time loop is one ``lax.scan`` per layer (static trip count, MXU-friendly
batched GEMMs per step) and hidden state is explicit — passed in, returned
out.

Layout: seq-major ``(T, B, F)`` like the reference backend (it "always
assumes batch_first" is false for input — RNNBackend.py:119 returns
``[sequence steps][batch size][features]``); ``batch_first=True`` transposes
at the boundary. ``output_size`` adds the reference's ``w_ho`` projection
(RNNBackend.py RNNCell). Inter-layer dropout matches torch semantics (not
applied after the last layer).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.rnn import cells as _cells


class RNN:
    """A stack of scanned RNN layers sharing one cell function.

    ``init(key)`` returns the param pytree (list of per-layer dicts);
    ``apply(params, x, hidden=None, key=None)`` returns
    ``(output, last_hidden)`` with ``last_hidden`` a tuple of
    ``n_hidden_states`` arrays shaped (num_layers*num_directions, B, H).

    Layer ordering is **direction-major** — all forward layers, then all
    backward layers — mirroring the reference's two independent stacks
    (bidirectionalRNN, RNNBackend.py:25-50). NOTE this differs from torch's
    layer-major interleave (l0_fwd, l0_bwd, l1_fwd, ...); the two coincide
    only for num_layers == 1.
    """

    def __init__(
        self,
        cell: Callable,
        gate_multiplier: int,
        n_hidden_states: int,
        input_size: int,
        hidden_size: int,
        num_layers: int,
        bias: bool = True,
        batch_first: bool = False,
        dropout: float = 0.0,
        bidirectional: bool = False,
        output_size: Optional[int] = None,
        multiplicative: bool = False,
    ):
        self.cell = cell
        self.gate_multiplier = gate_multiplier
        self.n_hidden_states = n_hidden_states
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bias = bias
        self.batch_first = batch_first
        self.dropout = dropout
        self.bidirectional = bidirectional
        self.output_size = output_size if output_size is not None else hidden_size
        self.multiplicative = multiplicative
        self.num_directions = 2 if bidirectional else 1
        if (self.output_size != self.hidden_size
                and cell is _cells.gru_cell):
            # GRU mixes hx elementwise with hidden_size-wide gates (z*hx),
            # so a projected (output_size-wide) carry cannot feed it; LSTM/
            # mLSTM/vanilla cells touch hx only through w_hh, which is
            # shaped (g*h, output_size)
            raise ValueError("GRU does not support output_size != hidden_size")

    # -- params ----------------------------------------------------------
    def _init_layer(self, key, in_size, dtype):
        h, g, out = self.hidden_size, self.gate_multiplier, self.output_size
        # torch RNN init: U(-1/sqrt(h), 1/sqrt(h)) (reference
        # reset_parameters, RNNBackend.py)
        bound = 1.0 / math.sqrt(h)
        ks = jax.random.split(key, 7)
        uni = lambda k, shape: jax.random.uniform(k, shape, dtype, -bound, bound)
        p = {"w_ih": uni(ks[0], (g * h, in_size)), "w_hh": uni(ks[1], (g * h, out))}
        if self.bias:
            p["b_ih"] = uni(ks[2], (g * h,))
            p["b_hh"] = uni(ks[3], (g * h,))
        if self.multiplicative:
            p["w_mih"] = uni(ks[4], (out, in_size))
            p["w_mhh"] = uni(ks[5], (out, out))
        if self.output_size != self.hidden_size:
            p["w_ho"] = uni(ks[6], (out, h))
        return p

    def init(self, key, dtype=jnp.float32):
        layers = []
        for d in range(self.num_directions):
            in_size = self.input_size
            for i in range(self.num_layers):
                key, sub = jax.random.split(key)
                layers.append(self._init_layer(sub, in_size, dtype))
                in_size = self.output_size
        return layers

    # -- forward ---------------------------------------------------------
    def _zero_hidden(self, bsz, dtype):
        shape = (bsz, self.output_size)
        return tuple(
            jnp.zeros(shape if i == 0 else (bsz, self.hidden_size), dtype)
            for i in range(self.n_hidden_states)
        )

    def _run_layer(self, p, x, h0, reverse):
        def step(h, xt):
            new_h = self.cell(p, xt, h)
            out = new_h[0]
            if "w_ho" in p:
                out = out @ p["w_ho"].T
                new_h = (out,) + new_h[1:]
            return new_h, out

        h_last, out = jax.lax.scan(step, h0, x, reverse=reverse)
        return out, h_last

    def apply(self, params, x, hidden=None, *, key=None, training=True):
        if self.batch_first:
            x = jnp.swapaxes(x, 0, 1)
        T, B = x.shape[0], x.shape[1]
        n_total = self.num_layers * self.num_directions
        if hidden is None:
            per_layer = [self._zero_hidden(B, x.dtype) for _ in range(n_total)]
        else:
            per_layer = [tuple(s[i] for s in hidden) for i in range(n_total)]

        def run_stack(layer_params, hiddens, reverse):
            y = x
            lasts = []
            for li, (p, h0) in enumerate(zip(layer_params, hiddens)):
                y, h_last = self._run_layer(p, y, h0, reverse)
                lasts.append(h_last)
                if self.dropout and training and li < len(layer_params) - 1:
                    if key is None:
                        raise ValueError("dropout requires key")
                    keep = jax.random.bernoulli(
                        jax.random.fold_in(key, li + (1000 if reverse else 0)),
                        1.0 - self.dropout, y.shape)
                    y = jnp.where(keep, y / (1.0 - self.dropout), 0.0)
            return y, lasts

        L = self.num_layers
        fwd_out, fwd_lasts = run_stack(params[:L], per_layer[:L], reverse=False)
        if self.bidirectional:
            bwd_out, bwd_lasts = run_stack(params[L:], per_layer[L:], reverse=True)
            out = jnp.concatenate([fwd_out, bwd_out], axis=-1)
            lasts = fwd_lasts + bwd_lasts
        else:
            out, lasts = fwd_out, fwd_lasts
        # stack per-layer hidden tuples -> tuple of (n_total, B, H)
        hidden_out = tuple(
            jnp.stack([l[i] for l in lasts]) for i in range(self.n_hidden_states)
        )
        if self.batch_first:
            out = jnp.swapaxes(out, 0, 1)
        return out, hidden_out

    __call__ = apply


def LSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None):
    """Reference apex/RNN/models.py:19."""
    return RNN(_cells.lstm_cell, 4, 2, input_size, hidden_size, num_layers,
               bias, batch_first, dropout, bidirectional, output_size)


def GRU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
        dropout=0.0, bidirectional=False, output_size=None):
    """Reference apex/RNN/models.py:26."""
    return RNN(_cells.gru_cell, 3, 1, input_size, hidden_size, num_layers,
               bias, batch_first, dropout, bidirectional, output_size)


def ReLU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None):
    """Reference apex/RNN/models.py:33."""
    return RNN(_cells.rnn_relu_cell, 1, 1, input_size, hidden_size, num_layers,
               bias, batch_first, dropout, bidirectional, output_size)


def Tanh(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None):
    """Reference apex/RNN/models.py:40."""
    return RNN(_cells.rnn_tanh_cell, 1, 1, input_size, hidden_size, num_layers,
               bias, batch_first, dropout, bidirectional, output_size)


def mLSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
          dropout=0.0, bidirectional=False, output_size=None):
    """Reference apex/RNN/models.py:47 (cells.py mLSTMRNNCell)."""
    return RNN(_cells.mlstm_cell, 4, 2, input_size, hidden_size, num_layers,
               bias, batch_first, dropout, bidirectional, output_size,
               multiplicative=True)
