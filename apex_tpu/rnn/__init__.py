"""RNN stack (reference apex/RNN/): LSTM/GRU/ReLU/Tanh/mLSTM over lax.scan."""

from apex_tpu.rnn.models import GRU, LSTM, RNN, ReLU, Tanh, mLSTM
from apex_tpu.rnn import cells

__all__ = ["RNN", "LSTM", "GRU", "ReLU", "Tanh", "mLSTM", "cells"]
