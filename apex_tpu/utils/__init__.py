from apex_tpu.utils.logging import RankInfoFormatter, get_logger  # noqa: F401
from apex_tpu.utils.tree import (  # noqa: F401
    tree_cast,
    tree_global_norm,
    tree_isfinite,
    tree_size,
    tree_zeros_like,
)
