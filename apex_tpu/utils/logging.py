"""Rank-aware logging.

TPU-native equivalent of the reference's per-rank log formatter
(apex/__init__.py:27-39, which injects ``(tp, pp, dp)`` rank info into every
record) and the transformer logger (apex/transformer/log_util.py:1-19).

On TPU there are no torch.distributed process groups; rank info comes from
``jax.process_index()`` and, when a model-parallel mesh has been initialised
via :mod:`apex_tpu.transformer.parallel_state`, the logical mesh coordinates.
"""

from __future__ import annotations

import logging
import os


class RankInfoFormatter(logging.Formatter):
    """Formatter that prefixes records with process/mesh rank info.

    Mirrors ``RankInfoFormatter`` (reference apex/__init__.py:27-39), with
    jax.process_index in place of torch.distributed.get_rank and mesh
    coordinates from parallel_state in place of (tp, pp, dp) group ranks.
    """

    def format(self, record):
        try:
            import jax

            rank = jax.process_index()
            nprocs = jax.process_count()
        except Exception:  # pragma: no cover - jax not initialised yet
            rank, nprocs = 0, 1
        try:
            from apex_tpu.transformer import parallel_state

            if parallel_state.model_parallel_is_initialized():
                info = parallel_state.get_rank_info()
                record.rank_info = f"[{rank}/{nprocs} tp={info[0]} pp={info[1]} dp={info[2]}]"
            else:
                record.rank_info = f"[{rank}/{nprocs}]"
        except Exception:
            record.rank_info = f"[{rank}/{nprocs}]"
        return super().format(record)


_FORMAT = "%(asctime)s %(rank_info)s %(name)s %(levelname)s: %(message)s"


def get_logger(name: str = "apex_tpu") -> logging.Logger:
    """Per-module logger with env-var level (APEX_TPU_LOG_LEVEL).

    Mirrors get_transformer_logger / set_logging_level
    (reference apex/transformer/log_util.py:1-19).
    """
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(RankInfoFormatter(_FORMAT))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("APEX_TPU_LOG_LEVEL", "WARNING").upper())
        logger.propagate = False
    return logger
