"""Pytree utilities shared across the framework."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_cast(tree, dtype, *, predicate=None):
    """Cast every floating-point leaf to ``dtype``.

    ``predicate(path, leaf) -> bool`` (path = jax key path tuple) may veto
    individual leaves (used by keep_batchnorm_fp32-style policies).
    """

    def _cast(path, x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            if predicate is None or predicate(path, x):
                return x.astype(dtype)
        return x

    return jax.tree_util.tree_map_with_path(_cast, tree)


def tree_select(pred, on_true, on_false):
    """Branchless whole-tree select: ``where(pred, a, b)`` per leaf. The
    skip-step primitive shared by amp and the optimizers."""
    return jax.tree_util.tree_map(lambda a, b: jnp.where(pred, a, b), on_true, on_false)


def tree_size(tree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_isfinite(tree):
    """Single fused all-finite check over a whole pytree.

    TPU-native replacement for the inf/nan poll that every reference
    multi-tensor kernel carries (csrc/multi_tensor_apply.cuh:32 noop_flag):
    one ``jnp.isfinite(...).all()`` per leaf, AND-reduced to a scalar.
    """
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.isfinite(x).all() for x in leaves]
    out = finite[0]
    for f in finite[1:]:
        out = jnp.logical_and(out, f)
    return out


def tree_global_norm(tree, *, ord=2):
    """Global l2 norm over all leaves (reference multi_tensor_l2norm semantics)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    if ord != 2:
        raise NotImplementedError("only l2 supported")
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)
