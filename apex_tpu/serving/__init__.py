"""Inference serving engine (ISSUE 8): flash-decode kernel, paged
KV-cache, and continuous-batching scheduler.

Three composable layers, bottom-up:

* :func:`apex_tpu.ops.flash_decode` — decode-mode attention over a
  paged KV cache (the kernel lives with its training siblings in
  ``ops/attention.py``; routing via
  :func:`~apex_tpu.ops.flash_decode_route`, forceable with
  ``routing_override(decode=...)``).
* :class:`PagedKVCache` — fixed-size pages in a preallocated HBM pool,
  per-request page lists, deterministic lowest-first allocation,
  :meth:`~PagedKVCache.defrag` compaction.
* :class:`ContinuousBatchingScheduler` + :class:`ServingEngine` —
  admission/growth/preemption/retirement policy, and the engine that
  turns it into a fixed set of compiled device functions (prefill
  row, decode step, admission scatter — plus, with
  :class:`SpecConfig`, the speculative verify step and the
  chunked-prefill step).
* :mod:`apex_tpu.serving.spec` (ISSUE 12) — the draft–verify
  subsystem: pluggable :class:`Proposer` drafts
  (:class:`NgramProposer` suffix-cache baseline), exact greedy
  verify-accept at ``q_len = k + 1``, chunked prefill.
* r17 serving-perf modes, all ``ServingEngine`` knobs: ``tp`` (decode
  sharded over the parallel_state tensor axis), ``kv_quant``
  (int8/fp8 pool codes + fp32 scales, quantize-on-write /
  dequantize-in-kernel), ``prefix_sharing`` (:class:`PrefixIndex` —
  refcounted copy-on-write pages; repeated prompts pay prefill once).

See docs/serving.md for the page-table layout, the admission policy,
decode routing, speculative decoding, prefix sharing, the quantized
parity bar, and the bench methodology.
"""

from apex_tpu.serving.engine import (  # noqa: F401
    ServingEngine,
    SimClock,
    poisson_trace,
    set_fault_hook,
)
from apex_tpu.serving.kv_cache import (  # noqa: F401
    PagedKVCache,
    PagePoolCorruption,
    PagePoolExhausted,
    PrefixIndex,
    quantize_tokens,
)
from apex_tpu.serving.model import (  # noqa: F401
    PagedDecoder,
    ServingModelConfig,
    init_params,
    shard_params_tp,
)
from apex_tpu.serving.scheduler import (  # noqa: F401
    FINISHED,
    RUNNING,
    WAITING,
    ContinuousBatchingScheduler,
    QueueFullError,
    Request,
)
from apex_tpu.serving.spec import (  # noqa: F401
    NgramProposer,
    Proposer,
    SpecConfig,
)

__all__ = [
    "SpecConfig",
    "Proposer",
    "NgramProposer",
    "ServingEngine",
    "SimClock",
    "poisson_trace",
    "set_fault_hook",
    "PagedKVCache",
    "PagePoolCorruption",
    "PagePoolExhausted",
    "PrefixIndex",
    "quantize_tokens",
    "PagedDecoder",
    "ServingModelConfig",
    "init_params",
    "shard_params_tp",
    "ContinuousBatchingScheduler",
    "QueueFullError",
    "Request",
    "WAITING",
    "RUNNING",
    "FINISHED",
]
