"""Paged KV cache: a preallocated HBM page pool + host-side page
accounting.

The training stack's KV tensors are per-call slabs; a serving engine
instead holds MANY requests' caches alive at once, each growing by one
token per decode step and dying at unpredictable times.  A slab per
request would fragment HBM and force reallocation-and-copy on growth —
the standard answer (vLLM's PagedAttention, SURVEY-adjacent) is a pool
of fixed-size pages:

* ``k``/``v``: ``[num_layers, num_pages, page_size, num_heads,
  head_dim]`` device arrays, allocated ONCE at engine start.  A
  request's cache is a *page list* — pages need not be contiguous, so
  the pool never fragments and "grow by one token" is at most "append
  one page id to a python list".
* Page 0 is the reserved **scratch page**: it is never allocated, page
  tables pad their rows with it, and packed-prefill scatter routes its
  padding positions there.  Readers never see its content (the decode
  kernel and the XLA baseline both mask columns past ``kv_len``), so
  duplicate pad writes landing in it are harmless by construction.
* Host-side accounting (free list, per-page owner, per-page REFCOUNT)
  is plain python — allocation is LOWEST-INDEX-FIRST so every run of
  the scheduler is bit-reproducible.
* r17 adds two orthogonal pool modes: **prefix sharing** (pages are
  refcounted; N requests whose prompts share a prefix reference the
  same physical pages, a write to a shared page copies it first —
  copy-on-write — and ``free`` only returns a page at refcount zero)
  and a **quantized pool** (``quantize="int8"``/``"fp8"``: the pool
  holds narrow codes plus per-(page, slot, head) fp32 scales;
  quantize-on-write in the scatter, dequantize-on-read in
  ``flash_decode``).

The device arrays are functionally updated (``.at[].set``); the cache
object re-binds them, so callers treat ``cache.k``/``cache.v`` (and,
quantized, ``cache.k_scale``/``cache.v_scale``) as the current pool
state (and may thread them through ``jax.jit`` as loop carries).
"""

from __future__ import annotations

import base64
import bisect
import functools
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _scatter_tokens(k_pool, v_pool, k_new, v_new, pages, offsets):
    return (k_pool.at[:, pages, offsets].set(k_new),
            v_pool.at[:, pages, offsets].set(v_new))


#: qmax per quantization mode: int8 symmetric [-127, 127] (the -128
#: code is unused so the grid is symmetric), fp8 e4m3 saturates at 448.
_QUANT_QMAX = {"int8": 127.0, "fp8": 448.0}


def quant_pool_dtype(mode: str):
    """Device dtype of the quantized pool's code arrays."""
    if mode == "int8":
        return jnp.int8
    if mode == "fp8":
        dt = getattr(jnp, "float8_e4m3fn", None)
        if dt is None:
            raise ValueError(
                "quantize='fp8' needs jnp.float8_e4m3fn, which this "
                "jax build lacks — use quantize='int8'")
        return dt
    raise ValueError(f"unknown quantize mode {mode!r} "
                     f"(expected one of {sorted(_QUANT_QMAX)})")


def quantize_tokens(x: jnp.ndarray, qdtype, qmax: float):
    """``x`` [..., H, D] -> (codes [..., H, D] ``qdtype``, scale
    [..., H] fp32).

    The scale is a PURE per-(token, head) function of that token's own
    values — absmax over D divided by ``qmax``, with absmax 0 mapped to
    scale 1 so zero rows stay exactly zero.  Order independence is the
    point: quantizing a token during incremental decode append and
    re-quantizing it during a bulk rebuild prefill produce
    bitwise-identical pool bytes, which is what lets the KV-rebuild
    recovery contract extend to the quantized pool.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
    codes = xf / scale[..., None]
    if np.dtype(qdtype) == np.dtype(np.int8):
        codes = jnp.clip(jnp.round(codes), -qmax, qmax)
    return codes.astype(qdtype), scale


def _scatter_tokens_quant(k_pool, v_pool, ks_pool, vs_pool,
                          k_new, v_new, pages, offsets, *, qmax):
    """Quantize-on-write admission scatter: incoming fp tokens are
    narrowed on device (codes + scales) and scattered in one fused
    update per pool array — the wide values never land in HBM."""
    kq, ks = quantize_tokens(k_new, k_pool.dtype, qmax)
    vq, vs = quantize_tokens(v_new, v_pool.dtype, qmax)
    return (k_pool.at[:, pages, offsets].set(kq),
            v_pool.at[:, pages, offsets].set(vq),
            ks_pool.at[:, pages, offsets].set(ks),
            vs_pool.at[:, pages, offsets].set(vs))


def _copy_page(pool, src, dst):
    """pool[:, dst] = pool[:, src] with traced indices, so every COW
    copy reuses one compiled executable regardless of page ids."""
    page = jax.lax.dynamic_index_in_dim(pool, src, axis=1, keepdims=True)
    return jax.lax.dynamic_update_slice_in_dim(pool, page, dst, axis=1)


def _import_page(pool, page, dst):
    """pool[:, dst] = page (a ``[layers, 1, ...]`` host slice) with a
    traced destination, so importing a SHIPPED page (r18 disaggregation)
    reuses one compiled executable regardless of the landing id."""
    return jax.lax.dynamic_update_slice_in_dim(pool, page, dst, axis=1)


def verify_page_payload(data: Dict[str, int]) -> bool:
    """Host-side CRC check of one shipped-page payload (r18) — pure
    base64/zlib, no device work, so receivers can reject a
    corrupted-in-flight page BEFORE touching their pool.  The digest
    recipe matches :meth:`PagedKVCache._page_digest` exactly (K bytes
    plus — quantized, inferred from the scale keys — the K scale
    bytes), so a payload that verifies here lands with a CRC the
    importing pool's read-back validation will agree with."""
    kb = base64.b64decode(data["k"])
    vb = base64.b64decode(data["v"])
    if "k_scale" in data:
        kb += base64.b64decode(data["k_scale"])
        vb += base64.b64decode(data["v_scale"])
    return (zlib.crc32(kb) == data["crc_k"]
            and zlib.crc32(vb) == data["crc_v"])


class PagePoolExhausted(RuntimeError):
    """No free pages left — the scheduler's cue to preempt, never an
    OOM: the pool size is fixed at construction and allocation failure
    is an ordinary, recoverable scheduling event."""


class PagePoolCorruption(RuntimeError):
    """A pool page's content no longer matches its recorded CRC32 —
    an HBM bit flip / DMA fault stand-in (ISSUE 10).  Recoverable by
    construction: page content is always rebuildable from host-side
    tokens via deterministic re-prefill, so the engine treats this
    like a device loss (rebuild pool + restore) rather than an abort."""


class PagedKVCache:
    """Fixed-size paged KV pool shared by all in-flight requests.

    ``max_pages_per_request`` fixes the page-table width ``p_max`` —
    every decode step sees a static ``[batch, p_max]`` table, so
    admitting or retiring requests never recompiles the step.
    """

    def __init__(self, *, num_layers: int, num_pages: int,
                 page_size: int, num_heads: int, head_dim: int,
                 max_pages_per_request: int,
                 dtype=jnp.float32, crc_pages: bool = False,
                 quantize: Optional[str] = None):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved scratch page)")
        if max_pages_per_request > num_pages - 1:
            raise ValueError(
                f"max_pages_per_request {max_pages_per_request} exceeds "
                f"the {num_pages - 1} allocatable pages")
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.max_pages_per_request = max_pages_per_request
        #: quantization mode (None / "int8" / "fp8").  ``dtype`` stays
        #: the COMPUTE dtype of the tokens fed to ``write_tokens``;
        #: quantized pools store narrow codes plus fp32 scales.
        self.quantize = quantize
        self.dtype = dtype
        pool_dtype = quant_pool_dtype(quantize) if quantize else dtype
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, pool_dtype)
        self.v = jnp.zeros(shape, pool_dtype)
        # the prefill scatter donates the old pool on TPU so the
        # update is in-place — two full-pool copies per admission
        # would otherwise sit on the TTFT-critical path
        if quantize:
            self.qmax = _QUANT_QMAX[quantize]
            sshape = (num_layers, num_pages, page_size, num_heads)
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
            donate = (0, 1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._scatter = jax.jit(
                functools.partial(_scatter_tokens_quant, qmax=self.qmax),
                donate_argnums=donate)
        else:
            self.qmax = None
            self.k_scale = self.v_scale = None
            donate = (0, 1) if jax.default_backend() == "tpu" else ()
            self._scatter = jax.jit(_scatter_tokens, donate_argnums=donate)
        self._copy = jax.jit(
            _copy_page,
            donate_argnums=(0,) if jax.default_backend() == "tpu" else ())
        self._import = jax.jit(
            _import_page,
            donate_argnums=(0,) if jax.default_backend() == "tpu" else ())
        # sorted free list, lowest-first allocation: deterministic
        self._free: List[int] = list(range(1, num_pages))
        self._owner: Dict[int, int] = {}
        # per-page refcount (r17 prefix sharing): every allocated page
        # has exactly one entry; allocate -> 1, share -> +1, free -> -1
        # with the page returning to the free list only at zero
        self._ref: Dict[int, int] = {}
        # opt-in per-page CRC validation (ISSUE 10): every host-visible
        # write records a crc32 of the page's K and V bytes;
        # verify_pages re-reads the device content and raises
        # PagePoolCorruption on mismatch.  Costs a device->host pull
        # per touched page per step — a chaos/debug knob, off by
        # default (docs/serving.md "Failure semantics").
        self.crc_pages = bool(crc_pages)
        self._crc: Dict[int, Tuple[int, int]] = {}

    # -- accounting ------------------------------------------------------

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_used(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    @property
    def pages_shared(self) -> int:
        """Pages currently referenced by MORE than one reader (live
        requests and/or the prefix index) — the ``pool_shared_pages``
        telemetry count."""
        return sum(1 for r in self._ref.values() if r > 1)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)  # ceil

    def allocate(self, n: int, owner: int) -> List[int]:
        """Take ``n`` free pages for ``owner`` (a request id) at
        refcount 1; raises :class:`PagePoolExhausted` — with the pool
        untouched — when fewer than ``n`` are free."""
        if n > len(self._free):
            raise PagePoolExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"({self.pages_used}/{self.num_pages - 1} in use)")
        pages, self._free = self._free[:n], self._free[n:]
        for p in pages:
            self._owner[p] = owner
            self._ref[p] = 1
        return pages

    def share(self, pages: Sequence[int]) -> None:
        """Add one reader to each page (prefix sharing): the pages'
        CONTENT becomes immutable until the refcount drops back —
        writers must :meth:`cow` first.  Raises on pages that are not
        currently allocated (sharing a free page would resurrect it)."""
        for p in pages:
            if p == 0 or p not in self._ref:
                raise ValueError(f"share of unallocated page {p}")
        for p in pages:
            self._ref[p] += 1

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def is_shared(self, page: int) -> bool:
        """True while more than one reader references ``page`` — the
        state in which writes (scatter/append), :meth:`free_tail` and
        :meth:`defrag` are forbidden on it (docs/serving.md
        "Prefix sharing")."""
        return self._ref.get(page, 0) > 1

    def cow(self, page: int, owner: int) -> int:
        """Copy-on-write: give ``owner`` a private copy of shared
        ``page`` and drop its own reference to the original.  Returns
        the new page id; the caller swaps it into its page list before
        writing.  Content (K, V and — quantized — the scale planes)
        moves by one compiled dynamic-slice copy per pool array, so
        repeated COWs never recompile.  Raises on an unshared page
        (a private page needs no copy — calling this would leak one)
        and propagates :class:`PagePoolExhausted` when no page is free
        (an ordinary scheduling event, like any allocation failure)."""
        if self._ref.get(page, 0) < 2:
            raise ValueError(f"cow on unshared page {page} "
                             f"(refcount {self._ref.get(page, 0)})")
        [new] = self.allocate(1, owner)
        src = jnp.int32(page)
        dst = jnp.int32(new)
        self.k = self._copy(self.k, src, dst)
        self.v = self._copy(self.v, src, dst)
        if self.quantize:
            self.k_scale = self._copy(self.k_scale, src, dst)
            self.v_scale = self._copy(self.v_scale, src, dst)
        self._ref[page] -= 1
        # content moved verbatim, so the copy inherits the digest
        if page in self._crc:
            self._crc[new] = self._crc[page]
        return new

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page returns to the pool
        (retirement or preemption) only when its refcount reaches zero
        — while the prefix index or another request still references
        it, the page stays live.  The freed page's CONTENT is left in
        place — readers mask by ``kv_len``, so stale values are
        unreachable, and skipping the zero-fill keeps retirement
        free."""
        for p in pages:
            if p == 0 or p not in self._ref:
                raise ValueError(f"double free / scratch free: page {p}")
            self._ref[p] -= 1
            if self._ref[p] > 0:
                continue
            del self._ref[p]
            self._owner.pop(p, None)
            self._crc.pop(p, None)
            bisect.insort(self._free, p)

    def free_tail(self, pages: List[int], keep: int) -> None:
        """Free ``pages[keep:]`` IN PLACE — the speculative-verify
        rollback (ISSUE 12): pages grown to hold a draft's K/V whose
        tail rows were rejected are returned to the pool, and the
        request's page list is truncated to the committed footprint.
        A ``keep`` at or past the list length is a no-op (a fully
        accepted draft rolls back nothing).

        FORBIDDEN on shared pages (r17): draft tails only ever live in
        pages the request grew privately past its prompt, so a shared
        page in the tail means the rollback arithmetic is wrong —
        raising beats silently dropping another reader's prefix."""
        if keep < 0:
            raise ValueError(f"free_tail keep={keep} must be >= 0")
        tail = pages[keep:]
        shared = [p for p in tail if self.is_shared(p)]
        if shared:
            raise ValueError(
                f"free_tail would roll back shared page(s) {shared} — "
                "rollback is only defined on a request's private tail")
        if tail:
            self.free(tail)
            del pages[keep:]

    def owner_of(self, page: int) -> Optional[int]:
        return self._owner.get(page)

    # -- device-facing views ---------------------------------------------

    def page_table(self, page_lists: Sequence[Sequence[int]],
                   rows: Optional[int] = None) -> jnp.ndarray:
        """``[rows, max_pages_per_request]`` int32 table, each row a
        request's page list in cache order, padded with the scratch
        page 0 (padding the row with a REPEATED valid index also lets
        the decode kernel's block pipeline elide the dead DMAs)."""
        rows = len(page_lists) if rows is None else rows
        t = np.zeros((rows, self.max_pages_per_request), np.int32)
        for i, pages in enumerate(page_lists):
            if len(pages) > self.max_pages_per_request:
                raise ValueError(
                    f"page list of {len(pages)} exceeds "
                    f"max_pages_per_request={self.max_pages_per_request}")
            t[i, :len(pages)] = pages
        return jnp.asarray(t)

    def write_tokens(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
                     pages: jnp.ndarray, offsets: jnp.ndarray) -> None:
        """Scatter per-token K/V into the pool (the prefill fill path).

        ``k_new``/``v_new``: ``[num_layers, T, num_heads, head_dim]``
        in the COMPUTE dtype; token t lands in ``(pages[t],
        offsets[t])``.  Padding positions point at the scratch page 0.
        Quantized pools quantize-on-write: codes and per-(slot, head)
        scales are produced on device and scattered together."""
        touched = ({int(p) for p in np.asarray(pages).ravel()} - {0}
                   if self.crc_pages else ())
        pages = jnp.asarray(pages, jnp.int32)
        offsets = jnp.asarray(offsets, jnp.int32)
        if self.quantize:
            self.k, self.v, self.k_scale, self.v_scale = self._scatter(
                self.k, self.v, self.k_scale, self.v_scale,
                k_new, v_new, pages, offsets)
        else:
            self.k, self.v = self._scatter(
                self.k, self.v, k_new, v_new, pages, offsets)
        if self.crc_pages:
            self.refresh_page_crcs(touched)

    def warm_copy(self) -> None:
        """Compile the COW page-copy executable (:meth:`cow`'s
        ``_copy_page``) against the live pool shapes — scratch page 0
        copied onto itself, a content no-op no reader ever sees — so
        the first shared-prefix admission's copy-on-write never pays a
        jit compile on the admission path.  Quantized pools warm the
        scale-plane shape too (same function, second specialization).
        Called from ``ServingEngine.warmup`` when prefix sharing is
        on; part of the zero-compiles-after-warmup contract."""
        z = jnp.int32(0)
        self.k = self._copy(self.k, z, z)
        self.v = self._copy(self.v, z, z)
        if self.quantize:
            self.k_scale = self._copy(self.k_scale, z, z)
            self.v_scale = self._copy(self.v_scale, z, z)

    def warm_import(self) -> None:
        """Compile the shipped-page import executable
        (:meth:`import_page_bytes`'s ``_import_page``) against the
        live pool shapes — an all-zero page written into scratch page
        0, a content no-op no reader ever sees — so a decode replica's
        FIRST inbound shipment never pays a jit compile.  Quantized
        pools warm the scale-plane shape too (same function, second
        specialization).  Called from ``ServingEngine.warmup`` when
        ``kv_import`` is on; part of the zero-compiles-after-warmup
        contract."""
        z = jnp.int32(0)
        pshape = (self.num_layers, 1, self.page_size,
                  self.num_heads, self.head_dim)
        self.k = self._import(self.k, jnp.zeros(pshape, self.k.dtype), z)
        self.v = self._import(self.v, jnp.zeros(pshape, self.v.dtype), z)
        if self.quantize:
            sshape = (self.num_layers, 1, self.page_size, self.num_heads)
            zs = jnp.zeros(sshape, jnp.float32)
            self.k_scale = self._import(self.k_scale, zs, z)
            self.v_scale = self._import(self.v_scale, zs, z)

    def warm_export(self) -> None:
        """Compile the page-slice gather :meth:`export_page_bytes`
        reads the pool through (``k[:, page:page+1]`` is a device op)
        by exporting scratch page 0 once and discarding the payload —
        so a prefill replica's FIRST outbound shipment never pays a
        jit compile.  Called from ``ServingEngine.warmup`` when
        ``prefill_only`` is on; the export twin of
        :meth:`warm_import`."""
        self.export_page_bytes(0)

    # -- page shipping (r18 disaggregation) ------------------------------

    def export_page_bytes(self, page: int) -> Dict[str, int]:
        """Serialize one page for shipping: C-order K/V page slices
        (quantized: the narrow codes, plus the fp32 scale planes as
        separate keys) as base64 text, with per-page CRCs stamped at
        export using the :meth:`_page_digest` recipe — the receiver
        verifies them host-side (:func:`verify_page_payload`) before
        its pool ever sees the bytes, and records them as the imported
        page's read-back digest."""
        k = np.ascontiguousarray(np.asarray(self.k[:, page:page + 1]))
        v = np.ascontiguousarray(np.asarray(self.v[:, page:page + 1]))
        kb, vb = k.tobytes(), v.tobytes()
        out = {"k": base64.b64encode(kb).decode("ascii"),
               "v": base64.b64encode(vb).decode("ascii")}
        if self.quantize:
            ksb = np.ascontiguousarray(
                np.asarray(self.k_scale[:, page:page + 1])).tobytes()
            vsb = np.ascontiguousarray(
                np.asarray(self.v_scale[:, page:page + 1])).tobytes()
            out["k_scale"] = base64.b64encode(ksb).decode("ascii")
            out["v_scale"] = base64.b64encode(vsb).decode("ascii")
            kb += ksb
            vb += vsb
        out["crc_k"] = zlib.crc32(kb)
        out["crc_v"] = zlib.crc32(vb)
        return out

    def import_page_bytes(self, page: int, data: Dict[str, int]) -> None:
        """Land one shipped payload in (already allocated) ``page``,
        verbatim: the pool bytes after import are bitwise the source
        pool's bytes — including quantized codes and scale planes — so
        decode over an imported page is indistinguishable from decode
        over a locally prefilled one.  Callers verify the payload
        first (:func:`verify_page_payload`); this method trusts it and
        records the shipped CRCs as the page's read-back digest."""
        pshape = (self.num_layers, 1, self.page_size,
                  self.num_heads, self.head_dim)
        dst = jnp.int32(page)
        k = np.frombuffer(base64.b64decode(data["k"]),
                          dtype=np.dtype(self.k.dtype)).reshape(pshape)
        v = np.frombuffer(base64.b64decode(data["v"]),
                          dtype=np.dtype(self.v.dtype)).reshape(pshape)
        self.k = self._import(self.k, jnp.asarray(k), dst)
        self.v = self._import(self.v, jnp.asarray(v), dst)
        if self.quantize:
            sshape = (self.num_layers, 1, self.page_size, self.num_heads)
            ks = np.frombuffer(base64.b64decode(data["k_scale"]),
                               dtype=np.float32).reshape(sshape)
            vs = np.frombuffer(base64.b64decode(data["v_scale"]),
                               dtype=np.float32).reshape(sshape)
            self.k_scale = self._import(self.k_scale, jnp.asarray(ks), dst)
            self.v_scale = self._import(self.v_scale, jnp.asarray(vs), dst)
        if self.crc_pages:
            # shipped bytes land verbatim, so the export digest IS the
            # imported page's digest — no device read-back needed
            self._crc[page] = (data["crc_k"], data["crc_v"])

    def analysis_executable(self, n_tokens: int, *, donate: bool = True):
        """``jax.stages.Lowered`` of the :meth:`write_tokens` scatter
        at an ``n_tokens``-row fill width, with the TPU pool donation
        forced on regardless of backend — the ISSUE 13 contract
        checker verifies the donation the shipped engine relies on (an
        undonated scatter copies BOTH full pools per admission on the
        TTFT-critical path: the PR 8 768 MB lesson).  ``donate=False``
        is the checker's negative control.  A quantized cache lowers
        the quantize-on-write variant with the scale planes donated
        too (params 0-3 alias outputs 0-3)."""
        sds = jax.ShapeDtypeStruct
        pool = sds(self.k.shape, self.k.dtype)
        new = sds((self.num_layers, n_tokens, self.num_heads,
                   self.head_dim), self.dtype)
        idx = sds((n_tokens,), jnp.int32)
        if self.quantize:
            scale = sds(self.k_scale.shape, jnp.float32)
            jitted = jax.jit(
                functools.partial(_scatter_tokens_quant, qmax=self.qmax),
                donate_argnums=(0, 1, 2, 3) if donate else ())
            return jitted.lower(pool, pool, scale, scale, new, new,
                                idx, idx)
        jitted = jax.jit(_scatter_tokens,
                         donate_argnums=(0, 1) if donate else ())
        return jitted.lower(pool, pool, new, new, idx, idx)

    # -- per-page CRC validation (ISSUE 10, opt-in) ----------------------

    def _page_digest(self, page: int) -> Tuple[int, int]:
        """crc32 of page ``page``'s K and V bytes across all layers
        (quantized: codes AND scale planes — content identity includes
        the scales, or a flipped scale bit would read back clean)."""
        k = np.ascontiguousarray(np.asarray(self.k[:, page]))
        v = np.ascontiguousarray(np.asarray(self.v[:, page]))
        kb, vb = k.tobytes(), v.tobytes()
        if self.quantize:
            # same sanctioned read-back as the code planes above —
            # device ``.tobytes()`` pulls the scale slice directly
            kb += self.k_scale[:, page].tobytes()
            vb += self.v_scale[:, page].tobytes()
        return (zlib.crc32(kb), zlib.crc32(vb))

    def refresh_page_crcs(self, pages: Sequence[int]) -> None:
        """Re-record CRCs after a host-visible write (prefill scatter /
        the decode step's per-row append).  No-op unless ``crc_pages``."""
        if not self.crc_pages:
            return
        for p in sorted({int(p) for p in pages} - {0}):
            self._crc[p] = self._page_digest(p)

    def verify_pages(self, page_lists: Sequence[Sequence[int]]) -> None:
        """Read-back validation: recompute each live page's digest and
        compare against the recorded CRC; raises
        :class:`PagePoolCorruption` naming the damaged page.  Pages
        with no recorded CRC (never written through a CRC-tracking
        path) are skipped — absence of a record is not corruption."""
        if not self.crc_pages:
            return
        for p in sorted({int(p) for lst in page_lists for p in lst} - {0}):
            want = self._crc.get(p)
            if want is None:
                continue
            if self._page_digest(p) != want:
                raise PagePoolCorruption(
                    f"page {p} failed CRC read-back "
                    f"(owner rid {self._owner.get(p)})")

    # -- defrag ----------------------------------------------------------

    def defrag(self, page_lists: Sequence[List[int]]) -> Dict[int, int]:
        """Compact live pages to the lowest pool indices.

        A long-running pool ends up with live pages scattered across
        the index space; compaction restores the dense prefix layout a
        fresh pool has (locality for the pool DMAs, and a cheap
        "occupancy == high-water-mark" invariant).  ``page_lists`` are
        the page lists of every live request, IN PLACE — they are
        rewritten to the new ids.  Returns the old→new mapping.
        Content moves by one device gather per pool array (quantized:
        the scale planes gather with the codes).

        FORBIDDEN while any page is shared (r17): under prefix sharing
        one physical page legitimately appears in several page lists,
        which breaks both the overlap check below (duplicates are no
        longer proof of corruption) and the dense-renumber arithmetic
        (a shared page would need ONE new id visible to every reader,
        including the prefix index's entries, which this method never
        sees).  Callers drain sharing first — evict the prefix index
        and wait for multi-reader pages to drop to refcount 1 — or
        skip the compaction; a pool with live sharing is by definition
        not fragmented enough to need it."""
        shared = sorted(p for p, r in self._ref.items() if r > 1)
        if shared:
            raise ValueError(
                f"defrag forbidden while page(s) {shared} are shared "
                "(refcount > 1) — evict the prefix index / let readers "
                "retire first")
        live: List[int] = []
        for pages in page_lists:
            live.extend(pages)
        if len(set(live)) != len(live):
            raise ValueError("page lists overlap — pool corruption")
        mapping = {old: new for new, old in enumerate(live, start=1)}
        src = np.arange(self.num_pages)
        for old, new in mapping.items():
            src[new] = old
        # pages outside the live prefix keep whatever content the
        # gather assigns them — they are free, nothing reads them
        src_j = jnp.asarray(src, jnp.int32)
        self.k = self.k[:, src_j]
        self.v = self.v[:, src_j]
        if self.quantize:
            self.k_scale = self.k_scale[:, src_j]
            self.v_scale = self.v_scale[:, src_j]
        self._owner = {mapping[p]: o for p, o in self._owner.items()
                       if p in mapping}
        self._ref = {mapping[p]: r for p, r in self._ref.items()
                     if p in mapping}
        # content moves verbatim with the ids, so digests remap too
        self._crc = {mapping[p]: c for p, c in self._crc.items()
                     if p in mapping}
        self._free = list(range(len(live) + 1, self.num_pages))
        for pages in page_lists:
            pages[:] = [mapping[p] for p in pages]
        return mapping


class PrefixIndex:
    """Prompt-prefix registry backing page sharing (r17).

    Maps a previously prefilled context (token tuple) to the pages
    holding its K/V, taking its OWN +1 refcount on every registered
    page (``PagedKVCache.share``) so an entry outlives the request
    that built it — a popular system prompt stays warm in the pool
    after every request using it has retired.

    Admission asks :meth:`lookup` for the longest registered prefix of
    a new request's context; on a hit the scheduler shares those pages
    (prefill for the covered tokens is SKIPPED — the new request
    chunk-prefills only its suffix against the shared pages).  The
    shared coverage is capped at ``len(context) - 1`` tokens so every
    admitted request still computes at least its final prompt token —
    that chunk is what yields the first-token logits.

    Capacity is bounded (``max_entries``); eviction is OLDEST-FIRST
    (insertion order — deterministic, like every other scheduling
    decision here) and only drops the INDEX's reference: a page some
    live request still reads keeps a nonzero refcount and never
    returns to the free list (pinned by the r17 eviction test).
    """

    def __init__(self, cache: PagedKVCache, *, max_entries: int = 8):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.cache = cache
        self.max_entries = int(max_entries)
        self._entries: "OrderedDict[Tuple[int, ...], List[int]]" = \
            OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def entries(self) -> List[Tuple[int, ...]]:
        return list(self._entries)

    def register(self, tokens: Sequence[int],
                 pages: Sequence[int]) -> bool:
        """Register a completed prefill's context -> page-list mapping
        (the request KEEPS its own references; the index adds one per
        page).  Rejects contexts shorter than one page (nothing to
        share) and duplicate keys; enforces that ``pages`` is exactly
        the context's page footprint, no more — registering a
        request's decode-grown tail would share pages it is still
        writing."""
        key = tuple(int(t) for t in tokens)
        if len(key) < self.cache.page_size or key in self._entries:
            return False
        if len(pages) != self.cache.pages_needed(len(key)):
            raise ValueError(
                f"register: {len(pages)} pages for a {len(key)}-token "
                f"context (expected {self.cache.pages_needed(len(key))})")
        self.cache.share(pages)
        self._entries[key] = list(pages)
        while len(self._entries) > self.max_entries:
            self.evict_one()
        return True

    def lookup(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest usable shared prefix for ``tokens``: returns
        ``(m, pages)`` where the first ``m`` context tokens are covered
        by ``pages`` (the entry's leading ``ceil(m / page_size)``
        pages), or ``(0, [])`` on a miss.  ``m`` is capped at
        ``len(tokens) - 1`` (see class docstring) and hits below one
        full page are ignored.  When ``m`` ends mid-page the last
        shared page also holds the ENTRY's diverging tokens past ``m``
        — safe, because readers mask by their own ``kv_len`` and the
        new reader's first write into that page copies it first
        (copy-on-write)."""
        ctx = tuple(int(t) for t in tokens)
        best_m, best_pages = 0, []
        for key, pages in self._entries.items():
            lim = min(len(key), len(ctx) - 1)
            m = 0
            while m < lim and key[m] == ctx[m]:
                m += 1
            if m >= self.cache.page_size and m > best_m:
                best_m = m
                best_pages = pages[:self.cache.pages_needed(m)]
        return best_m, list(best_pages)

    def evict_one(self) -> int:
        """Drop the oldest entry, releasing the index's reference on
        its pages; returns how many pages actually went back to the
        free list (pages another reader still holds stay live — the
        index can never free a page out from under a request)."""
        if not self._entries:
            return 0
        _, pages = self._entries.popitem(last=False)
        before = self.cache.pages_free
        self.cache.free(pages)
        return self.cache.pages_free - before

    def clear(self) -> int:
        """Evict every entry; returns pages returned to the pool."""
        freed = 0
        while self._entries:
            freed += self.evict_one()
        return freed
