"""Paged KV cache: a preallocated HBM page pool + host-side page
accounting.

The training stack's KV tensors are per-call slabs; a serving engine
instead holds MANY requests' caches alive at once, each growing by one
token per decode step and dying at unpredictable times.  A slab per
request would fragment HBM and force reallocation-and-copy on growth —
the standard answer (vLLM's PagedAttention, SURVEY-adjacent) is a pool
of fixed-size pages:

* ``k``/``v``: ``[num_layers, num_pages, page_size, num_heads,
  head_dim]`` device arrays, allocated ONCE at engine start.  A
  request's cache is a *page list* — pages need not be contiguous, so
  the pool never fragments and "grow by one token" is at most "append
  one page id to a python list".
* Page 0 is the reserved **scratch page**: it is never allocated, page
  tables pad their rows with it, and packed-prefill scatter routes its
  padding positions there.  Readers never see its content (the decode
  kernel and the XLA baseline both mask columns past ``kv_len``), so
  duplicate pad writes landing in it are harmless by construction.
* Host-side accounting (free list, per-page owner) is plain python —
  allocation is LOWEST-INDEX-FIRST so every run of the scheduler is
  bit-reproducible.

The device arrays are functionally updated (``.at[].set``); the cache
object re-binds them, so callers treat ``cache.k``/``cache.v`` as the
current pool state (and may thread them through ``jax.jit`` as loop
carries).
"""

from __future__ import annotations

import bisect
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _scatter_tokens(k_pool, v_pool, k_new, v_new, pages, offsets):
    return (k_pool.at[:, pages, offsets].set(k_new),
            v_pool.at[:, pages, offsets].set(v_new))


class PagePoolExhausted(RuntimeError):
    """No free pages left — the scheduler's cue to preempt, never an
    OOM: the pool size is fixed at construction and allocation failure
    is an ordinary, recoverable scheduling event."""


class PagePoolCorruption(RuntimeError):
    """A pool page's content no longer matches its recorded CRC32 —
    an HBM bit flip / DMA fault stand-in (ISSUE 10).  Recoverable by
    construction: page content is always rebuildable from host-side
    tokens via deterministic re-prefill, so the engine treats this
    like a device loss (rebuild pool + restore) rather than an abort."""


class PagedKVCache:
    """Fixed-size paged KV pool shared by all in-flight requests.

    ``max_pages_per_request`` fixes the page-table width ``p_max`` —
    every decode step sees a static ``[batch, p_max]`` table, so
    admitting or retiring requests never recompiles the step.
    """

    def __init__(self, *, num_layers: int, num_pages: int,
                 page_size: int, num_heads: int, head_dim: int,
                 max_pages_per_request: int,
                 dtype=jnp.float32, crc_pages: bool = False):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved scratch page)")
        if max_pages_per_request > num_pages - 1:
            raise ValueError(
                f"max_pages_per_request {max_pages_per_request} exceeds "
                f"the {num_pages - 1} allocatable pages")
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.max_pages_per_request = max_pages_per_request
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # the prefill scatter donates the old pool on TPU so the
        # update is in-place — two full-pool copies per admission
        # would otherwise sit on the TTFT-critical path
        donate = (0, 1) if jax.default_backend() == "tpu" else ()
        self._scatter = jax.jit(_scatter_tokens, donate_argnums=donate)
        # sorted free list, lowest-first allocation: deterministic
        self._free: List[int] = list(range(1, num_pages))
        self._owner: Dict[int, int] = {}
        # opt-in per-page CRC validation (ISSUE 10): every host-visible
        # write records a crc32 of the page's K and V bytes;
        # verify_pages re-reads the device content and raises
        # PagePoolCorruption on mismatch.  Costs a device->host pull
        # per touched page per step — a chaos/debug knob, off by
        # default (docs/serving.md "Failure semantics").
        self.crc_pages = bool(crc_pages)
        self._crc: Dict[int, Tuple[int, int]] = {}

    # -- accounting ------------------------------------------------------

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_used(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)  # ceil

    def allocate(self, n: int, owner: int) -> List[int]:
        """Take ``n`` free pages for ``owner`` (a request id); raises
        :class:`PagePoolExhausted` — with the pool untouched — when
        fewer than ``n`` are free."""
        if n > len(self._free):
            raise PagePoolExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"({self.pages_used}/{self.num_pages - 1} in use)")
        pages, self._free = self._free[:n], self._free[n:]
        for p in pages:
            self._owner[p] = owner
        return pages

    def free(self, pages: Sequence[int]) -> None:
        """Return pages to the pool (retirement or preemption).  The
        page CONTENT is left in place — readers mask by ``kv_len``, so
        stale values are unreachable, and skipping the zero-fill keeps
        retirement free."""
        for p in pages:
            if p == 0 or p in self._free:
                raise ValueError(f"double free / scratch free: page {p}")
            self._owner.pop(p, None)
            self._crc.pop(p, None)
            bisect.insort(self._free, p)

    def free_tail(self, pages: List[int], keep: int) -> None:
        """Free ``pages[keep:]`` IN PLACE — the speculative-verify
        rollback (ISSUE 12): pages grown to hold a draft's K/V whose
        tail rows were rejected are returned to the pool, and the
        request's page list is truncated to the committed footprint.
        A ``keep`` at or past the list length is a no-op (a fully
        accepted draft rolls back nothing)."""
        if keep < 0:
            raise ValueError(f"free_tail keep={keep} must be >= 0")
        tail = pages[keep:]
        if tail:
            self.free(tail)
            del pages[keep:]

    def owner_of(self, page: int) -> Optional[int]:
        return self._owner.get(page)

    # -- device-facing views ---------------------------------------------

    def page_table(self, page_lists: Sequence[Sequence[int]],
                   rows: Optional[int] = None) -> jnp.ndarray:
        """``[rows, max_pages_per_request]`` int32 table, each row a
        request's page list in cache order, padded with the scratch
        page 0 (padding the row with a REPEATED valid index also lets
        the decode kernel's block pipeline elide the dead DMAs)."""
        rows = len(page_lists) if rows is None else rows
        t = np.zeros((rows, self.max_pages_per_request), np.int32)
        for i, pages in enumerate(page_lists):
            if len(pages) > self.max_pages_per_request:
                raise ValueError(
                    f"page list of {len(pages)} exceeds "
                    f"max_pages_per_request={self.max_pages_per_request}")
            t[i, :len(pages)] = pages
        return jnp.asarray(t)

    def write_tokens(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
                     pages: jnp.ndarray, offsets: jnp.ndarray) -> None:
        """Scatter per-token K/V into the pool (the prefill fill path).

        ``k_new``/``v_new``: ``[num_layers, T, num_heads, head_dim]``;
        token t lands in ``(pages[t], offsets[t])``.  Padding positions
        point at the scratch page 0."""
        touched = ({int(p) for p in np.asarray(pages).ravel()} - {0}
                   if self.crc_pages else ())
        pages = jnp.asarray(pages, jnp.int32)
        offsets = jnp.asarray(offsets, jnp.int32)
        self.k, self.v = self._scatter(
            self.k, self.v, k_new, v_new, pages, offsets)
        if self.crc_pages:
            self.refresh_page_crcs(touched)

    def analysis_executable(self, n_tokens: int, *, donate: bool = True):
        """``jax.stages.Lowered`` of the :meth:`write_tokens` scatter
        at an ``n_tokens``-row fill width, with the TPU pool donation
        forced on regardless of backend — the ISSUE 13 contract
        checker verifies the donation the shipped engine relies on (an
        undonated scatter copies BOTH full pools per admission on the
        TTFT-critical path: the PR 8 768 MB lesson).  ``donate=False``
        is the checker's negative control."""
        sds = jax.ShapeDtypeStruct
        pool = sds(self.k.shape, self.k.dtype)
        new = sds((self.num_layers, n_tokens, self.num_heads,
                   self.head_dim), self.k.dtype)
        idx = sds((n_tokens,), jnp.int32)
        jitted = jax.jit(_scatter_tokens,
                         donate_argnums=(0, 1) if donate else ())
        return jitted.lower(pool, pool, new, new, idx, idx)

    # -- per-page CRC validation (ISSUE 10, opt-in) ----------------------

    def _page_digest(self, page: int) -> Tuple[int, int]:
        """crc32 of page ``page``'s K and V bytes across all layers."""
        k = np.ascontiguousarray(np.asarray(self.k[:, page]))
        v = np.ascontiguousarray(np.asarray(self.v[:, page]))
        return (zlib.crc32(k.tobytes()), zlib.crc32(v.tobytes()))

    def refresh_page_crcs(self, pages: Sequence[int]) -> None:
        """Re-record CRCs after a host-visible write (prefill scatter /
        the decode step's per-row append).  No-op unless ``crc_pages``."""
        if not self.crc_pages:
            return
        for p in sorted({int(p) for p in pages} - {0}):
            self._crc[p] = self._page_digest(p)

    def verify_pages(self, page_lists: Sequence[Sequence[int]]) -> None:
        """Read-back validation: recompute each live page's digest and
        compare against the recorded CRC; raises
        :class:`PagePoolCorruption` naming the damaged page.  Pages
        with no recorded CRC (never written through a CRC-tracking
        path) are skipped — absence of a record is not corruption."""
        if not self.crc_pages:
            return
        for p in sorted({int(p) for lst in page_lists for p in lst} - {0}):
            want = self._crc.get(p)
            if want is None:
                continue
            if self._page_digest(p) != want:
                raise PagePoolCorruption(
                    f"page {p} failed CRC read-back "
                    f"(owner rid {self._owner.get(p)})")

    # -- defrag ----------------------------------------------------------

    def defrag(self, page_lists: Sequence[List[int]]) -> Dict[int, int]:
        """Compact live pages to the lowest pool indices.

        A long-running pool ends up with live pages scattered across
        the index space; compaction restores the dense prefix layout a
        fresh pool has (locality for the pool DMAs, and a cheap
        "occupancy == high-water-mark" invariant).  ``page_lists`` are
        the page lists of every live request, IN PLACE — they are
        rewritten to the new ids.  Returns the old→new mapping.
        Content moves by one device gather per pool array."""
        live: List[int] = []
        for pages in page_lists:
            live.extend(pages)
        if len(set(live)) != len(live):
            raise ValueError("page lists overlap — pool corruption")
        mapping = {old: new for new, old in enumerate(live, start=1)}
        src = np.arange(self.num_pages)
        for old, new in mapping.items():
            src[new] = old
        # pages outside the live prefix keep whatever content the
        # gather assigns them — they are free, nothing reads them
        src_j = jnp.asarray(src, jnp.int32)
        self.k = self.k[:, src_j]
        self.v = self.v[:, src_j]
        self._owner = {mapping[p]: o for p, o in self._owner.items()
                       if p in mapping}
        # content moves verbatim with the ids, so digests remap too
        self._crc = {mapping[p]: c for p, c in self._crc.items()
                     if p in mapping}
        self._free = list(range(len(live) + 1, self.num_pages))
        for pages in page_lists:
            pages[:] = [mapping[p] for p in pages]
        return mapping
