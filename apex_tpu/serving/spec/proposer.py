"""Draft-token proposers: the policy half of speculative decoding.

A proposer guesses the next ``k`` tokens of a request's greedy stream
so the engine can score all of them in ONE verify launch
(:func:`~apex_tpu.ops.flash_decode` at ``q_len = k + 1``) instead of
one decode step per token.  Being a *guess* is the whole contract: the
verify-accept step (:mod:`apex_tpu.serving.spec.verify`) keeps exactly
the longest prefix the model itself would have produced, so a bad
proposer costs throughput, never correctness — and an EMPTY draft is
always legal (the engine falls back to plain decode).

:class:`NgramProposer` is the self-speculative baseline (no draft
model, no device work): a per-request suffix cache maps recent n-grams
of the request's own token history to where they last occurred, and
the draft is the continuation that followed — greedy decoding is
highly repetitive (loops, boilerplate, copied spans), which is exactly
the regime where "what followed this phrase last time" is a strong
guess.  Lookup is O(ngram_n) dict probes per boundary; indexing is
incremental (each committed token is indexed once), which is what
keeps :meth:`NgramProposer.propose` on the engine's hot path
(``HOT_PATH_FUNCTIONS``) safe.

The :class:`Proposer` protocol deliberately leaves room for a small
draft *model* later: ``propose`` sees only host-side token history and
returns host-side ints, so a device-backed proposer slots in without
touching the verify step.
"""

from __future__ import annotations

from typing import Dict, List, Protocol, Sequence, Tuple, runtime_checkable


@runtime_checkable
class Proposer(Protocol):
    """What the engine needs from a draft source.

    ``propose(rid, context, k)`` returns up to ``k`` draft tokens for
    the request whose committed history (prompt + generated) is
    ``context`` — an empty list means "no guess", and the engine runs
    a plain decode step for that request.  ``context`` is append-only
    for a live rid (preemption keeps tokens; only retirement ends a
    history), which is what makes incremental caching sound.

    ``observe(drafted, accepted)`` is the per-boundary feedback signal
    (aggregate counts, post-verify); ``release(rid)`` drops any
    per-request state at retirement.
    """

    def propose(self, rid: int, context: Sequence[int],
                k: int) -> List[int]: ...

    def observe(self, drafted: int, accepted: int) -> None: ...

    def release(self, rid: int) -> None: ...


class NgramProposer:
    """Suffix-cache self-speculative proposer.

    Per request, every n-gram (n = ``ngram_n`` down to 1) of the
    committed token history is indexed to the position RIGHT AFTER its
    most recent occurrence; ``propose`` looks up the current suffix,
    longest n first, and drafts the continuation that followed it.  A
    continuation that runs off the end of history keeps reading from
    the draft itself (self-referential unrolling), so a period-p cycle
    proposes the full ``k`` tokens, not just the p that exist verbatim.

    Deterministic by construction — latest occurrence wins, no
    randomness — so a seeded trace served through a spec engine
    replays bit-identically (``seed`` is accepted for protocol
    uniformity with future sampled proposers and recorded, unused).
    The cache is derived purely from the request's committed tokens:
    after a preemption (tokens kept) it is still valid, and after an
    engine ``restore``/``recover`` a fresh proposer rebuilds it from
    the context on first use — draft state never needs checkpointing.
    """

    def __init__(self, ngram_n: int = 3, seed: int = 0):
        if ngram_n < 1:
            raise ValueError("ngram_n must be >= 1")
        self.ngram_n = int(ngram_n)
        self.seed = int(seed)
        # rid -> {ngram tuple: continuation start}, and how many tokens
        # of the rid's history have been indexed (grams ending at the
        # final token are indexed on the NEXT call, once a continuation
        # exists to point at)
        self._index: Dict[int, Dict[Tuple[int, ...], int]] = {}
        self._indexed: Dict[int, int] = {}
        self._tail: Dict[int, int] = {}   # last indexed token, per rid
        self.drafted = 0
        self.accepted = 0

    def _reindex(self, rid: int, context: Sequence[int]) -> Dict:
        idx = self._index.setdefault(rid, {})
        done = self._indexed.get(rid, 0)
        # a rid reused with a DIFFERENT history (fresh engine, same
        # proposer) breaks the append-only invariant — stale grams
        # would propose phantom tokens, or point past the new end and
        # crash the self-referential unroll.  An incremental cursor
        # always sits at most at len-1, so done >= len means the
        # history shrank; the tail-token probe catches same-or-longer
        # replacements (review-found off-by-one: done == len slipped
        # the old `>` check — pinned)
        if done and (done >= len(context)
                     or context[done - 1] != self._tail.get(rid)):
            idx.clear()
            done = 0
        # index grams ENDING at t for t in [done, len-1): continuation
        # = t + 1 must exist, or the lookup would match the suffix
        # itself and propose nothing
        for t in range(done, len(context) - 1):
            for n in range(1, self.ngram_n + 1):
                if t + 1 >= n:
                    idx[tuple(context[t + 1 - n:t + 1])] = t + 1
        done = max(done, len(context) - 1)
        self._indexed[rid] = done
        if done:
            self._tail[rid] = int(context[done - 1])
        return idx

    def propose(self, rid: int, context: Sequence[int],
                k: int) -> List[int]:
        if k <= 0 or len(context) < 2:
            return []
        idx = self._reindex(rid, context)
        L = len(context)
        for n in range(min(self.ngram_n, L - 1), 0, -1):
            start = idx.get(tuple(context[L - n:L]))
            if start is None or start >= L:
                # start >= L can only come from a stale index that
                # slipped the reuse guard — never draft from it
                continue
            out: List[int] = []
            while len(out) < k:
                q = start + len(out)
                # past the end of committed history the draft continues
                # from itself — q - L always lands inside `out` because
                # start < L
                out.append(int(context[q]) if q < L else out[q - L])
            return out
        return []

    def observe(self, drafted: int, accepted: int) -> None:
        self.drafted += int(drafted)
        self.accepted += int(accepted)

    def release(self, rid: int) -> None:
        self._index.pop(rid, None)
        self._indexed.pop(rid, None)
        self._tail.pop(rid, None)

    @property
    def acceptance_rate(self) -> float:
        """Accepted over drafted, lifetime (0.0 before any draft)."""
        return self.accepted / self.drafted if self.drafted else 0.0
