"""Verify-accept policy: exact greedy acceptance over one verify launch.

The engine scores a request's ``j``-token draft with ONE
``flash_decode`` call at ``q_len = k + 1`` (query rows = the last
committed token plus the draft); the model's greedy argmax at row
``i`` is the token it would have produced after consuming the draft
prefix ``d_1..d_i``.  :func:`commit_tokens` turns those argmax rows
into the committed continuation:

* **longest matching prefix** — accept ``d_1..d_a`` where ``a`` is the
  largest count with ``d_i == argmax[i-1]`` for every ``i <= a``;
* **the bonus token** — ``argmax[a]`` is the model's own next token
  after the accepted prefix (the "+1": even a fully rejected draft
  commits one real token, so a speculative boundary NEVER produces
  less than a plain decode step);
* **exact acceptance ⇒ bitwise streams** — every committed token is
  either a draft token the model's argmax endorsed or the argmax
  itself, which is precisely the token-by-token greedy sequence; the
  proposer can only change HOW MANY tokens commit per boundary, never
  WHICH tokens (the docs/serving.md contract — and the honesty note:
  this argument is exclusive to greedy argmax; *sampled* acceptance
  (Leviathan-style rejection sampling) preserves the distribution, not
  the realized stream, and would re-scope the bitwise claim);
* **stream-edge truncation** — the commit stops early at ``eos_id`` or
  the request's remaining ``max_new_tokens`` budget, exactly where
  sequential decoding would have stopped.

The function also reports how many DRAFT tokens survived into the
commit (``n_draft_kv``): their K/V was written by the verify launch
and stays valid, while rejected rows are rolled back by the caller via
plain ``kv_len``/page accounting (stale K/V past ``kv_len`` is
unreachable by the decode mask and overwritten when the sequence grows
back into those slots).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


def commit_tokens(draft: Sequence[int], model_argmax: Sequence[int], *,
                  eos_id: Optional[int], remaining: int,
                  ) -> Tuple[List[int], int, int]:
    """Resolve one verify launch for one request.

    ``draft``: the ``j`` proposed tokens.  ``model_argmax``: ``j + 1``
    greedy ids for query rows ``[t_last, d_1..d_j]`` (``argmax[i]`` =
    the model's next token after ``d_1..d_i``).  ``remaining``: tokens
    the request may still generate (``max_new_tokens`` minus generated
    so far, >= 1 by the caller's contract — done requests retire
    before the decode boundary).

    Returns ``(committed, n_draft_kv, n_accepted)``: the tokens to
    append to the stream, how many of them are draft tokens whose K/V
    is already in the pool (the caller sets ``kv_len += n_draft_kv``
    — the bonus token's K/V is appended at the NEXT boundary, same as
    a plain decode step's), and the raw accepted-prefix length (the
    proposer-quality signal, pre-truncation).
    """
    j = len(draft)
    if len(model_argmax) != j + 1:
        raise ValueError(
            f"verify returned {len(model_argmax)} argmax rows for a "
            f"{j}-token draft (want {j + 1})")
    if remaining < 1:
        raise ValueError("commit_tokens on a request with no budget")
    a = 0
    while a < j and int(draft[a]) == int(model_argmax[a]):
        a += 1
    committed: List[int] = []
    for t in list(draft[:a]) + [model_argmax[a]]:
        committed.append(int(t))
        if len(committed) >= remaining:
            break
        if eos_id is not None and int(t) == eos_id:
            break
    # how many APPENDED tokens are draft rows (K/V already in pool):
    # all of them unless truncation cut before the bonus
    n_draft_kv = min(len(committed), a)
    return committed, n_draft_kv, a
