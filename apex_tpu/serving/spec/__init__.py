"""Speculative decoding + chunked prefill (ISSUE 12): the draft–verify
subsystem over the r8 serving engine.

The kernel half always existed — :func:`~apex_tpu.ops.flash_decode`
passes its parity sweep at ``q_len > 1`` — this package is the policy
half, split the same way the rest of the repo wraps fast kernels in
host-side policy:

* :mod:`~apex_tpu.serving.spec.proposer` — pluggable draft sources
  (:class:`Proposer` protocol; :class:`NgramProposer` is the
  suffix-cache self-speculative baseline);
* :mod:`~apex_tpu.serving.spec.verify` — the exact greedy
  verify-accept rule (:func:`commit_tokens`): longest matching prefix
  plus the model's bonus token, so speculation changes throughput,
  never the token stream;
* :class:`SpecConfig` — the engine-facing knob bundle: draft width
  ``k`` (the verify launch is ONE compiled executable at
  ``q_len = k + 1``), the proposer, and the chunked-prefill width
  (long prefills split into fixed chunks that interleave with decode
  boundaries instead of monopolizing them).

See docs/serving.md "Speculative decoding" and "Chunked prefill".
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from apex_tpu.serving.spec.proposer import (  # noqa: F401
    NgramProposer,
    Proposer,
)
from apex_tpu.serving.spec.verify import commit_tokens  # noqa: F401


@dataclasses.dataclass
class SpecConfig:
    """Speculation/chunking knobs for :class:`~apex_tpu.serving.
    engine.ServingEngine`.

    ``k`` — max draft tokens per request per decode boundary; the
    verify executable is compiled once at ``q_len = k + 1`` (``k = 0``
    disables speculation, e.g. a chunked-prefill-only engine).
    ``proposer`` — any :class:`Proposer`; None builds a default
    :class:`NgramProposer` (per-engine, so engines never share cache
    state).  ``chunk_size`` — chunked-prefill width in tokens (None
    disables chunking; contexts <= chunk_size still take the
    whole-row prefill path).
    """

    k: int = 4
    proposer: Optional[Proposer] = None
    chunk_size: Optional[int] = None

    def __post_init__(self):
        if self.k < 0:
            raise ValueError("SpecConfig.k must be >= 0")
        if self.chunk_size is not None and self.chunk_size < 1:
            raise ValueError("SpecConfig.chunk_size must be >= 1")
        if self.k == 0 and self.chunk_size is None:
            raise ValueError(
                "SpecConfig with k=0 and no chunk_size enables nothing "
                "— pass spec=None instead")


__all__ = ["SpecConfig", "Proposer", "NgramProposer", "commit_tokens"]
