"""GPT-style decoder with a paged KV cache — the serving engine's
model half.

Two entry points mirror the two phases of continuous batching:

* :meth:`PagedDecoder.prefill` — the ADMISSION path.  A fixed-width
  packed token row with segment ids (the PR 5 varlen packed path:
  cross-segment tiles are masked in-kernel and skipped by the
  block-skip index on TPU) — one fixed-shape forward per call, no
  recompiles.  The row format carries ANY number of segments, but the
  engine feeds ONE request per row: a multi-segment row is not
  offset-invariant at the last ulp (the attention contraction's
  reduction grouping depends on where a segment starts), which would
  break the engine's bitwise batched-vs-sequential contract — see
  ``engine.py`` "The isolation contract".  It returns per-layer K/V
  for every packed position; the engine scatters them into the page
  pool.
* :meth:`PagedDecoder.decode` — the STEADY-STATE path.  One token per
  running request: append the token's K/V into its current page, then
  attend over the request's page list via
  :func:`~apex_tpu.ops.flash_decode` (the r8 decode route).  Batch
  width is fixed at the engine's ``max_batch`` with idle rows masked,
  so this too is one compiled step for the whole serving lifetime.
* :meth:`PagedDecoder.extend` — the MULTI-TOKEN cache-extension path
  (ISSUE 12): ``q`` tokens per request through ONE
  :func:`~apex_tpu.ops.flash_decode` call at ``q_len = q``.  Both
  halves of the draft–verify subsystem are this method under two
  fixed shapes: speculative VERIFY (``[max_batch, k + 1]`` — the last
  committed token plus the draft, all scored in one launch) and
  CHUNKED PREFILL (``[1, chunk_size]`` — one chunk of a long context
  against the pages already filled by earlier chunks).  Rows are
  front-padded so the valid tokens are always the LAST rows of the
  window — that is what keeps ``flash_decode``'s causal alignment
  (query row i sees columns ``[0, kv_len - q_len + i]``) exact for
  partial drafts/chunks without a second mask operand.  K/V write
  targets are HOST-computed ``(page, offset)`` arrays (the same idiom
  as ``PagedKVCache.write_tokens``), so padding rows scatter into the
  scratch page instead of a live slot.

Per-row independence is a hard contract: every op in ``decode`` is
row-wise (embedding lookup, layer norm, per-row matmuls, paged
attention over the row's own page list), which is what makes batched
continuous decoding produce bit-identical tokens to one-request-at-a-
time decoding — the scheduler composes batches freely without
perturbing anyone's output.

r17 adds two orthogonal execution modes, both threaded through the
same three methods:

* **Tensor parallelism** (``tp_axis=...``): the methods are written to
  run INSIDE ``shard_map`` over a mesh axis, Megatron-style — wqkv/w1
  column-sharded (each shard owns a head slice; see
  :func:`shard_params_tp` for the wqkv column reorder that keeps the
  in-method ``jnp.split`` correct), wo/w2 row-sharded, embeddings and
  layer norms replicated.  The head count is derived from the LOCAL
  shard shapes, the paged pool shards on its head axis, and each
  block contributes its partial residual via ONE ``lax.psum`` — the
  only collectives on the decode hot path (pinned by the HLO
  contract registry).  Note batched==sequential stays bitwise WITHIN
  a tp config (same executable, same reduction grouping); tp=1 vs
  tp=2 outputs differ at the last ulp like any re-grouped reduction.
* **Quantized pool** (``k_scale``/``v_scale`` given): appends
  quantize-on-write (:func:`~apex_tpu.serving.kv_cache.
  quantize_tokens` — per-(token, head) scales, order-independent) and
  reads dequantize-in-kernel via ``flash_decode``'s scale operands.
  Scales shard on their head axis exactly like the pool, so the two
  modes compose with no extra collectives.

The parameter layout is a plain pytree (:func:`init_params`) with tied
embeddings; fp32 by default (the serving tests pin bitwise claims),
bf16 for TPU throughput via ``ServingModelConfig(dtype=...)``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops import flash_attention, flash_decode
from apex_tpu.serving.kv_cache import quantize_tokens


def quant_qmax(dtype) -> float:
    """qmax for a quantized pool's code dtype (int8 -> 127, fp8 e4m3
    -> 448) — lets the model derive the grid from the pool it is
    handed instead of carrying a second config knob."""
    if np.dtype(dtype) == np.dtype(np.int8):
        return 127.0
    return 448.0


def shard_params_tp(params, tp: int):
    """Reorder each layer's fused ``wqkv`` [h, 3h] into SHARD-MAJOR
    column blocks ``[q_0|k_0|v_0 | q_1|k_1|v_1 | ...]`` so that
    column-sharding it over ``tp`` devices hands shard j exactly its
    head slice of all three projections — the in-method
    ``jnp.split(qkv, 3, -1)`` then works unchanged on the local block.
    Plain column sharding of the unreordered fusion would give shard 0
    a slab of pure-q columns instead.  Returns a NEW pytree (host-side
    numpy reorder, done once at engine init); ``tp=1`` returns the
    params untouched."""
    if tp == 1:
        return params
    out = dict(params)
    out["layers"] = []
    for layer in params["layers"]:
        w = np.asarray(layer["wqkv"])
        h = w.shape[0]
        if h % tp:
            raise ValueError(f"hidden_size {h} not divisible by tp={tp}")
        wq, wk, wv = np.split(w, 3, axis=1)
        blocks = []
        for j in range(tp):
            sl = slice(j * h // tp, (j + 1) * h // tp)
            blocks += [wq[:, sl], wk[:, sl], wv[:, sl]]
        new = dict(layer)
        new["wqkv"] = jnp.asarray(np.concatenate(blocks, axis=1),
                                  w.dtype)
        out["layers"].append(new)
    return out


@dataclasses.dataclass(frozen=True)
class ServingModelConfig:
    """Decoder geometry.  ``max_position`` bounds the learned position
    table — admission must reject requests that could outgrow it."""

    vocab_size: int = 256
    hidden_size: int = 64
    num_heads: int = 4
    num_layers: int = 2
    max_position: int = 512
    mlp_ratio: int = 4
    dtype: object = jnp.float32

    @property
    def head_dim(self) -> int:
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide by num_heads")
        return self.hidden_size // self.num_heads


def init_params(cfg: ServingModelConfig, seed: int = 0):
    """Deterministic parameter pytree (scaled-normal init, tied LM
    head = embedding transpose)."""
    keys = jax.random.split(jax.random.PRNGKey(seed),
                            2 + 4 * cfg.num_layers)
    h, r = cfg.hidden_size, cfg.mlp_ratio
    dt = cfg.dtype

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dt)

    params = {
        "embed": norm(keys[0], (cfg.vocab_size, h), h),
        "pos": norm(keys[1], (cfg.max_position, h), h),
        "ln_f": {"g": jnp.ones((h,), dt), "b": jnp.zeros((h,), dt)},
        "layers": [],
    }
    for i in range(cfg.num_layers):
        k = keys[2 + 4 * i: 6 + 4 * i]
        params["layers"].append({
            "ln1": {"g": jnp.ones((h,), dt), "b": jnp.zeros((h,), dt)},
            "wqkv": norm(k[0], (h, 3 * h), h),
            "wo": norm(k[1], (h, h), h),
            "ln2": {"g": jnp.ones((h,), dt), "b": jnp.zeros((h,), dt)},
            "w1": norm(k[2], (h, r * h), h),
            "w2": norm(k[3], (r * h, h), r * h),
        })
    return params


def _ln(x, p):
    m = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - m), axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(var + 1e-5) * p["g"] + p["b"]


def _mlp(x, layer):
    return jax.nn.gelu(x @ layer["w1"]) @ layer["w2"]


class PagedDecoder:
    """The decoder model over the cache layouts the engine owns (the
    engine holds params/pool; this class is pure functions of them)."""

    def __init__(self, cfg: ServingModelConfig):
        self.cfg = cfg

    # -- admission: packed varlen prefill --------------------------------

    def prefill(self, params, tokens: jnp.ndarray, seg: jnp.ndarray,
                positions: jnp.ndarray,
                last_index: Optional[jnp.ndarray] = None,
                *, tp_axis: Optional[str] = None,
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """tokens/seg/positions ``[1, S]`` (one packed row; seg 0 =
        padding, real segments 1..n; positions restart per segment).
        Returns (logits, k, v ``[L, 1, S, H, D]``) — K/V for every
        packed position, for the engine to scatter into pages.

        ``last_index`` (traced int scalar, so the compiled shape never
        changes): compute logits ``[1, 1, vocab]`` for that single
        position only.  Admission needs exactly one next-token
        distribution (the last context position) — projecting all S
        rows through the LM head would put an S×hidden×vocab matmul on
        the TTFT-critical path for one useful row.  ``None`` returns
        the full ``[1, S, vocab]`` logits (teacher-forcing/scoring
        use).

        ``tp_axis``: run as the per-shard body under ``shard_map`` —
        the local wqkv block carries this shard's heads (the returned
        k/v are the LOCAL head slice) and each block's residual is
        one ``psum``."""
        cfg = self.cfg
        hd = cfg.head_dim
        x = params["embed"][tokens] + params["pos"][positions]
        ks, vs = [], []
        for layer in params["layers"]:
            hdn = _ln(x, layer["ln1"])
            qkv = hdn @ layer["wqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            b, s = q.shape[:2]
            nh = k.shape[-1] // hd  # LOCAL heads (H/tp under shard_map)
            q4 = q.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
            k4 = k.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
            v4 = v.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
            ctx = flash_attention(q4, k4, v4, causal=True,
                                  segment_ids=seg)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, -1)
            attn = ctx @ layer["wo"]
            if tp_axis is not None:
                attn = jax.lax.psum(attn, tp_axis)
            x = x + attn
            mlp = _mlp(_ln(x, layer["ln2"]), layer)
            if tp_axis is not None:
                mlp = jax.lax.psum(mlp, tp_axis)
            x = x + mlp
            ks.append(k.reshape(b, s, nh, hd))
            vs.append(v.reshape(b, s, nh, hd))
        x = _ln(x, params["ln_f"])
        if last_index is not None:
            x = jax.lax.dynamic_slice_in_dim(
                x, jnp.asarray(last_index, jnp.int32), 1, axis=1)
        logits = x @ params["embed"].T
        return logits, jnp.stack(ks), jnp.stack(vs)

    # -- steady state: paged decode --------------------------------------

    def decode(self, params, k_pool, v_pool, tokens: jnp.ndarray,
               positions: jnp.ndarray, page_table: jnp.ndarray,
               kv_len: jnp.ndarray, *,
               k_scale: Optional[jnp.ndarray] = None,
               v_scale: Optional[jnp.ndarray] = None,
               tp_axis: Optional[str] = None):
        """One decode step for a fixed-width batch.

        ``tokens``/``positions`` ``[b]``: each row's newest token and
        its 0-based sequence position; ``kv_len = positions + 1`` (the
        flash_decode contract: the count includes the query token,
        whose K/V this step appends).  ``page_table`` ``[b, p_max]``.
        Idle rows carry position 0 / kv_len 1 / an all-scratch page
        row; their writes land in scratch page 0 and their outputs are
        discarded by the engine.  Returns (logits ``[b, vocab]``,
        k_pool', v_pool') — or, with ``k_scale``/``v_scale`` (the
        quantized pool's [L, n_pages, ps, H] fp32 scale planes), a
        5-tuple appending the updated scale planes: the append
        quantizes on write and ``flash_decode`` dequantizes on read.
        ``tp_axis``: per-shard body under ``shard_map`` (local head
        slice of pool and scales, one ``psum`` per block)."""
        cfg = self.cfg
        hd = cfg.head_dim
        page_size = k_pool.shape[2]
        quantized = k_scale is not None
        qmax = quant_qmax(k_pool.dtype) if quantized else None
        x = params["embed"][tokens] + params["pos"][positions]  # [b, h]
        page_slot = positions // page_size
        page_idx = jnp.take_along_axis(
            page_table, page_slot[:, None], axis=1)[:, 0]
        offset = positions % page_size
        for li, layer in enumerate(params["layers"]):
            hdn = _ln(x, layer["ln1"])
            qkv = hdn @ layer["wqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            b = q.shape[0]
            nh = k.shape[-1] // hd  # LOCAL heads (H/tp under shard_map)
            k_new, v_new = k.reshape(b, nh, hd), v.reshape(b, nh, hd)
            if quantized:
                k_new, k_s = quantize_tokens(k_new, k_pool.dtype, qmax)
                v_new, v_s = quantize_tokens(v_new, v_pool.dtype, qmax)
                k_scale = k_scale.at[li, page_idx, offset].set(k_s)
                v_scale = v_scale.at[li, page_idx, offset].set(v_s)
            k_pool = k_pool.at[li, page_idx, offset].set(k_new)
            v_pool = v_pool.at[li, page_idx, offset].set(v_new)
            q4 = q.reshape(b, 1, nh, hd).transpose(0, 2, 1, 3)
            ctx = flash_decode(
                q4, k_pool[li], v_pool[li], page_table, kv_len,
                k_scale=k_scale[li] if quantized else None,
                v_scale=v_scale[li] if quantized else None)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(b, -1)
            attn = ctx @ layer["wo"]
            if tp_axis is not None:
                attn = jax.lax.psum(attn, tp_axis)
            x = x + attn
            mlp = _mlp(_ln(x, layer["ln2"]), layer)
            if tp_axis is not None:
                mlp = jax.lax.psum(mlp, tp_axis)
            x = x + mlp
        logits = _ln(x, params["ln_f"]) @ params["embed"].T
        if quantized:
            return logits, k_pool, v_pool, k_scale, v_scale
        return logits, k_pool, v_pool

    # -- draft–verify / chunked prefill: multi-token extension -----------

    def extend(self, params, k_pool, v_pool, tokens: jnp.ndarray,
               positions: jnp.ndarray, write_pages: jnp.ndarray,
               write_offsets: jnp.ndarray, page_table: jnp.ndarray,
               kv_len: jnp.ndarray, *, last_only: bool = False,
               k_scale: Optional[jnp.ndarray] = None,
               v_scale: Optional[jnp.ndarray] = None,
               tp_axis: Optional[str] = None):
        """Append ``q`` tokens per row to the paged cache and score
        them in one :func:`~apex_tpu.ops.flash_decode` launch.

        ``tokens``/``positions`` ``[b, q]``: each row's newest tokens,
        FRONT-padded — the valid tokens must be the LAST rows, because
        flash_decode's causal rule (row i sees columns
        ``[0, kv_len - q_len + i]``) anchors the query window to the
        END of the ``kv_len``-token cache.  ``write_pages``/
        ``write_offsets`` ``[b, q]``: host-computed scatter targets for
        each row's K/V (padding rows point at scratch page 0, so a
        partial draft/chunk never dirties a live slot).  ``kv_len``
        ``[b]``: valid tokens INCLUDING the q-window's real rows — it
        may be SMALLER than ``q`` (a whole sequence shorter than the
        fixed window): flash_decode's empty-window rule returns exact
        zeros for rows whose causal window is empty, and the caller
        discards pad-row outputs either way (idle rows pass
        ``kv_len = q``).  ``page_table`` ``[b, p_max]``.

        ``last_only`` (static): project only the final row through the
        LM head — the chunked-prefill shape, where one next-token
        distribution is wanted and front-padding pins the chunk's last
        valid token to row ``q - 1``.  ``k_scale``/``v_scale`` and
        ``tp_axis``: as in :meth:`decode` (quantize-on-write appends /
        per-shard ``shard_map`` body).  Returns (logits
        ``[b, q, vocab]`` or ``[b, 1, vocab]``, k_pool', v_pool'[,
        k_scale', v_scale']).
        """
        cfg = self.cfg
        hd = cfg.head_dim
        b, q = tokens.shape
        quantized = k_scale is not None
        qmax = quant_qmax(k_pool.dtype) if quantized else None
        x = params["embed"][tokens] + params["pos"][positions]  # [b, q, h]
        for li, layer in enumerate(params["layers"]):
            hdn = _ln(x, layer["ln1"])
            qkv = hdn @ layer["wqkv"]
            qh, kh, vh = jnp.split(qkv, 3, axis=-1)
            nh = kh.shape[-1] // hd  # LOCAL heads (H/tp under shard_map)
            k_new = kh.reshape(b, q, nh, hd)
            v_new = vh.reshape(b, q, nh, hd)
            if quantized:
                k_new, k_s = quantize_tokens(k_new, k_pool.dtype, qmax)
                v_new, v_s = quantize_tokens(v_new, v_pool.dtype, qmax)
                k_scale = k_scale.at[li, write_pages,
                                     write_offsets].set(k_s)
                v_scale = v_scale.at[li, write_pages,
                                     write_offsets].set(v_s)
            k_pool = k_pool.at[li, write_pages, write_offsets].set(k_new)
            v_pool = v_pool.at[li, write_pages, write_offsets].set(v_new)
            q4 = qh.reshape(b, q, nh, hd).transpose(0, 2, 1, 3)
            ctx = flash_decode(
                q4, k_pool[li], v_pool[li], page_table, kv_len,
                k_scale=k_scale[li] if quantized else None,
                v_scale=v_scale[li] if quantized else None)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(b, q, -1)
            attn = ctx @ layer["wo"]
            if tp_axis is not None:
                attn = jax.lax.psum(attn, tp_axis)
            x = x + attn
            mlp = _mlp(_ln(x, layer["ln2"]), layer)
            if tp_axis is not None:
                mlp = jax.lax.psum(mlp, tp_axis)
            x = x + mlp
        x = _ln(x, params["ln_f"])
        if last_only:
            x = x[:, -1:, :]
        logits = x @ params["embed"].T
        if quantized:
            return logits, k_pool, v_pool, k_scale, v_scale
        return logits, k_pool, v_pool
