"""Continuous-batching scheduler: host-side admission / growth /
preemption / retirement policy.

Static-batching serving (pad every request to the longest, decode until
ALL finish) wastes most of the chip on retired-or-absent rows; the
continuous-batching answer (Orca/vLLM lineage) re-forms the batch
BETWEEN decode steps: finished requests leave immediately, waiting
requests join whenever a batch slot, prefill-token budget, and KV pages
are available.  This module is the pure-python policy half — it owns
request lifecycles and the page accounting, and never touches device
state (the :class:`~apex_tpu.serving.engine.ServingEngine` turns its
decisions into prefill/decode calls).

Policy (all deterministic — FIFO queues, lowest-first page allocation —
so a seeded arrival trace replays bit-identically):

* **admission**: FIFO over the waiting queue while (a) a batch slot is
  open, (b) this step's prefill-token budget has room for the
  request's context, and (c) the pool can supply its context pages.
  ``prefill_budget`` plays two roles: per REQUEST it is the fixed
  prefill row width (``submit`` rejects contexts that could outgrow
  it), and per STEP it caps the total prefill tokens admitted between
  two decode steps — each admission is its own fixed-width launch (the
  engine's isolation contract), so the step cap is not a packing
  constraint but head-of-line-latency control: admitting unbounded
  prefill work in one step would stall every running request's next
  token.  First failure stops admission for this step (no out-of-order
  admission — fairness over packing efficiency).
* **growth**: before each decode step every running request crossing a
  page boundary gets one page.
* **preemption**: when growth (or nothing-running admission) finds the
  pool empty, the MOST-RECENTLY-admitted running request is evicted —
  its pages are freed, its generated-so-far TOKENS are kept, and it
  rejoins the FRONT of the waiting queue; on re-admission its context
  (prompt + generated) is re-prefilled, deterministically regenerating
  its KV from the kept tokens, so preemption is invisible in the
  output stream (pinned token-for-token by
  ``test_preemption_is_output_invisible``; the regenerated KV is the
  same computation, not byte-for-byte the same buffers —
  docs/serving.md "Preemption").
* **retirement**: EOS or ``max_new_tokens`` reached → pages freed (and
  immediately reusable), terminal state recorded.

Resilience policy (ISSUE 10 — docs/serving.md "Failure semantics"):

* **deadlines**: a request may carry ``deadline_s`` (seconds after
  arrival by which it must FINISH).  :meth:`expire_deadlines` sheds
  queued requests that can no longer meet it (``now + min_service_s``
  already past the deadline — the SLO-aware part: shedding *before*
  expiry refuses work that would only burn pool pages to miss anyway)
  and retires in-flight expirations with a ``timeout`` status and
  immediate page free.
* **bounded queue**: ``max_queue`` caps the waiting queue; ``submit``
  raises :class:`QueueFullError` instead of growing without bound
  under overload (the engine converts it into an explicit
  ``request_reject`` event — load is refused loudly, never absorbed
  into an hours-deep queue every entry of which will time out).
* **anti-livelock aging**: evict-newest preemption skips requests that
  have already been preempted ``preempt_cap`` times — a long request
  under sustained short-request pressure is hit at most ``preempt_cap``
  times and then becomes senior to fresh admissions, so it provably
  completes (pinned by the livelock regression test).  When EVERY
  running request is at the cap the plain newest is evicted anyway
  (progress must never deadlock on the aging rule).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional

from apex_tpu.serving.kv_cache import (
    PagedKVCache,
    PagePoolExhausted,
    PrefixIndex,
)

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"


class QueueFullError(RuntimeError):
    """The bounded submit queue is full — the overload reject signal,
    not an error in the request itself (a retry later may succeed).
    The engine converts it into a ``request_reject`` telemetry event
    and a ``rejected`` terminal state."""


@dataclasses.dataclass
class Request:
    """One serving request and its runtime state."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    arrival_t: float = 0.0
    # completion deadline, seconds after arrival (None = no SLO).
    # Stored relative so serve()'s arrival rebase moves it too.
    deadline_s: Optional[float] = None
    # runtime
    state: str = WAITING
    generated: List[int] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    kv_len: int = 0               # tokens whose K/V sit in the pool
    # chunked-prefill cursor (ISSUE 12): tokens of the admission
    # context already computed into pages; None = not mid-chunk (the
    # whole-row path, or prefill complete).  DELIBERATELY not part of
    # any checkpoint: chunk progress is rebuildable by deterministic
    # re-prefill, so preemption/restore reset it to start over (the
    # same contract that keeps KV pages out of engine snapshots).
    prefill_pos: Optional[int] = None
    # r17 prefix sharing: True when the CURRENT admission covered a
    # context prefix with shared pages (reset on preemption — a
    # re-admission does its own lookup).  Telemetry-visible as the
    # request_admit event's ``prefix_hit`` bool.
    prefix_hit: bool = False
    preemptions: int = 0
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    # r19 shipping-aware SLO accounting: when the first sampled token
    # became STREAMABLE — equal to first_token_t on a colocated path,
    # but a disaggregated request's first token is not client-visible
    # until its KV pages land on the decode replica, so adopt_prefilled
    # stamps adoption time here and the kv_ship wall below.  TTFT is
    # measured against stream_t; the ship wall moves into TTFT (where
    # the SLO feels it), not TPOT.
    stream_t: Optional[float] = None
    ship_s: float = 0.0
    finish_t: Optional[float] = None
    finish_reason: Optional[str] = None

    # memoized `context` backing store (not part of the request state:
    # excluded from repr and from any comparison semantics)
    _ctx: Optional[List[int]] = dataclasses.field(
        default=None, repr=False, compare=False)
    _ctx_key: tuple = dataclasses.field(
        default=(-1, -1), repr=False, compare=False)

    @property
    def deadline_t(self) -> Optional[float]:
        """Absolute deadline on the engine clock (None = no SLO)."""
        if self.deadline_s is None:
            return None
        return self.arrival_t + self.deadline_s

    @property
    def context(self) -> List[int]:
        """Tokens whose K/V must be cached at (re-)admission: the
        prompt plus everything generated before a preemption.

        Memoized on ``(len(prompt), len(generated))``: both lists are
        append-only for a live request, so the concat is rebuilt only
        when tokens were committed — a chunked prefill (context frozen
        across its chunks) and the per-boundary proposer lookup read
        the SAME list instead of copying O(seq_len) per access
        (review-found; the hot-path cost was O(C²/chunk) over a long
        prefill).  Callers must treat the returned list as read-only.
        """
        key = (len(self.prompt), len(self.generated))
        if self._ctx_key != key:
            self._ctx = self.prompt + self.generated
            self._ctx_key = key
        return self._ctx

    @property
    def seq_len(self) -> int:
        return len(self.prompt) + len(self.generated)

    @property
    def done(self) -> bool:
        if self.eos_id is not None and self.generated \
                and self.generated[-1] == self.eos_id:
            return True
        return len(self.generated) >= self.max_new_tokens


class ContinuousBatchingScheduler:
    """Admission/growth/preemption/retirement over a shared page pool."""

    def __init__(self, cache: PagedKVCache, *, max_batch: int,
                 prefill_budget: int, max_position: int,
                 max_queue: Optional[int] = None,
                 preempt_cap: Optional[int] = 4,
                 chunk_size: Optional[int] = None,
                 prefix_index: Optional[PrefixIndex] = None):
        if chunk_size is not None and chunk_size > prefill_budget:
            raise ValueError(
                f"chunk_size {chunk_size} exceeds the per-step prefill "
                f"budget {prefill_budget} — a chunk could never launch")
        if prefix_index is not None and chunk_size is None:
            # a prefix hit admits the request mid-context — its suffix
            # prefills through the fixed [1, chunk_size] extend
            # executable, attending over the shared pages.  Without a
            # chunk path there is no way to compute a suffix's K/V
            # against an existing cache.
            raise ValueError(
                "prefix sharing requires chunked prefill "
                "(chunk_size=None)")
        self.cache = cache
        self.max_batch = max_batch
        self.prefill_budget = prefill_budget
        self.max_position = max_position
        # overload policy (ISSUE 10): bounded submit queue + aging cap
        # on evict-newest preemption (None disables either)
        self.max_queue = max_queue
        self.preempt_cap = preempt_cap
        # chunked prefill (ISSUE 12): contexts longer than chunk_size
        # admit into chunked prefill — one fixed-width chunk per
        # boundary under the shared prefill-token budget — instead of
        # one whole-row launch (None = every prefill is whole-row)
        self.chunk_size = chunk_size
        # prefix sharing (r17): admission consults the index for a
        # shared prefix (pages refcounted, prefill skipped for the
        # covered tokens); allocation pressure evicts index entries
        # BEFORE preempting a running request — dropping warm-cache
        # opportunism is always cheaper than killing live work
        self.prefix_index = prefix_index
        self.waiting: Deque[Request] = deque()
        self.running: List[Request] = []   # admission order
        self.finished: List[Request] = []

    # -- intake ----------------------------------------------------------

    def check_servable(self, req: Request) -> None:
        """Raise ``ValueError`` if ``req`` could NEVER be served by
        THIS scheduler's geometry (so capacity failures later are
        always transient).  Shared by :meth:`submit` and the engine's
        ``restore`` — a snapshot taken on a differently-configured
        engine (e.g. chunked → chunk-less) must fail here, loudly,
        instead of queueing a request admission can never take."""
        worst = len(req.prompt) + req.max_new_tokens
        if worst > self.max_position:
            raise ValueError(
                f"request {req.rid}: prompt+max_new {worst} exceeds "
                f"max_position {self.max_position}")
        if self.cache.pages_needed(worst) > \
                self.cache.max_pages_per_request:
            raise ValueError(
                f"request {req.rid}: needs up to "
                f"{self.cache.pages_needed(worst)} pages > "
                f"max_pages_per_request "
                f"{self.cache.max_pages_per_request}")
        if worst > self.prefill_budget and self.chunk_size is None:
            # the PREEMPTION contract needs the whole worst-case
            # context (prompt + everything it may generate) to fit the
            # fixed prefill row width, or an evicted request could
            # never be re-admitted.  A CHUNKED scheduler lifts this
            # bound (ISSUE 12): any context past chunk_size — original
            # or regrown by re-admission — prefills through the fixed
            # [1, chunk_size] executable, so the row width no longer
            # caps request size (max_position still does, above)
            raise ValueError(
                f"request {req.rid}: prompt+max_new {worst} exceeds "
                f"prefill budget {self.prefill_budget}")

    def submit(self, req: Request) -> None:
        """Queue a request; rejects up front what could NEVER be
        served (:meth:`check_servable`)."""
        self.check_servable(req)
        if self.max_queue is not None and len(self.waiting) >= self.max_queue:
            # overload: refuse loudly rather than queue work that will
            # only time out.  Only NEW submissions are bounded —
            # preemption requeues bypass submit() by design (an evicted
            # request must always be able to come back)
            raise QueueFullError(
                f"request {req.rid}: submit queue full "
                f"({len(self.waiting)}/{self.max_queue})")
        self.waiting.append(req)

    # -- admission -------------------------------------------------------

    def admit(self) -> List[Request]:
        """Admit FIFO-eligible requests for this step (each gets its
        own prefill launch; the shared ``prefill_budget`` decrement
        caps this STEP's total prefill work — see the module
        docstring).  Returns the admitted list (pages allocated, state
        RUNNING); never raises on capacity — a full pool just admits
        fewer.  The whole-row-only entry point: a chunked scheduler
        must go through :meth:`schedule_prefill`, which also plans the
        in-flight chunk launches this call would silently drop."""
        if self.chunk_size is not None:
            raise RuntimeError(
                "admit() on a chunked scheduler — use schedule_prefill()")
        _, admitted = self.schedule_prefill()
        return admitted

    def schedule_prefill(self) -> tuple:
        """Plan this boundary's prefill work under the shared
        prefill-token budget; returns ``(chunks, admitted)``.

        ``chunks`` — ``(request, start, n_tokens)`` launches, in
        execution order: first one chunk for every in-flight chunked
        request (admission order — a long prefill advances by AT MOST
        one chunk per boundary, which is the head-of-line-latency
        point: decode steps interleave between its chunks instead of
        stalling behind a whole-row launch), then the first chunk of
        each newly admitted long request.  ``admitted`` — requests
        admitted this boundary (pages for the FULL context reserved at
        admission — the ISSUE 10 reserve-at-admit invariant is
        unchanged; a context at or under ``chunk_size``, or any
        context when chunking is off, takes the whole-row prefill
        path and appears only in ``admitted``).

        Budget accounting: an in-flight chunk consumes its token
        count; a whole-row admission consumes its context length; a
        chunked admission consumes ``chunk_size`` (its first chunk —
        the rest of the context is later boundaries' budget, which is
        exactly how a 2k-token arrival stops monopolizing a boundary).
        First failure stops each phase (no out-of-order work — the
        FIFO fairness rule).
        """
        budget = self.prefill_budget
        chunks: List[tuple] = []
        if self.chunk_size is not None:
            for req in self.running:
                if req.prefill_pos is None:
                    continue
                # seq_len == len(context) during prefill, without
                # materializing the prompt+generated list per boundary
                n = min(self.chunk_size, req.seq_len - req.prefill_pos)
                if n > budget:
                    break
                chunks.append((req, req.prefill_pos, n))
                budget -= n
        admitted: List[Request] = []
        while self.waiting and \
                len(self.running) + len(admitted) < self.max_batch:
            req = self.waiting[0]
            ctx = req.seq_len
            # prefix sharing: the longest indexed prefix of the context
            # rides in on shared pages; only the suffix [m, ctx) is
            # prefilled, always through the chunk path (it must attend
            # over the shared pages)
            m, shared = (0, [])
            if self.prefix_index is not None:
                m, shared = self.prefix_index.lookup(req.context)
            if m:
                chunked = True
                need = min(self.chunk_size, ctx - m)
            else:
                chunked = (self.chunk_size is not None
                           and ctx > self.chunk_size)
                need = self.chunk_size if chunked else ctx
            if need > budget:
                break
            if shared:
                # pin the shared pages FIRST: index eviction inside
                # the allocation retry below may otherwise free them
                self.cache.share(shared)
            try:
                fresh = self._allocate_evicting(
                    self.cache.pages_needed(ctx) - len(shared), req.rid)
            except PagePoolExhausted:
                if shared:
                    self.cache.free(shared)
                if not self.running and not admitted:
                    # nothing to preempt and nothing in flight: the
                    # waiting request's context alone exceeds the pool
                    # minus other waiters' leavings — surface it, this
                    # is a sizing bug, not a transient
                    raise
                break
            pages = list(shared) + fresh
            if m % self.cache.page_size:
                # the hit ends MID-page: the suffix's first chunk will
                # write position m into the last shared page, so it is
                # copy-on-write'd HERE, at admission, where exhaustion
                # is still an ordinary stop-admitting event — a COW
                # failing mid-launch would have no clean rollback
                try:
                    self._privatize(pages, m // self.cache.page_size,
                                    req.rid)
                except PagePoolExhausted:
                    self.cache.free(pages)
                    if not self.running and not admitted:
                        raise
                    break
            self.waiting.popleft()
            req.pages = pages
            req.state = RUNNING
            req.prefix_hit = bool(m)
            budget -= need
            if chunked:
                req.prefill_pos = m
                chunks.append((req, m, min(self.chunk_size, ctx - m)))
            admitted.append(req)
        self.running.extend(admitted)
        return chunks, admitted

    def _allocate_evicting(self, n: int, rid: int) -> List[int]:
        """:meth:`PagedKVCache.allocate`, but allocation pressure
        first evicts prefix-index entries (oldest-first) — an index
        entry is a reuse OPPORTUNITY, never a reason to fail an
        admission or preempt live work.  Only entries whose pages drop
        to refcount zero actually return capacity; entries still read
        by live requests release nothing (their pages stay live), so
        the loop is bounded by the index size."""
        while True:
            try:
                return self.cache.allocate(n, rid)
            except PagePoolExhausted:
                if self.prefix_index is None or \
                        len(self.prefix_index) == 0:
                    raise
                self.prefix_index.evict_one()

    def _privatize(self, pages: List[int], idx: int, rid: int) -> None:
        """Copy-on-write ``pages[idx]`` in place for ``rid``, evicting
        prefix-index entries under allocation pressure (the same relief
        order as :meth:`_allocate_evicting`).  If an eviction drops the
        page's OTHER reader, the caller's pin is the only reference
        left and no copy is needed — the loop re-checks sharedness
        before each attempt."""
        while self.cache.is_shared(pages[idx]):
            try:
                pages[idx] = self.cache.cow(pages[idx], rid)
                return
            except PagePoolExhausted:
                if self.prefix_index is None or \
                        len(self.prefix_index) == 0:
                    raise
                self.prefix_index.evict_one()

    # -- growth / preemption ---------------------------------------------

    def preempt_one(self) -> Optional[Request]:
        """Evict the most-recently-admitted running request: free its
        pages, keep its tokens, requeue it at the FRONT of the waiting
        queue.  Returns the victim (or None if nothing runs).

        Anti-livelock aging (ISSUE 10): a request already preempted
        ``preempt_cap`` times is skipped — the victim is the newest
        request still UNDER the cap, so sustained pressure cannot hit
        the same request forever.  If every running request is capped
        the plain newest is evicted anyway: the aging rule bounds
        repeat victimization, it must never deadlock progress."""
        if not self.running:
            return None
        victim = None
        if self.preempt_cap is not None:
            for req in reversed(self.running):
                if req.preemptions < self.preempt_cap:
                    victim = req
                    break
        if victim is None:
            victim = self.running[-1]
        self.running.remove(victim)
        self.cache.free(victim.pages)
        victim.pages = []
        victim.kv_len = 0
        # a mid-chunk victim restarts its chunked prefill on
        # re-admission — chunk progress is rebuildable, like KV
        victim.prefill_pos = None
        # re-admission does its own prefix lookup
        victim.prefix_hit = False
        victim.state = WAITING
        victim.preemptions += 1
        self.waiting.appendleft(victim)
        return victim

    def ensure_decode_capacity(self, extra: Optional[Dict[int, int]]
                               = None) -> List[Request]:
        """Give every running request the page its next token needs,
        preempting from the back of the batch when the pool runs dry.
        Returns the requests preempted (possibly including ones that
        had already grown — eviction strictly follows admission
        order).

        ``extra`` (ISSUE 12): per-rid additional token headroom this
        boundary — a speculative verify launch writes its draft's K/V
        at positions ``seq_len .. seq_len + draft - 1``, so drafted
        requests grow to ``pages_needed(seq_len + draft)`` here and
        the engine rolls the rejected tail back afterwards
        (:meth:`PagedKVCache.free_tail`)."""
        evicted: List[Request] = []
        for req in list(self.running):
            if req not in self.running:
                continue  # evicted while growing an earlier request
            while req in self.running:
                want = req.seq_len + (extra.get(req.rid, 0)
                                      if extra else 0)
                need_pages = self.cache.pages_needed(want)
                if len(req.pages) >= need_pages:
                    break
                try:
                    req.pages.extend(
                        self.cache.allocate(
                            need_pages - len(req.pages), req.rid))
                except PagePoolExhausted:
                    # pressure relief order: drop a prefix-index entry
                    # first (reuse opportunism is cheaper than killing
                    # live work), preempt only once the index is dry
                    if self.prefix_index is not None and \
                            len(self.prefix_index):
                        self.prefix_index.evict_one()
                        continue
                    # the victim can be ``req`` itself (it is the
                    # newest admission left): then the loop's membership
                    # check ends its growth and it waits its turn
                    victim = self.preempt_one()
                    assert victim is not None  # self.running non-empty
                    evicted.append(victim)
        return evicted

    # -- deadlines -------------------------------------------------------

    def expire_deadlines(self, now: float, *, min_service_s: float = 0.0
                         ) -> tuple:
        """Enforce per-request deadlines; returns ``(shed, timed_out)``.

        *Shed* — queued requests that can no longer meet their deadline
        (``now + min_service_s`` at or past it; ``min_service_s`` is
        the caller's floor estimate of remaining service time, 0.0 =
        shed only once expired).  They finish with reason ``"shed"``
        without ever taking pool pages.

        *Timed out* — RUNNING requests whose deadline has passed:
        removed from the batch with reason ``"timeout"`` and their
        pages freed immediately (reusable by the very next admission —
        the timeout-storm no-leak test pins this).
        """
        shed: List[Request] = []
        timed_out: List[Request] = []
        for req in list(self.waiting):
            dt = req.deadline_t
            if dt is not None and now + min_service_s >= dt:
                self.waiting.remove(req)
                req.state = FINISHED
                req.finish_t = now
                req.finish_reason = "shed"
                self.finished.append(req)
                shed.append(req)
        for req in list(self.running):
            dt = req.deadline_t
            if req.done:
                # its last token was generated before the deadline
                # died — the request is COMPLETE, just not yet swept
                # by retire_finished (the engine retires right after
                # expiring); timing it out here would misreport a full
                # token stream as a timeout
                continue
            if dt is not None and now >= dt:
                self.running.remove(req)
                self.cache.free(req.pages)
                req.pages = []
                req.kv_len = 0
                req.prefill_pos = None
                req.state = FINISHED
                req.finish_t = now
                req.finish_reason = "timeout"
                self.finished.append(req)
                timed_out.append(req)
        return shed, timed_out

    # -- retirement ------------------------------------------------------

    def retire_finished(self, now: float) -> List[Request]:
        """Move done requests out of the batch and free their pages —
        the pages are reusable by the very next admission."""
        done = [r for r in self.running if r.done]
        for req in done:
            self.running.remove(req)
            self.cache.free(req.pages)
            req.pages = []
            req.state = FINISHED
            req.finish_t = now
            req.finish_reason = (
                "eos" if req.eos_id is not None and req.generated
                and req.generated[-1] == req.eos_id else "length")
            self.finished.append(req)
        return done

    @property
    def idle(self) -> bool:
        return not self.waiting and not self.running
