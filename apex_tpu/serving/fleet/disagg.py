"""Disaggregated prefill/decode serving (ISSUE 18 tentpole).

:class:`DisaggRouter` splits the fleet along the role axis: dedicated
PREFILL replicas (``role="prefill"``, engines built ``prefill_only``)
admit and chunk-prefill requests, then ship the finished KV pages to
DECODE replicas (``role="decode"``, engines built ``kv_import``) that
import the pages into their own pool and decode as if they had
prefilled locally.  ``mixed`` replicas can do either — a fleet of
only mixed replicas behaves exactly like the r16 router.

Everything rides the r18 transport seam, and the shipment protocol is
built for a lossy wire:

* **one transfer per request** — ``transfer_id = "t<rid>"``, N
  ``kv_page`` messages (one per page: base64 C-order page slices,
  quantized scale planes, per-page CRC stamped at export —
  :meth:`~apex_tpu.serving.kv_cache.PagedKVCache.export_page_bytes`)
  followed by one ``kv_commit`` carrying the request record.
* **idempotent + resumable** — the receiver
  (:class:`PageImporter`) buffers pages per transfer id, dedupes
  repeats (same page landing twice is a no-op), verifies each page's
  CRC host-side BEFORE buffering (a corrupted page answers
  ``crc_mismatch`` and is re-sent — NEVER adopted), and memoizes the
  commit reply so a duplicated/retried commit cannot double-admit.
  A commit that finds pages missing (dropped in flight) answers
  ``missing_pages`` and the sender re-ships exactly those — partial
  transfers resume, they never restart.
* **bounded retries, then graceful degradation** — transport
  timeouts/corruption cost ``kv_ship_retry`` + exponential round
  backoff (the PR 16 ``1 << attempts`` discipline); past the router's
  ``fault_retries`` budget the transfer FALLS BACK
  (``kv_ship_fallback``): the request record migrates to the decode
  replica over the ordinary migrate path and is re-prefilled LOCALLY
  there — deterministic re-prefill, the same machinery every
  recovery/migration path uses.  Zero dropped requests by
  construction, under any fault pattern.

The decode replica then owns the request end-to-end; its token stream
is bitwise the colocated control's whichever path admitted it: a
shipped page lands verbatim (codes + scales included), a fallback
re-prefill is deterministic, and decode rows are independent of batch
composition.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from apex_tpu.serving.engine import AdmissionRefused
from apex_tpu.serving.fleet.replica import ReplicaProxy
from apex_tpu.serving.fleet.router import FleetRouter
from apex_tpu.serving.fleet.transport import (TransportCorruption,
                                              TransportTimeout)
from apex_tpu.serving.kv_cache import (PagePoolExhausted,
                                       verify_page_payload)


class PageImporter:
    """Decode-replica receiver for KV page shipments: the ``kv_page``
    / ``kv_commit`` handlers one replica registers on the transport.

    State is per-transfer-id: ``_buf`` accumulates verified pages
    (order-independent — reordered deliveries reassemble by
    ``page_index``), ``_done`` memoizes commit replies so the
    at-least-once wire cannot admit a request twice (a retried commit
    after a delayed-but-processed one returns the memoized success)."""

    def __init__(self, rep: ReplicaProxy, transport=None):
        self.rep = rep
        self.transport = transport
        self._buf: Dict[str, Dict[int, Dict[str, Any]]] = {}
        self._done: Dict[str, Dict[str, Any]] = {}
        #: transfer_id -> engine-clock time the FIRST page arrived —
        #: the kv_import span opens at first byte, not at commit
        self._t0: Dict[str, float] = {}

    def on_page(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        tid = payload["transfer_id"]
        if tid in self._done:
            # a page re-sent after its transfer already committed
            # (delayed reply → sender retry): the transfer is over
            return {"ok": True}
        self._t0.setdefault(tid, self.rep.engine.clock())
        buf = self._buf.setdefault(tid, {})
        idx = int(payload["page_index"])
        if idx in buf:
            return {"ok": True}   # duplicate page: a no-op
        if not verify_page_payload(payload["data"]):
            # corrupted in flight — refuse it so the sender re-ships;
            # the damaged bytes never touch this replica's pool
            return {"ok": False, "reason": "crc_mismatch",
                    "page_index": idx}
        buf[idx] = payload["data"]
        return {"ok": True}

    def on_commit(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        tid = payload["transfer_id"]
        if tid in self._done:
            return self._done[tid]
        rid = int(payload["record"]["rid"])
        if self.rep.find_request(rid) is not None:
            # the request is already live here — an earlier commit
            # landed (its reply was lost), or a fallback/fence
            # migration raced the retry.  rid-level idempotency, same
            # as the migrate handler: adopt nothing twice.
            reply = {"ok": True, "rid": rid}
            self._done[tid] = reply
            self._buf.pop(tid, None)
            self._t0.pop(tid, None)
            return reply
        n_pages = int(payload["n_pages"])
        buf = self._buf.get(tid, {})
        missing = [i for i in range(n_pages) if i not in buf]
        if missing:
            # dropped/reordered pages: the sender re-ships exactly
            # these — the transfer resumes, it never restarts
            return {"ok": False, "reason": "missing_pages",
                    "missing": missing}
        pages_payload = [buf[i] for i in range(n_pages)]
        try:
            self.rep.engine.adopt_prefilled(
                payload["record"], pages_payload,
                int(payload["kv_len"]))
        except (AdmissionRefused, PagePoolExhausted) as e:
            # capacity, not corruption: the sender backs off and
            # retries into the SAME buffered pages
            return {"ok": False, "reason": "no_capacity",
                    "detail": str(e)}
        reply = {"ok": True, "rid": int(payload["record"]["rid"])}
        self._done[tid] = reply
        del self._buf[tid]
        self._emit_import_span(tid, rid)
        return reply

    def _emit_import_span(self, tid: str, rid: int) -> None:
        """The receiver half of the ship pair: ``kv_import`` spans
        first-page-arrival → adopted, parented on the LITERAL sender
        ``kv_ship`` span id carried in the wire trace context — the
        causal join survives retries/duplicates because the context
        rides the envelope verbatim."""
        ctx = (self.transport.current_trace
               if self.transport is not None else None) or {}
        now = self.rep.engine.clock()
        self.rep.engine._emit(
            "span", rid=rid,
            span_id=f"{rid}:kv_import:{int(ctx.get('attempt', 0))}",
            parent_id=ctx.get("span_id"), kind="kv_import",
            t_start=self._t0.pop(tid, now), t_end=now,
            replica=self.rep.name, attempt=int(ctx.get("attempt", 0)))


class _Transfer:
    """Sender-side state for one in-flight shipment."""

    def __init__(self, rid: int, src: str, dst: str,
                 record: Dict[str, Any],
                 pages: List[Dict[str, Any]], kv_len: int):
        self.rid = rid
        self.src = src
        self.dst = dst
        self.record = record
        self.pages = pages
        self.kv_len = kv_len
        self.transfer_id = f"t{rid}"
        self.acked: set = set()
        self.attempts = 0
        self.backoff_until = 0
        # tracing state for the CURRENT drive attempt (one kv_ship
        # span per attempt; ids carry the destination + attempt no)
        self.span_t0: Optional[float] = None
        self.span_id: Optional[str] = None
        self.span_attempt = 0


class DisaggRouter(FleetRouter):
    """Fleet router with the prefill/decode role split.

    Intake routes to prefill-capable replicas (``prefill``/``mixed``)
    with the usual least-loaded + prefix-affinity policy; every fleet
    round, finished prefills are exported off prefill replicas and
    shipped — pages then commit — to the least-loaded decode-capable
    replica, where they enter the decode batch directly
    (:meth:`~apex_tpu.serving.engine.ServingEngine.adopt_prefilled`).
    Migration never targets prefill-only replicas (they cannot decode
    adopted work).  Requires at least one prefill-capable AND one
    decode-capable replica; a fleet of only mixed replicas is legal
    and behaves exactly like the base router plus a (trivially
    colocated) ship path.
    """

    def __init__(self, replicas: Sequence[ReplicaProxy], **kwargs):
        super().__init__(replicas, **kwargs)
        if not [r for r in self.replicas
                if r.role in ("prefill", "mixed")]:
            raise ValueError("disaggregated fleet needs at least one "
                             "prefill-capable (prefill/mixed) replica")
        if not [r for r in self.replicas
                if r.role in ("decode", "mixed")]:
            raise ValueError("disaggregated fleet needs at least one "
                             "decode-capable (decode/mixed) replica")
        #: rid -> in-flight shipment
        self._transfers: Dict[int, _Transfer] = {}
        self._importers: Dict[str, PageImporter] = {}
        for rep in self.replicas:
            if rep.role in ("decode", "mixed"):
                imp = PageImporter(rep, transport=self.transport)
                self._importers[rep.name] = imp
                self.transport.register(rep.name, "kv_page", imp.on_page)
                self.transport.register(rep.name, "kv_commit",
                                        imp.on_commit)

    # -- placement overrides ----------------------------------------------

    def route(self, prompt=None, roles=None) -> ReplicaProxy:
        """Intake goes to prefill-capable replicas unless the caller
        already restricted the roles (migration targeting passes its
        own set)."""
        if roles is None:
            roles = ("prefill", "mixed")
        return super().route(prompt=prompt, roles=roles)

    def _migration_targets(self, source: ReplicaProxy
                           ) -> List[ReplicaProxy]:
        """Healthy peers that can DECODE — migrating a live request
        onto a prefill-only replica would strand it (those engines
        never run decode rows)."""
        return [r for r in self.replicas
                if r.healthy and r.name != source.name
                and r.role != "prefill"]

    # -- the disaggregated round ------------------------------------------

    def step(self) -> None:
        super().step()
        self._pump_disagg()

    def _fleet_busy(self) -> bool:
        # a transfer sitting out its backoff is live work even when
        # every engine is momentarily idle — run() must not drain
        # under it
        return super()._fleet_busy() or bool(self._transfers)

    def _decode_target(self) -> Optional[ReplicaProxy]:
        """Least-loaded healthy decode-capable replica, counting
        IN-FLIGHT transfers against their destination (one pending
        shipment weighs one live request) — without it a burst of
        simultaneous prefill completions would all target the replica
        whose load_score hasn't moved yet and serialize behind its
        batch capacity."""
        pool = [r for r in self.replicas
                if r.healthy and r.role in ("decode", "mixed")]
        if not pool:
            return None
        pending: Dict[str, int] = {}
        for t in self._transfers.values():
            pending[t.dst] = pending.get(t.dst, 0) + 1
        return min(pool, key=lambda r: (r.load_score()
                                        + pending.get(r.name, 0), r.name))

    def _pump_disagg(self) -> None:
        """Export every finished prefill on a prefill replica into a
        transfer, then drive all in-flight transfers past their
        backoff.  Done-at-prefill requests (budget of one token / EOS
        on the first sample) retire locally — nothing to ship."""
        for rep in self.replicas:
            if not rep.healthy or rep.role != "prefill":
                continue
            ready = [r for r in list(rep.engine.sched.running)
                     if r.prefill_pos is None and r.generated
                     and not r.done and r.rid not in self._transfers]
            for req in ready:
                dst = self._decode_target()
                if dst is None:
                    raise RuntimeError(
                        "no healthy decode-capable replica to ship "
                        f"rid {req.rid} to — a disaggregated fleet "
                        "cannot serve without its decode tier")
                record, pages, kv_len = rep.engine.export_request(req.rid)
                self._transfers[req.rid] = _Transfer(
                    req.rid, rep.name, dst.name, record, pages, kv_len)
        for rid in sorted(self._transfers):
            t = self._transfers.get(rid)
            if t is None:
                continue
            if not self._by_name[t.dst].healthy:
                # the destination fenced mid-transfer: retarget to a
                # live decode replica and re-ship from scratch (the
                # old buffer died with the fence; acked means nothing
                # against a different pool)
                dst = self._decode_target()
                if dst is None:
                    raise RuntimeError(
                        "no healthy decode-capable replica to "
                        f"retarget rid {t.rid}'s transfer to")
                now = self._clock()
                self._by_name[t.src].engine._emit(
                    "span", rid=t.rid,
                    span_id=(f"{t.rid}:kv_ship:{t.dst}"
                             f":retarget:{self.round}"),
                    parent_id=t.record.get("export_span"),
                    kind="kv_ship", t_start=now, t_end=now,
                    replica=t.src, outcome="retarget")
                t.dst = dst.name
                t.acked = set()
            if t.backoff_until > self.round:
                continue
            self._drive(t)

    def _drive(self, t: _Transfer) -> None:
        """One attempt at completing transfer ``t``: ship every
        unacked page, then commit.  Any transport fault, missing-page
        report, or capacity refusal costs one attempt + backoff; a
        per-page CRC refusal re-ships that page immediately (bounded
        by the same attempt budget); past the budget the transfer
        falls back to local prefill on the decode replica."""
        n = len(t.pages)
        t.span_attempt = t.attempts + 1
        t.span_t0 = self._clock()
        t.span_id = f"{t.rid}:kv_ship:{t.dst}:{t.span_attempt}"
        # the trace context every wire message of this attempt carries
        # (envelope-level, outside the payload CRC): the receiver
        # parents its kv_import span on the literal span id
        ctx = {"rid": t.rid, "span_id": t.span_id,
               "attempt": t.span_attempt}
        try:
            for i in range(n):
                if i in t.acked:
                    continue
                reply = self.transport.call(
                    t.dst, "kv_page",
                    {"transfer_id": t.transfer_id, "page_index": i,
                     "n_pages": n, "data": t.pages[i]}, trace=ctx)
                retries = 0
                while not reply.get("ok"):
                    # corrupted in flight: the receiver refused the
                    # page (never adopted) — re-ship it clean
                    self._emit_retry(t, reason="crc_mismatch")
                    retries += 1
                    if retries > self.fault_retries:
                        self._fallback(t, reason="crc_mismatch")
                        return
                    reply = self.transport.call(
                        t.dst, "kv_page",
                        {"transfer_id": t.transfer_id, "page_index": i,
                         "n_pages": n, "data": t.pages[i]}, trace=ctx)
                t.acked.add(i)
            reply = self.transport.call(
                t.dst, "kv_commit",
                {"transfer_id": t.transfer_id, "record": t.record,
                 "kv_len": t.kv_len, "n_pages": n}, trace=ctx)
        except TransportTimeout:
            self._bump(t, reason="timeout")
            return
        except TransportCorruption:
            self._bump(t, reason="corrupt")
            return
        if reply.get("ok"):
            self._emit_ship_span(t, outcome="ok")
            req = self._by_name[t.dst].find_request(t.rid)
            self.handles[t.rid] = req
            self.placement[t.rid] = t.dst
            self._emit("kv_ship", rid=t.rid, from_replica=t.src,
                       to_replica=t.dst, pages=n,
                       payload_bytes=sum(
                           len(p["k"]) + len(p["v"])
                           + len(p.get("k_scale", ""))
                           + len(p.get("v_scale", ""))
                           for p in t.pages),
                       attempts=t.attempts)
            del self._transfers[t.rid]
            return
        if reply.get("reason") == "missing_pages":
            # reordered/lost pages the receiver never saw: resume the
            # transfer by re-shipping exactly those
            t.acked -= set(int(i) for i in reply["missing"])
            self._bump(t, reason="missing_pages")
            return
        self._bump(t, reason=str(reply.get("reason", "no_capacity")))

    def _bump(self, t: _Transfer, *, reason: str) -> None:
        t.attempts += 1
        if t.attempts > self.fault_retries:
            self._fallback(t, reason=reason)
            return
        self._emit_ship_span(t, outcome="retry", reason=reason)
        t.backoff_until = self.round + (1 << t.attempts)
        self._emit_retry(t, reason=reason,
                         backoff_rounds=t.backoff_until - self.round)

    def _emit_retry(self, t: _Transfer, *, reason: str,
                    **extra) -> None:
        self._emit("kv_ship_retry", rid=t.rid, from_replica=t.src,
                   to_replica=t.dst, attempt=t.attempts,
                   reason=reason, **extra)

    def _emit_ship_span(self, t: _Transfer, *, outcome: str,
                        reason: Optional[str] = None) -> None:
        """Close the CURRENT attempt's ``kv_ship`` span with a typed
        outcome — one span per drive attempt, parented on the
        sender's ``kv_export`` span (carried in the transfer record);
        retries/fallbacks/retargets are outcomes, not separate
        kinds."""
        if t.span_id is None:
            return
        ev: Dict[str, Any] = dict(
            rid=t.rid, span_id=t.span_id,
            parent_id=t.record.get("export_span"), kind="kv_ship",
            t_start=t.span_t0, t_end=self._clock(), replica=t.src,
            attempt=t.span_attempt, outcome=outcome)
        if reason is not None:
            ev["reason"] = reason
        self._by_name[t.src].engine._emit("span", **ev)

    def _fallback(self, t: _Transfer, *, reason: str) -> None:
        """Graceful degradation past the retry budget: the request
        record migrates to the decode replica over the ordinary
        (idempotent) migrate path and re-prefills LOCALLY there —
        slower, but the stream stays bitwise (deterministic
        re-prefill) and the request is never dropped.  If the commit
        actually landed and only its reply was lost, the migrate
        handler's rid-dedupe finds the request live and adopts
        nothing — the rebind below picks up the shipped copy."""
        self._emit_ship_span(t, outcome="fallback", reason=reason)
        self._emit("kv_ship_fallback", rid=t.rid, from_replica=t.src,
                   to_replica=t.dst, attempts=t.attempts, reason=reason)
        self._call_with_retry(
            t.dst, "migrate", {"records": [t.record]},
            trace=({"rid": t.rid, "span_id": t.span_id,
                    "attempt": t.span_attempt}
                   if t.span_id is not None else None))
        req = self._by_name[t.dst].find_request(t.rid)
        self.handles[t.rid] = req
        self.placement[t.rid] = t.dst
        del self._transfers[t.rid]
