"""The replica seam: one :class:`ReplicaProxy` per engine.

The router never touches :class:`~apex_tpu.serving.ServingEngine`
internals — everything it needs (placement signals, stepping, health,
snapshot/adopt, restart) goes through this proxy, which is in-process
today and the process/RPC boundary later.  Two consequences shape the
surface:

* every method speaks plain data (ints, floats, snapshot dicts) or
  raises a typed exception — nothing here would break across a wire;
* the fleet chaos hook lives HERE, not in the engine: ``KillReplica``
  / ``SlowReplica`` / ``BlackholeReplica`` model the *replica*
  failing (its process, its link), which is invisible to the engine
  inside it.  The serving fault hook (``engine.set_fault_hook``)
  keeps modeling the *device* failing.

Health checks are deterministic: :meth:`ReplicaProxy.ping` fires the
fleet fault point with a mutable ``{"latency_s": 0.0}`` payload that
injectors inflate; a latency past the budget raises
:class:`HealthCheckTimeout` without any real sleeping, so a
blackholed replica is detected in virtual time and chaos tests never
hang the suite.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from apex_tpu.serving.kv_cache import PagePoolCorruption

#: ReplicaProxy lifecycle states (the fence/backoff state machine is
#: documented in docs/serving.md "Fleet tier").
HEALTHY = "healthy"
DRAINING = "draining"
FENCED = "fenced"
RESTARTING = "restarting"


class ReplicaDead(RuntimeError):
    """An operation was routed to a fenced/restarting replica."""


class HealthCheckTimeout(RuntimeError):
    """A replica's health probe exceeded its latency budget."""


# -- fleet chaos hook (ISSUE 16) ---------------------------------------------
# The fleet twin of engine.set_fault_hook: the chaos tier installs an
# injector here to kill / slow / blackhole a named REPLICA at a fleet
# event ("step" before a proxy steps its engine, "ping" during a
# health probe — the ping payload is a mutable dict whose "latency_s"
# the injector inflates).  Production never sets it.

_FLEET_FAULT_HOOK: Optional[Callable[[str, str, Any], None]] = None


def set_fleet_fault_hook(hook: Optional[Callable[[str, str, Any], None]]):
    """Install (or clear) the fleet fault hook; returns the previous
    hook so context-manager injectors can chain/restore."""
    global _FLEET_FAULT_HOOK
    prev = _FLEET_FAULT_HOOK
    _FLEET_FAULT_HOOK = hook
    return prev


def _fleet_fault_point(event: str, replica: str, info: Any) -> None:
    if _FLEET_FAULT_HOOK is not None:
        _FLEET_FAULT_HOOK(event, replica, info)


class ReplicaProxy:
    """Router-facing handle on one serving engine.

    ``engine_factory`` is a zero-arg callable returning a fresh,
    un-warmed :class:`~apex_tpu.serving.ServingEngine`; the proxy owns
    the engine's lifecycle (construction, warmup, restart) so the
    router can treat "replica" as an opaque unit of capacity.  The
    factory is also the restart path: :meth:`restart` swaps in a
    brand-new engine, which is exactly what a process respawn will do
    at the RPC boundary.
    """

    def __init__(self, name: str, engine_factory, *, telemetry=None,
                 role: str = "mixed"):
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r} "
                             "(expected mixed / prefill / decode)")
        self.name = name
        self.engine_factory = engine_factory
        self.telemetry = telemetry
        #: r18 disaggregation role axis: "mixed" replicas do everything
        #: (the pre-r18 fleet); "prefill" replicas only admit +
        #: chunk-prefill (their engines are ``prefill_only``); "decode"
        #: replicas receive shipped pages (``kv_import``) and decode.
        #: The role is a PLACEMENT attribute — the proxy itself treats
        #: every engine identically.
        self.role = role
        self.engine = engine_factory()
        self.state = HEALTHY
        #: router-level retry budget consumed (engine-level recovery
        #: is counted separately by ``engine.recoveries``)
        self.fault_attempts = 0
        #: router round before which this replica is skipped (backoff)
        self.backoff_until = 0
        self.restarts = 0

    # -- lifecycle -------------------------------------------------------

    def warmup(self) -> float:
        return self.engine.warmup()

    def restart(self) -> float:
        """Replace the engine with a fresh factory build and warm it;
        the old engine's state is gone (the caller migrates/readmits
        requests around this — see ``rolling_restart``)."""
        self.state = RESTARTING
        self.engine = self.engine_factory()
        secs = self.engine.warmup()
        self.state = HEALTHY
        self.fault_attempts = 0
        self.backoff_until = 0
        self.restarts += 1
        return secs

    def fence(self) -> None:
        self.state = FENCED

    @property
    def healthy(self) -> bool:
        return self.state == HEALTHY

    # -- health ----------------------------------------------------------

    def ping(self, timeout_s: float) -> float:
        """Deterministic health probe: injectors inflate the probe's
        virtual latency through the fleet fault hook; past the budget
        the probe raises :class:`HealthCheckTimeout` (no real sleep —
        a blackholed replica reports ``inf`` and fails instantly)."""
        probe = {"latency_s": 0.0}
        _fleet_fault_point("ping", self.name, probe)
        latency = float(probe["latency_s"])
        if latency > timeout_s:
            raise HealthCheckTimeout(
                f"replica {self.name}: health probe {latency:.3f}s "
                f"exceeds budget {timeout_s:.3f}s")
        return latency

    # -- work ------------------------------------------------------------

    def step(self) -> None:
        """One engine step behind the replica fault point.  A fault
        injected here (or raised by the engine itself) first burns the
        ENGINE's recovery budget via its own ``_handle_fault`` path —
        only an exhausted/disabled engine lets the fault propagate to
        the router, which then spends its retry-with-backoff budget
        before fencing.  Two nested nets, each observable."""
        if self.state != HEALTHY:
            raise ReplicaDead(f"step on {self.state} replica {self.name}")
        from apex_tpu.resilience.chaos import DeviceLossError

        try:
            _fleet_fault_point("step", self.name, self.engine.steps)
            self.engine.step()
        except (DeviceLossError, PagePoolCorruption) as e:
            self.engine._handle_fault(e)

    # -- placement signals ----------------------------------------------

    @property
    def idle(self) -> bool:
        return self.engine.sched.idle

    def queue_depth(self) -> int:
        return len(self.engine.sched.waiting)

    def running(self) -> int:
        return len(self.engine.sched.running)

    def queue_headroom(self) -> Optional[int]:
        """Remaining bounded-queue slots (``None`` = unbounded)."""
        mq = self.engine.sched.max_queue
        if mq is None:
            return None
        return mq - len(self.engine.sched.waiting)

    def occupancy(self) -> float:
        """Page-pool occupancy in [0, 1] over the allocatable pool
        (page 0 is scratch, never allocatable)."""
        cache = self.engine.cache
        allocatable = max(1, cache.num_pages - 1)
        return cache.pages_used / allocatable

    def shed_count(self) -> int:
        """Requests this engine refused or dropped (rejects live on
        ``engine.rejected``; deadline sheds/timeouts retire with a
        timeout reason)."""
        timeouts = sum(1 for r in self.engine.sched.finished
                       if r.finish_reason in ("timeout", "shed"))
        return len(self.engine.rejected) + timeouts

    def load_score(self) -> float:
        """Least-loaded placement key: live request pressure plus pool
        occupancy (the fractional tiebreak between equally-queued
        replicas)."""
        return (self.queue_depth() + self.running()) + self.occupancy()

    # -- migration -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return self.engine.snapshot()

    def adopt(self, records: List[Dict[str, Any]]):
        return self.engine.adopt(records)

    def find_request(self, rid: int):
        """This replica's live :class:`Request` handle for ``rid``
        (running, waiting, or finished), or ``None``.  The rebinding
        step after a transport-mediated transfer: the wire carries
        records, not handles, so after a migrate/ship reply the router
        looks the adopted request up by rid to hand the caller a live
        handle."""
        rid = int(rid)
        for pool in (self.engine.sched.running,
                     self.engine.sched.waiting,
                     self.engine.sched.finished):
            for req in pool:
                if req.rid == rid:
                    return req
        return None
