"""The SLO-aware fleet router (ISSUE 16 tentpole).

:class:`FleetRouter` fronts N replicas behind one submit surface:

* **Placement** — :meth:`~FleetRouter.route` picks the least-loaded
  healthy replica with bounded-queue headroom (signals:
  queue depth + running batch + page-pool occupancy, all read through
  the :class:`~apex_tpu.serving.fleet.replica.ReplicaProxy` seam).
  When every bounded queue is full, the pick falls back to the
  least-loaded healthy replica so the ENGINE rejects loudly
  (``request_reject`` ``reason="queue_full"``) instead of the router
  inventing a second shedding policy.
* **SLO classes** — deadlines are existing per-request knobs; the
  router just assigns them per tenant class
  (:class:`SLOClass`), so SLO enforcement stays where it already
  works: the engine's shed/timeout machinery.
* **Fault handling, two nested nets** — an engine absorbs device
  faults up to its own ``max_recoveries``; only then does the fault
  propagate to the router, which retries the replica with exponential
  round backoff up to ``fault_retries`` before FENCING it: out of
  rotation, ``replica_fence`` emitted, live requests migrated.
* **Migration** — ``snapshot()`` →
  :func:`~apex_tpu.serving.fleet.migrate.plan_migration` → one
  transport ``migrate`` message per target (r18: the transport's
  serialize → deliver → deserialize pipeline IS the serializability
  pin the old inline JSON round-trip carried), adopted by an
  idempotent rid-deduping handler.  Atomic at both levels (plan
  refuses whole — with the full unplaceable list on
  ``migrate_refused`` — and adopt validates before mutating); every
  hop is a ``request_migrate`` event; zero silent drops.  Migrated
  streams are bitwise the unmigrated control's — KV is rebuilt by
  deterministic re-prefill, exactly the single-engine recovery
  contract.
* **Rolling restart** — :func:`rolling_restart` drains, migrates,
  restarts and readmits one replica at a time; a fleet of one
  readmits its own snapshot after the restart (nothing to migrate
  onto).
* **Autoscaling signal** — :func:`scale_hint` is a pure function of
  shed rate / occupancy / deadline attainment; the router only ever
  EMITS ``fleet_scale_hint`` (testable against recorded traces via
  :func:`scale_hint_from_events`) — acting on it is the operator's
  job.

The router owns the fleet-global rid namespace and the rid → handle
map (``handles``); a handle *is* a rid, which is what survives an RPC
boundary.  All replicas share ONE clock — per-replica clocks would
skew deadline math across a migration hop.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from apex_tpu.serving.fleet.migrate import (FleetCapacityError,
                                            plan_migration)
from apex_tpu.serving.fleet.replica import (FENCED, HealthCheckTimeout,
                                            ReplicaProxy)
from apex_tpu.serving.fleet.transport import (LocalTransport, Transport,
                                              TransportCorruption,
                                              TransportTimeout,
                                              register_error)
from apex_tpu.serving.kv_cache import PagePoolCorruption
from apex_tpu.serving.scheduler import Request

# the one replica-owned exception that legitimately crosses the
# transport as a typed error reply (a ping probe timing out on the
# REMOTE side must re-raise as itself on the router side)
register_error(HealthCheckTimeout)


@dataclass(frozen=True)
class SLOClass:
    """A tenant tier mapped onto existing per-request knobs: the
    router assigns ``deadline_s`` at submit; ``None`` = best effort
    (no deadline, shed last)."""

    name: str
    deadline_s: Optional[float] = None


def scale_hint(*, shed_rate: float, occupancy: float,
               deadline_hit_rate: Optional[float] = None) -> str:
    """The autoscaling SIGNAL (never an action): pure thresholds over
    the three pressure signals the serving tier already measures.
    Shedding or missed deadlines mean the fleet is refusing work it
    was asked to do — scale up; a near-idle pool with perfect SLO
    attainment is paying for capacity it does not use — scale down;
    anything between holds."""
    if shed_rate > 0.05 or occupancy > 0.85:
        return "scale_up"
    if deadline_hit_rate is not None and deadline_hit_rate < 0.90:
        return "scale_up"
    if shed_rate == 0.0 and occupancy < 0.25 and (
            deadline_hit_rate is None or deadline_hit_rate >= 0.99):
        return "scale_down"
    return "hold"


def scale_hint_from_events(events: Sequence[Dict[str, Any]]) -> str:
    """Derive the hint from a RECORDED telemetry stream (a list of
    schema-valid event dicts), so the policy is testable against
    traces without standing a fleet up.  Terminal outcomes =
    retires + rejects + timeouts; shed rate counts the refused/dropped
    share; occupancy averages ``decode_step`` pool pressure over the
    allocatable pool (page 0 is scratch)."""
    retires = [e for e in events if e.get("type") == "request_retire"]
    rejects = [e for e in events if e.get("type") == "request_reject"]
    timeouts = [e for e in events if e.get("type") == "request_timeout"]
    steps = [e for e in events if e.get("type") == "decode_step"]
    total = len(retires) + len(rejects) + len(timeouts)
    shed_rate = (len(rejects) + len(timeouts)) / max(1, total)
    occ = 0.0
    if steps:
        occ = sum(e["pool_used"] / max(1, e["pool_pages"] - 1)
                  for e in steps) / len(steps)
    hits = [e["deadline_hit"] for e in retires if "deadline_hit" in e]
    hit_rate = (sum(1 for h in hits if h) / len(hits)) if hits else None
    return scale_hint(shed_rate=shed_rate, occupancy=occ,
                      deadline_hit_rate=hit_rate)


class FleetRouter:
    """Route requests over ``replicas``
    (:class:`~apex_tpu.serving.fleet.replica.ReplicaProxy`), fencing
    and migrating around faults.  ``fault_retries`` is the
    router-level retry budget AFTER a replica's engine has exhausted
    its own recoveries; ``health_timeout_s`` is the deterministic ping
    latency budget; ``scale_hint_every`` emits ``fleet_scale_hint``
    every N fleet rounds (0 = never).  ``on_round`` fires once at the
    end of every fleet round — the virtual-clock injection point: all
    replicas step CONCURRENTLY in a real fleet, so a shared
    :class:`~apex_tpu.serving.engine.SimClock` (which ticks per
    engine step, i.e. N ticks per round) would charge N replicas N×
    the time of one; a router-ticked clock charges one round one
    tick regardless of fleet width (bench_fleet measures TTFT on
    exactly this)."""

    def __init__(self, replicas: Sequence[ReplicaProxy], *,
                 telemetry=None,
                 slo_classes: Sequence[SLOClass] = (),
                 fault_retries: int = 2,
                 health_timeout_s: float = 0.25,
                 scale_hint_every: int = 50,
                 on_round: Optional[Callable[[], None]] = None,
                 transport: Optional[Transport] = None):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.replicas: List[ReplicaProxy] = list(replicas)
        self._by_name = {r.name: r for r in self.replicas}
        self.telemetry = telemetry
        # r18: EVERY cross-replica payload — health pings, migration
        # snapshots, KV page shipments — goes through the transport
        # seam (serialize → deliver → deserialize, per-message ids).
        # Default is the plain in-process LocalTransport; tests wrap it
        # in ChaosTransport to lose/delay/duplicate/reorder/corrupt
        # messages in flight.
        self.transport = transport if transport is not None \
            else LocalTransport()
        for rep in self.replicas:
            self.transport.register(
                rep.name, "ping",
                lambda p, rep=rep:
                    {"latency_s": rep.ping(float(p["timeout_s"]))})
            self.transport.register(
                rep.name, "migrate",
                lambda p, rep=rep: self._migrate_handler(rep, p))
        self.slo_classes = {c.name: c for c in slo_classes}
        self.fault_retries = int(fault_retries)
        self.health_timeout_s = float(health_timeout_s)
        self.scale_hint_every = int(scale_hint_every)
        self.on_round = on_round
        #: fleet-global rid namespace — rid collisions across replicas
        #: would make migration ambiguous (pinned in adopt())
        self._next_rid = 0
        #: rid -> live Request handle; REBOUND on migration (the old
        #: engine's object is dead).  A handle is a rid — the only
        #: thing that survives an RPC boundary.
        self.handles: Dict[int, Request] = {}
        #: rid -> replica name (current placement)
        self.placement: Dict[int, str] = {}
        self.round = 0

    # -- lifecycle -------------------------------------------------------

    def warmup(self) -> float:
        return sum(rep.warmup() for rep in self.replicas)

    # -- intake ----------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               slo: Optional[str] = None,
               deadline_s: Optional[float] = None,
               arrival_t: Optional[float] = None) -> int:
        """Place one request on the fleet; returns its rid (THE
        handle).  ``slo`` names a registered :class:`SLOClass` whose
        deadline overrides ``deadline_s``; rejection semantics are the
        engine's (terminal ``rejected`` + ``request_reject`` event) —
        check ``handles[rid].finish_reason``."""
        if slo is not None:
            cls = self.slo_classes.get(slo)
            if cls is None:
                raise ValueError(
                    f"unknown SLO class {slo!r}; registered: "
                    f"{sorted(self.slo_classes)}")
            deadline_s = cls.deadline_s
        rep = self.route(prompt=prompt)
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid, prompt=list(prompt),
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      arrival_t=(rep.engine.clock() if arrival_t is None
                                 else arrival_t),
                      deadline_s=deadline_s)
        rep.engine.submit_request(req)
        self.handles[rid] = req
        self.placement[rid] = rep.name
        return rid

    def route(self, prompt: Optional[Sequence[int]] = None,
              roles: Optional[Sequence[str]] = None) -> ReplicaProxy:
        """Pick the least-loaded healthy replica, preferring ones with
        bounded-queue headroom; with every queue full the least-loaded
        healthy replica takes the submission and its engine rejects
        loudly (backpressure stays ONE policy, the engine's).  Raises
        when no replica is healthy — a dead fleet is not a routing
        decision.

        ``roles`` restricts the candidates to the named replica roles
        (the r18 disaggregation axis; ``None`` considers everyone).
        ``prompt`` enables PREFIX AFFINITY (r18 satellite): among the
        candidate pool, replicas whose local
        :class:`~apex_tpu.serving.kv_cache.PrefixIndex` already holds
        a usable prefix of the prompt are preferred — the deepest hit
        wins, least-loaded tiebreak — so repeated prompts land where
        their pages are already warm instead of re-prefilling cold on
        a less-loaded peer.  No index state is shipped or shared: the
        affinity reads each replica's existing local hit signal, and a
        fleet with no sharing enabled routes exactly as before."""
        healthy = [r for r in self.replicas if r.healthy]
        if roles is not None:
            healthy = [r for r in healthy if r.role in roles]
        if not healthy:
            raise RuntimeError(
                "no healthy replicas in the fleet" if roles is None else
                f"no healthy replica with role in {tuple(roles)}")
        with_room = [r for r in healthy
                     if r.queue_headroom() is None or r.queue_headroom() > 0]
        pool = with_room or healthy
        if prompt is not None:
            hits = {}
            for r in pool:
                idx = r.engine.prefix_index
                if idx is not None:
                    m, _ = idx.lookup(list(prompt))
                    if m > 0:
                        hits[r.name] = m
            if hits:
                best = max(hits.values())
                pool = [r for r in pool if hits.get(r.name) == best]
        return min(pool, key=lambda r: (r.load_score(), r.name))

    # -- health + fencing ------------------------------------------------

    def _health_check(self) -> None:
        """Probe every in-rotation replica THROUGH the transport; a
        probe timing out remotely, or the probe message itself lost /
        late / corrupted in flight, fences the replica on the spot and
        reroutes its live requests — an unreachable replica and an
        unhealthy one get the same treatment, because the router
        cannot tell them apart (and must not block finding out: the
        probe is virtual-latency, no sleep)."""
        for rep in self.replicas:
            if not rep.healthy:
                continue
            try:
                self.transport.call(rep.name, "ping",
                                    {"timeout_s": self.health_timeout_s})
            except HealthCheckTimeout:
                self._fence(rep, cause="health_check_timeout")
            except TransportTimeout:
                self._fence(rep, cause="transport_timeout")
            except TransportCorruption:
                self._fence(rep, cause="transport_corruption")

    def _clock(self) -> float:
        """The fleet's shared clock (all replicas share ONE clock by
        construction — see the module docstring), read through any
        replica's engine."""
        return self.replicas[0].engine.clock()

    def _call_with_retry(self, dst: str, msg_class: str,
                         payload: Dict[str, Any], *,
                         trace: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        """Transport call with the router's bounded retry budget:
        ``fault_retries + 1`` immediate attempts absorbing in-flight
        loss/corruption (each retry re-serializes, so a corrupted
        message goes out clean; the receiver's idempotency makes a
        delayed-but-processed message's retry harmless).  Exhaustion
        raises ``RuntimeError`` — control-plane operations like
        migration have no fallback tier, failing them loudly beats
        silently dropping requests."""
        last: Optional[Exception] = None
        for _ in range(self.fault_retries + 1):
            try:
                return self.transport.call(dst, msg_class, payload,
                                           trace=trace)
            except (TransportTimeout, TransportCorruption) as e:
                last = e
        raise RuntimeError(
            f"{msg_class} to {dst} failed after "
            f"{self.fault_retries + 1} attempts: {last}") from last

    @staticmethod
    def _migrate_handler(rep: ReplicaProxy,
                         payload: Dict[str, Any]) -> Dict[str, Any]:
        """Receiver side of a migration shipment: adopt the records
        this replica does NOT already hold.  The rid-dedupe makes the
        handler idempotent — a duplicated wire message, or a sender
        retry after a delayed-but-processed delivery, finds the rids
        live and adopts nothing twice."""
        records = payload["records"]
        fresh = [r for r in records
                 if rep.find_request(int(r["rid"])) is None]
        if fresh:
            rep.adopt(fresh)
        return {"ok": True,
                "adopted": [int(r["rid"]) for r in records]}

    def _fence(self, rep: ReplicaProxy, cause: str,
               migrate: bool = True) -> None:
        live = rep.queue_depth() + rep.running()
        rep.fence()
        self._emit("replica_fence", replica=rep.name, cause=cause,
                   live_requests=live, recoveries=rep.engine.recoveries,
                   fault_retries=rep.fault_attempts)
        # r19 flight recorder: a fence is a fault boundary — dump the
        # fenced replica's recent-event ring while the evidence is hot
        from apex_tpu.telemetry.tracing import maybe_dump_flight_record
        maybe_dump_flight_record(rep.engine.telemetry,
                                 f"replica_fence:{cause}",
                                 step=self.round)
        if migrate:
            self._migrate_requests(rep)

    def _migration_targets(self, source: ReplicaProxy
                           ) -> List[ReplicaProxy]:
        """Candidate adopters for ``source``'s live requests: healthy
        peers.  Overridable — the disaggregated router excludes
        prefill-only replicas, whose engines would queue migrated
        decode work forever."""
        return [r for r in self.replicas
                if r.healthy and r.name != source.name]

    def _migrate_requests(self, source: ReplicaProxy) -> List[Request]:
        """Move every live request off ``source`` onto healthy peers,
        THROUGH the transport (serialize → deliver → deserialize is
        now the serializability pin the old inline JSON round-trip
        carried; in-flight loss/corruption costs bounded immediate
        retries against the idempotent migrate handler).  The plan
        validates headroom + geometry before any adopt, and each adopt
        validates atomically again — a failure anywhere leaves every
        engine untouched and raises loudly; a REFUSED plan additionally
        emits ``migrate_refused`` with the full unplaceable list and
        the required-vs-available page counts, so operators can size
        capacity from the stream.  Handles are REBOUND to the adopting
        engine's request objects; token streams continue bitwise
        (deterministic re-prefill)."""
        records = source.snapshot()["requests"]
        if not records:
            return []
        targets = self._migration_targets(source)
        try:
            plan = plan_migration(records, targets)
        except FleetCapacityError as e:
            self._emit("migrate_refused", replica=source.name,
                       unplaceable=list(e.unplaceable),
                       requests=len(e.unplaceable),
                       pages_required=e.pages_required,
                       pages_available=e.pages_available)
            from apex_tpu.telemetry.tracing import \
                maybe_dump_flight_record
            maybe_dump_flight_record(self.telemetry, "migrate_refused",
                                     step=self.round)
            raise
        moved: List[Request] = []
        for name, recs in sorted(plan.items()):
            if not recs:
                continue
            self._call_with_retry(name, "migrate", {"records": recs})
            for rec in recs:
                req = self._by_name[name].find_request(int(rec["rid"]))
                self.handles[req.rid] = req
                self.placement[req.rid] = name
                self._emit("request_migrate", rid=req.rid,
                           from_replica=source.name, to_replica=name,
                           tokens_done=len(req.generated),
                           was_running=bool(rec["was_running"]))
                self._emit_hop_span(req.rid, source.name, name)
                moved.append(req)
        return moved

    def _emit_hop_span(self, rid: int, src: str, dst: str) -> None:
        """Point ``migrate_hop`` span on the fleet bus (r19).  Root
        level (no parent): a hop can move a QUEUED request whose
        admission spans never existed, so parenting on them would
        dangle; the trace CLI stitches hops to the rid's tree by
        trace id alone."""
        now = self._clock()
        self._emit("span", rid=rid,
                   span_id=f"{rid}:migrate_hop:{src}:{dst}:{self.round}",
                   kind="migrate_hop", t_start=now, t_end=now,
                   replica=src)

    # -- the fleet round -------------------------------------------------

    def step(self) -> None:
        """One fleet round: health-check everything, then step each
        in-rotation replica with work.  A propagated fault (the
        engine's own recovery budget is already spent by the time it
        reaches here) costs one retry: the replica sits out
        ``2^attempts`` rounds of backoff, and past ``fault_retries``
        it is fenced and drained."""
        from apex_tpu.resilience.chaos import DeviceLossError

        self.round += 1
        self._health_check()
        for rep in self.replicas:
            if not rep.healthy or rep.idle:
                continue
            if rep.backoff_until > self.round:
                continue
            try:
                rep.step()
            except (DeviceLossError, PagePoolCorruption) as e:
                rep.fault_attempts += 1
                if rep.fault_attempts > self.fault_retries:
                    self._fence(rep, cause=type(e).__name__)
                else:
                    rep.backoff_until = self.round + (1 << rep.fault_attempts)
        if self.on_round is not None:
            self.on_round()

    def _fleet_busy(self) -> bool:
        """Live work remains somewhere in the fleet (the
        :meth:`run` drain predicate).  Overridable: the disaggregated
        router also counts in-flight page transfers, which can be
        backing off while every engine is momentarily idle."""
        return any(r.healthy and not r.idle for r in self.replicas)

    def run(self, max_steps: int = 100_000) -> List[Request]:
        """Round until every in-rotation replica drains; returns the
        handles in rid order.  Non-drain raises — a backing-off
        replica still counts as live work, so the budget must cover
        backoff rounds too."""
        for _ in range(max_steps):
            if not self._fleet_busy():
                break
            self.step()
            if self.scale_hint_every and \
                    self.round % self.scale_hint_every == 0:
                self.emit_scale_hint()
        else:
            raise RuntimeError(
                f"fleet did not drain in {max_steps} rounds")
        for rep in self.replicas:
            if rep.healthy:
                rep.engine._retire(rep.engine.clock())
        return [self.handles[rid] for rid in sorted(self.handles)]

    # -- autoscaling signal ----------------------------------------------

    def signals(self) -> Dict[str, Any]:
        """Fleet-aggregate pressure signals over in-rotation replicas
        (the inputs to :func:`scale_hint`, also emitted verbatim on
        ``fleet_scale_hint`` so recorded traces can replay the
        decision)."""
        healthy = [r for r in self.replicas if r.healthy]
        occ = (sum(r.occupancy() for r in healthy) / len(healthy)
               if healthy else 1.0)
        shed = sum(r.shed_count() for r in healthy)
        shed_rate = shed / max(1, len(self.handles))
        hits = []
        for rep in healthy:
            for req in rep.engine.sched.finished:
                if req.deadline_t is not None and req.finish_t is not None:
                    hits.append(req.finish_t <= req.deadline_t)
        hit_rate = (sum(1 for h in hits if h) / len(hits)) if hits else None
        return {"shed_rate": shed_rate, "occupancy": occ,
                "deadline_hit_rate": hit_rate,
                "replicas": len(self.replicas), "healthy": len(healthy)}

    def emit_scale_hint(self) -> str:
        sig = self.signals()
        hint = scale_hint(shed_rate=sig["shed_rate"],
                          occupancy=sig["occupancy"],
                          deadline_hit_rate=sig["deadline_hit_rate"])
        ev = dict(hint=hint, shed_rate=sig["shed_rate"],
                  occupancy=sig["occupancy"], replicas=sig["replicas"],
                  healthy=sig["healthy"])
        if sig["deadline_hit_rate"] is not None:
            # optional means absent, never a sentinel
            ev["deadline_hit_rate"] = sig["deadline_hit_rate"]
        self._emit("fleet_scale_hint", **ev)
        return hint

    # -- plumbing --------------------------------------------------------

    def _emit(self, type_: str, **payload) -> None:
        if self.telemetry is not None:
            self.telemetry.emit(type_, step=self.round, **payload)


def rolling_restart(router: FleetRouter, *, serve_between: int = 0) -> None:
    """Drain, migrate, restart, readmit — one replica at a time, so
    N-1 replicas keep serving and p99 TTFT holds (the bench_fleet
    restart segment gates this).  Each replica's turn: fence with
    ``cause="rolling_restart"`` (out of rotation + ``replica_fence``
    event), migrate its live requests onto the still-healthy peers,
    rebuild its engine from the factory (fresh warmup — zero compiles
    later, by the standing contract), and rejoin rotation empty.
    ``serve_between`` is the replica's DOWNTIME WINDOW in fleet
    rounds: those rounds run between its fence and its restart, so
    the still-healthy peers keep serving (first tokens keep landing)
    while the replica is conceptually down — the in-process stand-in
    for peers serving concurrently while one process respawns.

    A fleet of ONE has nowhere to migrate: it snapshots, sits out the
    same downtime window with NOTHING serving (its queue just ages —
    the honest cost of single-replica stop-the-world), restarts, and
    re-adopts its own records.

    FENCED replicas rejoin too: their live requests already migrated
    at fence time, so a bare restart returns them to rotation — the
    rolling restart is also the repair operation after a chaos kill."""
    for rep in list(router.replicas):
        if not rep.healthy:
            # fenced at some earlier fault: drained already, restart
            # brings it back empty
            if rep.state == FENCED:
                rep.restart()
            continue
        peers = [r for r in router.replicas
                 if r.healthy and r.name != rep.name]
        if peers:
            router._fence(rep, cause="rolling_restart")
            for _ in range(serve_between):
                router.step()
            rep.restart()
        else:
            snap = json.loads(json.dumps(rep.snapshot()))
            router._fence(rep, cause="rolling_restart", migrate=False)
            for _ in range(serve_between):
                router.step()
            rep.restart()
            records = snap["requests"]
            if records:
                adopted = rep.adopt(records)
                for req, rec in zip(adopted, records):
                    router.handles[req.rid] = req
                    router.placement[req.rid] = rep.name
                    router._emit("request_migrate", rid=req.rid,
                                 from_replica=rep.name, to_replica=rep.name,
                                 tokens_done=len(req.generated),
                                 was_running=bool(rec["was_running"]))
                    router._emit_hop_span(req.rid, rep.name, rep.name)
