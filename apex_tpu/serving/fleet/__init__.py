"""Serving fleet tier (ISSUE 16): an SLO-aware router over N
:class:`~apex_tpu.serving.ServingEngine` replicas.

Three modules, outside-in:

* :mod:`~apex_tpu.serving.fleet.router` — :class:`FleetRouter`:
  per-tenant :class:`SLOClass` assignment, least-loaded placement on
  live telemetry signals, retry-with-backoff on replica fault, fencing
  + live migration, :func:`rolling_restart`, and the autoscaling
  *signal* (:func:`scale_hint` — never an action).
* :mod:`~apex_tpu.serving.fleet.replica` — :class:`ReplicaProxy`: the
  in-process stand-in for the process/RPC boundary.  The router talks
  ONLY to this surface (submit/step/ping/snapshot/adopt/restart), so
  promoting a replica to its own process later changes the proxy, not
  the router.
* :mod:`~apex_tpu.serving.fleet.migrate` — the migration planner:
  pure partition of snapshot records over healthy targets, headroom-
  and geometry-validated before any engine mutates, loud
  :class:`FleetCapacityError` instead of silent drops.

r18 adds two more, underneath and on top:

* :mod:`~apex_tpu.serving.fleet.transport` — the message-level seam
  every cross-replica payload (pings, migration snapshots, KV page
  shipments) flows through: :class:`LocalTransport` (in-process,
  RPC-shaped: serialize → deliver → deserialize with per-message ids)
  and :class:`ChaosTransport` (per-message-class drop / delay /
  duplicate / reorder / corrupt injection).
* :mod:`~apex_tpu.serving.fleet.disagg` — disaggregated
  prefill/decode: :class:`DisaggRouter` ships finished prefills' KV
  pages from prefill replicas to decode replicas (idempotent,
  resumable, CRC-verified, retried with backoff, falling back to
  local prefill past the budget — zero dropped requests).

See docs/serving.md "Fleet tier" / "Disaggregated prefill/decode" for
the router policy, the migration and shipment contracts (what is and
isn't bitwise), and the fence/backoff state machine.
"""

from apex_tpu.serving.fleet.disagg import (  # noqa: F401
    DisaggRouter,
    PageImporter,
)
from apex_tpu.serving.fleet.migrate import (  # noqa: F401
    FleetCapacityError,
    plan_migration,
)
from apex_tpu.serving.fleet.replica import (  # noqa: F401
    FENCED,
    HEALTHY,
    RESTARTING,
    HealthCheckTimeout,
    ReplicaDead,
    ReplicaProxy,
    set_fleet_fault_hook,
)
from apex_tpu.serving.fleet.router import (  # noqa: F401
    FleetRouter,
    SLOClass,
    rolling_restart,
    scale_hint,
    scale_hint_from_events,
)
from apex_tpu.serving.fleet.transport import (  # noqa: F401
    ChaosTransport,
    LocalTransport,
    Transport,
    TransportCorruption,
    TransportTimeout,
    register_error,
)

__all__ = [
    "FleetRouter",
    "SLOClass",
    "rolling_restart",
    "scale_hint",
    "scale_hint_from_events",
    "ReplicaProxy",
    "ReplicaDead",
    "HealthCheckTimeout",
    "set_fleet_fault_hook",
    "HEALTHY",
    "FENCED",
    "RESTARTING",
    "FleetCapacityError",
    "plan_migration",
    "Transport",
    "LocalTransport",
    "ChaosTransport",
    "TransportTimeout",
    "TransportCorruption",
    "register_error",
    "DisaggRouter",
    "PageImporter",
]
