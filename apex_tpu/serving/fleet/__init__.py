"""Serving fleet tier (ISSUE 16): an SLO-aware router over N
:class:`~apex_tpu.serving.ServingEngine` replicas.

Three modules, outside-in:

* :mod:`~apex_tpu.serving.fleet.router` — :class:`FleetRouter`:
  per-tenant :class:`SLOClass` assignment, least-loaded placement on
  live telemetry signals, retry-with-backoff on replica fault, fencing
  + live migration, :func:`rolling_restart`, and the autoscaling
  *signal* (:func:`scale_hint` — never an action).
* :mod:`~apex_tpu.serving.fleet.replica` — :class:`ReplicaProxy`: the
  in-process stand-in for the process/RPC boundary.  The router talks
  ONLY to this surface (submit/step/ping/snapshot/adopt/restart), so
  promoting a replica to its own process later changes the proxy, not
  the router.
* :mod:`~apex_tpu.serving.fleet.migrate` — the migration planner:
  pure partition of snapshot records over healthy targets, headroom-
  and geometry-validated before any engine mutates, loud
  :class:`FleetCapacityError` instead of silent drops.

See docs/serving.md "Fleet tier" for the router policy, the migration
contract (what is and isn't bitwise), and the fence/backoff state
machine.
"""

from apex_tpu.serving.fleet.migrate import (  # noqa: F401
    FleetCapacityError,
    plan_migration,
)
from apex_tpu.serving.fleet.replica import (  # noqa: F401
    FENCED,
    HEALTHY,
    RESTARTING,
    HealthCheckTimeout,
    ReplicaDead,
    ReplicaProxy,
    set_fleet_fault_hook,
)
from apex_tpu.serving.fleet.router import (  # noqa: F401
    FleetRouter,
    SLOClass,
    rolling_restart,
    scale_hint,
    scale_hint_from_events,
)

__all__ = [
    "FleetRouter",
    "SLOClass",
    "rolling_restart",
    "scale_hint",
    "scale_hint_from_events",
    "ReplicaProxy",
    "ReplicaDead",
    "HealthCheckTimeout",
    "set_fleet_fault_hook",
    "HEALTHY",
    "FENCED",
    "RESTARTING",
    "FleetCapacityError",
    "plan_migration",
]
