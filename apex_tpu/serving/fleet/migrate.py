"""Migration planning: partition a fenced replica's snapshot records
over healthy targets, validated before any engine mutates.

The planner is PURE — it reads target headroom/geometry and returns an
assignment; execution (``adopt`` per target, re-handling, telemetry)
stays in the router.  Pure planning is what makes refusal atomic at
the fleet level: if any live record cannot be placed, the plan raises
:class:`FleetCapacityError` and nothing has moved — zero silent drops,
the snapshot is intact, and the operator sees exactly which request
did not fit.

Records travel in the engine's snapshot format (format 1, host-only,
JSON-serializable by construction); since r18 they ship to each
target through the fleet transport's serialize → deliver →
deserialize pipeline, so the in-process fast path exercises exactly
the serialization a process/RPC boundary will.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence


class FleetCapacityError(RuntimeError):
    """No healthy target can take one or more live migrating requests.

    Carries the FULL refusal shape (r18 satellite), not just the first
    failure: ``unplaceable`` lists every rid that fit no target,
    ``pages_required`` the pool pages their worst-case footprints
    need, ``pages_available`` the free pages across the candidate
    targets — the numbers an operator sizes capacity from (the router
    also emits them on a ``migrate_refused`` event)."""

    def __init__(self, msg: str, *,
                 unplaceable: Sequence[int] = (),
                 pages_required: int = 0,
                 pages_available: int = 0):
        super().__init__(msg)
        self.unplaceable = list(unplaceable)
        self.pages_required = int(pages_required)
        self.pages_available = int(pages_available)


def _servable_by(target, record: Dict[str, Any]) -> bool:
    """Geometry check without mutating the target: mirrors
    ``check_servable`` over the snapshot record's worst case."""
    sched = target.engine.sched
    cache = target.engine.cache
    worst = len(record["prompt"]) + int(record["max_new_tokens"])
    if worst > sched.max_position:
        return False
    if cache.pages_needed(worst) > cache.max_pages_per_request:
        return False
    if worst > sched.prefill_budget and sched.chunk_size is None:
        return False
    return True


def plan_migration(records: Sequence[Dict[str, Any]],
                   targets: Sequence) -> Dict[str, List[Dict[str, Any]]]:
    """Assign snapshot ``records`` to healthy ``targets``
    (:class:`~apex_tpu.serving.fleet.replica.ReplicaProxy`), least
    loaded first, respecting each target's bounded-queue headroom and
    geometry.  Returns ``{replica_name: [records...]}`` covering EVERY
    record, or raises :class:`FleetCapacityError` — a migration plan
    never quietly sheds.

    Done-at-capture records retire immediately on adoption (they never
    enter the waiting queue), so they don't consume headroom; live
    records do.  Assignment order is rid order for determinism."""
    done = [r for r in records if _record_done(r)]
    live = [r for r in records if not _record_done(r)]
    if not targets:
        raise FleetCapacityError(
            f"no healthy targets for {len(records)} migrating requests",
            unplaceable=[int(r["rid"]) for r in
                         sorted(live, key=lambda r: int(r["rid"]))])
    plan: Dict[str, List[Dict[str, Any]]] = {t.name: [] for t in targets}
    headroom = {t.name: t.queue_headroom() for t in targets}
    # fractional load tiebreak frozen at plan time; planned placements
    # added on top so a burst spreads instead of piling on one target
    load = {t.name: t.load_score() for t in targets}
    by_name = {t.name: t for t in targets}
    # a refused plan reports EVERY request that fit nowhere, not just
    # the first — one fence, one error, the complete capacity gap
    unplaceable: List[Dict[str, Any]] = []
    for rec in sorted(live, key=lambda r: int(r["rid"])):
        candidates = [
            n for n, t in by_name.items()
            if (headroom[n] is None or headroom[n] > 0)
            and _servable_by(t, rec)
        ]
        if not candidates:
            unplaceable.append(rec)
            continue
        name = min(candidates, key=lambda n: (load[n], n))
        plan[name].append(rec)
        load[name] += 1
        if headroom[name] is not None:
            headroom[name] -= 1
    if unplaceable:
        rids = [int(r["rid"]) for r in unplaceable]
        required = sum(
            min(t.engine.cache.pages_needed(
                len(r["prompt"]) + int(r["max_new_tokens"]))
                for t in targets)
            for r in unplaceable)
        available = sum(t.engine.cache.pages_free for t in targets)
        raise FleetCapacityError(
            f"{len(rids)} of {len(live)} migrating requests fit no "
            f"healthy target (rids {rids}; worst-case pages required "
            f"{required}, free across targets {available}; headroom "
            f"{dict(headroom)}) — refuse the whole plan, drop nothing",
            unplaceable=rids, pages_required=required,
            pages_available=available)
    for rec in sorted(done, key=lambda r: int(r["rid"])):
        name = min(by_name, key=lambda n: (load[n], n))
        plan[name].append(rec)
    return plan


def _record_done(rec: Dict[str, Any]) -> bool:
    """Snapshot-record twin of ``Request.done``: generation budget
    exhausted or EOS sampled (the engine retires these immediately on
    adopt instead of re-prefilling past max_new_tokens)."""
    gen = rec["generated"]
    if len(gen) >= int(rec["max_new_tokens"]):
        return True
    eos = rec["eos_id"]
    return eos is not None and bool(gen) and gen[-1] == eos
