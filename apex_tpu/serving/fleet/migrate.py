"""Migration planning: partition a fenced replica's snapshot records
over healthy targets, validated before any engine mutates.

The planner is PURE — it reads target headroom/geometry and returns an
assignment; execution (``adopt`` per target, re-handling, telemetry)
stays in the router.  Pure planning is what makes refusal atomic at
the fleet level: if any live record cannot be placed, the plan raises
:class:`FleetCapacityError` and nothing has moved — zero silent drops,
the snapshot is intact, and the operator sees exactly which request
did not fit.

Records travel in the engine's snapshot format (format 1, host-only,
JSON-serializable by construction); the router round-trips the
snapshot through ``json`` before planning, so the in-process fast path
exercises the same serialization a process/RPC boundary will.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence


class FleetCapacityError(RuntimeError):
    """No healthy target can take a live migrating request."""


def _servable_by(target, record: Dict[str, Any]) -> bool:
    """Geometry check without mutating the target: mirrors
    ``check_servable`` over the snapshot record's worst case."""
    sched = target.engine.sched
    cache = target.engine.cache
    worst = len(record["prompt"]) + int(record["max_new_tokens"])
    if worst > sched.max_position:
        return False
    if cache.pages_needed(worst) > cache.max_pages_per_request:
        return False
    if worst > sched.prefill_budget and sched.chunk_size is None:
        return False
    return True


def plan_migration(records: Sequence[Dict[str, Any]],
                   targets: Sequence) -> Dict[str, List[Dict[str, Any]]]:
    """Assign snapshot ``records`` to healthy ``targets``
    (:class:`~apex_tpu.serving.fleet.replica.ReplicaProxy`), least
    loaded first, respecting each target's bounded-queue headroom and
    geometry.  Returns ``{replica_name: [records...]}`` covering EVERY
    record, or raises :class:`FleetCapacityError` — a migration plan
    never quietly sheds.

    Done-at-capture records retire immediately on adoption (they never
    enter the waiting queue), so they don't consume headroom; live
    records do.  Assignment order is rid order for determinism."""
    if not targets:
        raise FleetCapacityError(
            f"no healthy targets for {len(records)} migrating requests")
    plan: Dict[str, List[Dict[str, Any]]] = {t.name: [] for t in targets}
    headroom = {t.name: t.queue_headroom() for t in targets}
    # fractional load tiebreak frozen at plan time; planned placements
    # added on top so a burst spreads instead of piling on one target
    load = {t.name: t.load_score() for t in targets}
    by_name = {t.name: t for t in targets}
    done = [r for r in records if _record_done(r)]
    live = [r for r in records if not _record_done(r)]
    for rec in sorted(live, key=lambda r: int(r["rid"])):
        candidates = [
            n for n, t in by_name.items()
            if (headroom[n] is None or headroom[n] > 0)
            and _servable_by(t, rec)
        ]
        if not candidates:
            raise FleetCapacityError(
                f"request {rec['rid']} fits no healthy target "
                f"(headroom {dict(headroom)}) — refuse the whole plan, "
                "drop nothing")
        name = min(candidates, key=lambda n: (load[n], n))
        plan[name].append(rec)
        load[name] += 1
        if headroom[name] is not None:
            headroom[name] -= 1
    for rec in sorted(done, key=lambda r: int(r["rid"])):
        name = min(by_name, key=lambda n: (load[n], n))
        plan[name].append(rec)
    return plan


def _record_done(rec: Dict[str, Any]) -> bool:
    """Snapshot-record twin of ``Request.done``: generation budget
    exhausted or EOS sampled (the engine retires these immediately on
    adopt instead of re-prefilling past max_new_tokens)."""
    gen = rec["generated"]
    if len(gen) >= int(rec["max_new_tokens"]):
        return True
    eos = rec["eos_id"]
    return eos is not None and bool(gen) and gen[-1] == eos
