"""The message-level transport seam (ISSUE 18 tentpole).

Every cross-replica payload — health pings, migration snapshots, and
the disaggregation tier's KV page shipments — flows through ONE
surface: :meth:`Transport.call`.  The in-process implementation
(:class:`LocalTransport`) is deliberately RPC-shaped: an explicit
serialize → deliver → deserialize pipeline with a per-message id, a
JSON wire envelope, and a body CRC — so a network transport later
replaces :meth:`LocalTransport.deliver` and nothing above the seam
changes.  The router's old inline ``json.loads(json.dumps(...))``
serializability pin now lives here, where the real boundary will be.

Wire discipline:

* **envelope** — ``{"msg_id", "class", "dst", "payload",
  "body_crc"}``, JSON text.  ``body_crc`` is a crc32 of the
  canonically-serialized payload, stamped at serialize time; the
  receiver recomputes it before dispatch and answers a typed
  ``corrupt_envelope`` error on mismatch (the sender sees
  :class:`TransportCorruption` — retryable, like a timeout).
* **at-most-once processing per wire message** — the receiver memoizes
  replies by ``msg_id``, so a DUPLICATED wire message is processed
  once and the second copy gets the memoized reply.  Sender-level
  retries mint a new ``msg_id``, so end-to-end idempotency is the
  application's job (migration dedupes by rid, shipments by transfer
  id — see :mod:`~apex_tpu.serving.fleet.disagg`).
* **typed errors over the wire** — a handler exception whose type was
  :func:`register_error`-ed serializes into the reply and re-raises
  sender-side as the same type (``HealthCheckTimeout`` crossing the
  ping boundary); unregistered exceptions propagate raw, loudly — a
  handler bug must not be laundered into a retry.

:class:`ChaosTransport` wraps any transport and injects per-message-
class faults (drop / delay / duplicate / reorder / corrupt), each a
``fault_injected`` telemetry event.  The injection semantics encode
the failure modes the disaggregation contract must survive —
docs/serving.md "Disaggregated prefill/decode" pins each (message
class × fault) cell to its outcome:

* **drop** — the message is never delivered; the sender gets
  :class:`TransportTimeout`.
* **delay** — the message IS delivered and processed, but the reply
  arrives past the budget: the sender still gets
  :class:`TransportTimeout`.  This is the at-least-once ambiguity
  that forces receiver-side idempotency — the sender cannot tell a
  dropped request from a dropped reply, and its retry re-delivers
  work the receiver already did.
* **duplicate** — the same wire message is delivered twice; the
  msg-id memo makes the second copy a no-op.
* **reorder** — a ``kv_page`` message is stashed (its sender gets a
  synthesized ack) and delivered late, after the NEXT message to the
  same destination; a ``kv_commit`` flushes the stash first, so the
  commit always fences the data plane.  Control classes (ping /
  migrate) are request-reply ordered by construction — reorder never
  fires on them (a no-op, documented as such in the chaos matrix).
* **corrupt** — ping/migrate payloads are mutated WITHOUT fixing the
  envelope CRC (the receiver's envelope check catches it →
  :class:`TransportCorruption`); a ``kv_page`` payload has its page
  BYTES mutated with the envelope CRC re-stamped — the envelope reads
  clean and only the application-level per-page export CRC catches
  it, which is exactly the corruption class the re-request path
  exists for.

No real sleeping anywhere: delays are virtual (the exception IS the
late reply), so chaos tests never slow the suite.
"""

from __future__ import annotations

import json
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class TransportTimeout(RuntimeError):
    """No reply within the (virtual) budget — the message or its
    reply was lost in flight.  The sender cannot know which: retry
    against an idempotent receiver, or fence/fall back past the
    budget."""


class TransportCorruption(RuntimeError):
    """The receiver's envelope CRC check rejected the message — a
    corrupted-in-flight request.  Retryable, like a timeout (the
    next copy re-serializes clean)."""


#: Exception types allowed to cross the wire as typed error replies
#: (name -> class).  Populated by the modules that own the types
#: (:mod:`router` registers ``HealthCheckTimeout``); anything NOT
#: here propagates raw at the handler — in-process that is a loud
#: crash, which is what a handler BUG deserves.
_ERROR_TYPES: Dict[str, type] = {}


def register_error(exc_type: type) -> type:
    """Allow ``exc_type`` to serialize across the transport as a
    typed error reply; returns the type (usable as a decorator)."""
    _ERROR_TYPES[exc_type.__name__] = exc_type
    return exc_type


def _body_crc(payload: Any) -> int:
    """crc32 of the canonical (sorted-key) JSON payload bytes — the
    envelope integrity stamp."""
    return zlib.crc32(
        json.dumps(payload, sort_keys=True).encode("utf-8"))


class Transport:
    """The seam: message-class handlers register per destination, and
    every cross-replica payload goes through :meth:`call`.

    ``trace`` (r19) is the distributed-tracing context: an opaque
    JSON-serializable dict carried VERBATIM inside the wire envelope
    (never inside the payload, so payload CRC / corruption faults
    cannot touch it), exposed to the receiving handler as
    :attr:`current_trace` for the duration of its dispatch.  Span
    identity lives in the context itself — transport msg ids are
    useless for it, since sender retries mint fresh ones."""

    #: the in-flight message's trace context while its handler runs
    current_trace: Optional[Dict[str, Any]] = None

    def register(self, dst: str, msg_class: str,
                 handler: Callable[[Dict[str, Any]], Dict[str, Any]]
                 ) -> None:
        raise NotImplementedError

    def call(self, dst: str, msg_class: str, payload: Dict[str, Any],
             *, trace: Optional[Dict[str, Any]] = None
             ) -> Dict[str, Any]:
        raise NotImplementedError


class LocalTransport(Transport):
    """In-process transport with the full RPC pipeline: per-message
    ids, JSON envelope + body CRC, receiver-side dispatch, JSON
    reply.  Payloads and replies MUST be JSON-serializable — the
    round-trip is the serializability pin the router used to carry
    inline."""

    def __init__(self):
        #: (dst, msg_class) -> handler(payload) -> reply dict
        self._handlers: Dict[Tuple[str, str], Callable] = {}
        self._next_msg_id = 0
        #: msg_id -> serialized reply (at-most-once processing per
        #: wire message; bounded by the life of the transport, which
        #: is the life of the fleet — a few bytes per message)
        self._replies: Dict[int, str] = {}
        self.current_trace: Optional[Dict[str, Any]] = None

    # -- registration -----------------------------------------------------

    def register(self, dst: str, msg_class: str,
                 handler: Callable[[Dict[str, Any]], Dict[str, Any]]
                 ) -> None:
        self._handlers[(dst, msg_class)] = handler

    # -- the pipeline ------------------------------------------------------

    def serialize(self, dst: str, msg_class: str,
                  payload: Dict[str, Any],
                  trace: Optional[Dict[str, Any]] = None) -> str:
        """Mint a message: assign the next msg id, stamp the body
        CRC, return the JSON wire text.  ``trace`` rides in the
        envelope OUTSIDE the payload: the body CRC does not cover it,
        corruption faults do not touch it, and duplicated wire copies
        carry the identical context — span ids stay idempotent."""
        msg_id = self._next_msg_id
        self._next_msg_id += 1
        return json.dumps({"msg_id": msg_id, "class": msg_class,
                           "dst": dst, "payload": payload,
                           "trace": trace,
                           "body_crc": _body_crc(payload)})

    def deliver(self, wire: str) -> str:
        """Receiver side: parse the envelope, verify the body CRC,
        dedupe by msg id, dispatch to the registered handler, and
        return the serialized reply."""
        env = json.loads(wire)
        msg_id = int(env["msg_id"])
        if msg_id in self._replies:
            # a duplicated wire message: processed once, the second
            # copy gets the memoized reply
            return self._replies[msg_id]
        if _body_crc(env["payload"]) != env["body_crc"]:
            reply = json.dumps({"__error__": {
                "type": "TransportCorruption",
                "message": f"envelope CRC mismatch on msg {msg_id} "
                           f"(class {env['class']!r})"}})
            self._replies[msg_id] = reply
            return reply
        handler = self._handlers.get((env["dst"], env["class"]))
        if handler is None:
            raise KeyError(
                f"no handler for class {env['class']!r} on "
                f"{env['dst']!r} — register before calling")
        self.current_trace = env.get("trace")
        try:
            out = handler(env["payload"])
        except Exception as e:   # noqa: BLE001 — typed re-raise below
            if type(e).__name__ not in _ERROR_TYPES:
                raise
            out = {"__error__": {"type": type(e).__name__,
                                 "message": str(e)}}
        finally:
            self.current_trace = None
        reply = json.dumps(out)
        self._replies[msg_id] = reply
        return reply

    def deserialize_reply(self, reply_wire: str) -> Dict[str, Any]:
        """Sender side: parse the reply; a typed error reply
        re-raises as its registered exception type."""
        reply = json.loads(reply_wire)
        err = reply.get("__error__") if isinstance(reply, dict) else None
        if err is not None:
            if err["type"] == "TransportCorruption":
                raise TransportCorruption(err["message"])
            raise _ERROR_TYPES[err["type"]](err["message"])
        return reply

    def call(self, dst: str, msg_class: str, payload: Dict[str, Any],
             *, trace: Optional[Dict[str, Any]] = None
             ) -> Dict[str, Any]:
        return self.deserialize_reply(
            self.deliver(self.serialize(dst, msg_class, payload, trace)))


#: The injectable fault classes, in injection-priority order (at most
#: ONE fault per message; when a schedule/rate arms several for the
#: same message, the first in this order wins).
FAULTS = ("drop", "delay", "duplicate", "reorder", "corrupt")


class ChaosTransport(Transport):
    """Fault-injecting wrapper around a real transport.

    Two arming modes, composable:

    * ``schedule`` — ``{(msg_class, fault): {n, ...}}``: inject
      ``fault`` on the n-th message of ``msg_class`` (1-based, counted
      per class).  Deterministic — the chaos matrix test pins each
      cell with exactly this.
    * ``rates`` — ``{(msg_class, fault): p}``: inject with
      probability ``p`` per message, seeded (``np.random.RandomState``
      — same discipline as every other chaos injector).

    Every injection emits a ``fault_injected`` event
    (``kind="transport_<fault>"``, ``event=<msg_class>``,
    ``replica=<dst>``).  Reorder only ever fires on ``kv_page``
    messages (see the module docstring); arming it on a control class
    is accepted and never fires.
    """

    def __init__(self, inner: LocalTransport, *,
                 schedule: Optional[Dict[Tuple[str, str], Any]] = None,
                 rates: Optional[Dict[Tuple[str, str], float]] = None,
                 seed: int = 0, telemetry=None):
        self.inner = inner
        self.schedule = {k: set(v) for k, v in (schedule or {}).items()}
        self.rates = dict(rates or {})
        self._rng = np.random.RandomState(seed)
        self.telemetry = telemetry
        self._seen: Dict[str, int] = {}      # per-class message count
        self._stash: Dict[str, List[str]] = {}  # dst -> reordered wires
        self.injected: Dict[str, int] = {}   # f"{class}:{fault}" -> n

    def register(self, dst, msg_class, handler) -> None:
        self.inner.register(dst, msg_class, handler)

    @property
    def current_trace(self) -> Optional[Dict[str, Any]]:
        # handlers dispatch on the inner transport; delegate so code
        # holding the chaos wrapper sees the same context
        return self.inner.current_trace

    # -- fault selection ---------------------------------------------------

    def _pick(self, msg_class: str) -> Optional[str]:
        n = self._seen.get(msg_class, 0) + 1
        self._seen[msg_class] = n
        for fault in FAULTS:
            if fault == "reorder" and msg_class != "kv_page":
                continue
            if n in self.schedule.get((msg_class, fault), ()):
                return fault
            p = self.rates.get((msg_class, fault), 0.0)
            if p > 0.0 and self._rng.random_sample() < p:
                return fault
        return None

    def _emit(self, fault: str, msg_class: str, dst: str) -> None:
        key = f"{msg_class}:{fault}"
        self.injected[key] = self.injected.get(key, 0) + 1
        if self.telemetry is not None:
            self.telemetry.emit("fault_injected",
                                kind=f"transport_{fault}",
                                event=msg_class, replica=dst)

    def _corrupt(self, wire: str, msg_class: str) -> str:
        """Mutate the message in flight.  Control classes: flip a
        payload value WITHOUT re-stamping the envelope CRC (caught at
        the envelope).  ``kv_page``: flip the page's data bytes and
        RE-STAMP the envelope — clean envelope, damaged content; only
        the per-page export CRC can catch it on import."""
        env = json.loads(wire)
        if msg_class == "kv_page":
            data = env["payload"]["data"]
            # mutate the b64 text of the K plane — any in-alphabet
            # change decodes to different bytes, so the export CRC
            # recorded at the source can no longer match
            k = data["k"]
            data["k"] = ("BBBB" + k[4:]) if not k.startswith("BBBB") \
                else ("CCCC" + k[4:])
            env["body_crc"] = _body_crc(env["payload"])
        else:
            env["payload"] = {"__corrupted__": True,
                              "was": env["payload"]}
        return json.dumps(env)

    def _flush(self, dst: str) -> None:
        """Deliver every stashed (reordered) message for ``dst`` —
        their synthesized acks were already returned, so the replies
        go nowhere; the content lands late, which is the point."""
        for wire in self._stash.pop(dst, []):
            self.inner.deliver(wire)

    # -- the wrapped call --------------------------------------------------

    def call(self, dst: str, msg_class: str, payload: Dict[str, Any],
             *, trace: Optional[Dict[str, Any]] = None
             ) -> Dict[str, Any]:
        fault = self._pick(msg_class)
        wire = self.inner.serialize(dst, msg_class, payload, trace)
        if fault == "drop":
            self._emit(fault, msg_class, dst)
            raise TransportTimeout(
                f"{msg_class} to {dst} dropped in flight")
        if fault == "corrupt":
            self._emit(fault, msg_class, dst)
            wire = self._corrupt(wire, msg_class)
        if fault == "reorder":
            # stash; the sender gets an optimistic synthesized ack and
            # the content lands after the NEXT message to this dst
            self._emit(fault, msg_class, dst)
            self._stash.setdefault(dst, []).append(wire)
            return {"ok": True, "reordered": True}
        if msg_class == "kv_commit":
            # the commit fences the data plane: reordered pages land
            # before it, so order-independent reassembly always sees
            # everything that was actually sent
            self._flush(dst)
        reply = self.inner.deliver(wire)
        if fault == "duplicate":
            self._emit(fault, msg_class, dst)
            self.inner.deliver(wire)   # msg-id memo: processed once
        self._flush(dst)
        if fault == "delay":
            # delivered AND processed — only the reply is late.  The
            # sender's retry re-delivers work the receiver already
            # did; idempotency makes that harmless.
            self._emit(fault, msg_class, dst)
            raise TransportTimeout(
                f"{msg_class} to {dst}: reply past the virtual budget")
        return self.inner.deserialize_reply(reply)
