"""The serving engine: the device-facing half of continuous batching.

:class:`ServingEngine` turns the :class:`~apex_tpu.serving.scheduler.
ContinuousBatchingScheduler`'s host-side decisions into a fixed set of
five compiled executables (:data:`SERVING_EXECUTABLES`; the last two
only when :class:`~apex_tpu.serving.spec.SpecConfig` enables them),
each traced ONCE for the engine's lifetime — the table in
docs/serving.md "The compiled-shapes contract" is machine-checked
against this module.  The two workhorses:

* **prefill** — a fixed-width packed row (``[1, prefill_budget]``
  tokens + segment ids + per-segment positions) through
  :meth:`~apex_tpu.serving.model.PagedDecoder.prefill`, returning the
  greedy next-token per position and per-layer K/V, which the engine
  scatters into the request's freshly allocated pages (the
  **admission scatter**, ``PagedKVCache.write_tokens`` — executable
  #3).
* **decode** — a fixed-width ``[max_batch]`` step through
  :meth:`~apex_tpu.serving.model.PagedDecoder.decode`: append each
  row's newest token's K/V into its current page, attend over the
  row's page list via :func:`~apex_tpu.ops.flash_decode`, sample
  greedily.  Idle rows are pointed at the scratch page and ignored.

The ISSUE 12 draft–verify subsystem adds the **speculative verify**
step (``[max_batch, spec.k + 1]``) and the **chunked-prefill** step
(``[1, spec.chunk_size]``) — executables #4 and #5.

Admitting, retiring, growing or preempting requests between steps
never changes a device shape, so after :meth:`ServingEngine.warmup`
the serving lifetime sees ZERO further XLA compilations.  The warmup
compiles a FIXED, documented executable set (docs/serving.md "The
compiled-shapes contract"): the two step functions plus the pool-fill
scatter (``PagedKVCache.write_tokens``), and — with the ISSUE 12
draft–verify subsystem on — the speculative verify step
(``q_len = spec.k + 1``) and the ``[1, chunk_size]`` chunked-prefill
step.  The no-compile steady state is enforced by construction with
:func:`apex_tpu.analysis.hot_path_guard` (ISSUE 11 pin, extended over
a speculative + chunked trace in ISSUE 12).

**Speculative decoding (ISSUE 12, docs/serving.md).**  With
``spec=SpecConfig(k, proposer, chunk_size)`` the decode boundary asks
a host-side proposer for up to ``k`` draft tokens per request, scores
all of them in ONE ``flash_decode`` launch at ``q_len = k + 1``
(:meth:`_verify_batch`), commits the longest prefix the model's own
greedy argmax endorses plus the bonus token, and rolls rejected rows
back via plain ``kv_len``/page accounting — exact acceptance keeps
the bitwise batched==sequential contract intact.  Long prefills split
into fixed-width chunks (:meth:`_chunk_step`) that interleave with
decode boundaries under the existing prefill-token budget.

**The isolation contract (and why prefill is one request per row).**
The acceptance bar for this engine is bitwise: batched continuous
decoding must produce exactly the tokens sequential one-request-at-a-
time decoding produces.  Decode is row-wise by construction, but a
packed prefill row holding SEVERAL segments is not offset-invariant —
the attention contraction reduces over the packed axis, and XLA's
blocked reduction groups differently depending on where in the row a
segment starts (measured: a segment at offset 17 differs from offset 0
in the last ulp, enough to flip a greedy tie).  So the engine prefills
each admitted request in its OWN fixed-width row at offset 0: the
varlen packed machinery (segment ids mask the padding) with exactly
one segment per row.  Admission still batches — the scheduler admits
many requests per step — but each prefill launch serves one request.
The multi-segment form of :meth:`PagedDecoder.prefill` remains
available for throughput-over-isolation deployments; the engine does
not use it (docs/serving.md, "Prefill isolation").

Telemetry: every lifecycle edge lands on the PR 4 bus as one of the
serving event types — ``request_admit``, ``request_retire`` (with
per-request TTFT/TPOT and, when the request carried a deadline, a
``deadline_hit`` bool), ``decode_step`` (batch width, tokens,
page-pool occupancy), plus the ISSUE 10 resilience set
(``request_reject``, ``request_timeout``, ``serving_recovery``) — so
``python -m apex_tpu.telemetry summarize`` renders a serving line and
the bench's stream is schema-validated by the existing ``validate``
CLI.

**Failure semantics (ISSUE 10).** The engine degrades instead of
falling over: per-request deadlines shed/time out work that can no
longer meet its SLO, a bounded submit queue rejects overload loudly,
:meth:`ServingEngine.snapshot`/:meth:`~ServingEngine.restore` capture
the HOST-side serving state (queue order + per-request tokens — KV
pages deliberately excluded, they are rebuildable by deterministic
re-prefill), and a :class:`~apex_tpu.resilience.chaos.DeviceLossError`
or :class:`~apex_tpu.serving.kv_cache.PagePoolCorruption` raised
mid-decode triggers :meth:`~ServingEngine.recover` — fresh pool,
live requests back to the front of the queue, token streams bitwise
identical to an uninterrupted run.  See docs/serving.md "Failure
semantics".
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.kv_cache import (PagedKVCache, PagePoolCorruption,
                                       PagePoolExhausted, PrefixIndex,
                                       verify_page_payload)
from apex_tpu.serving.model import (PagedDecoder, ServingModelConfig,
                                    init_params, shard_params_tp)
from apex_tpu.serving.scheduler import (FINISHED, RUNNING, WAITING,
                                        ContinuousBatchingScheduler,
                                        QueueFullError, Request)
from apex_tpu.serving.spec import (NgramProposer, SpecConfig,
                                   commit_tokens)

#: The compiled-shapes contract as code, in docs/serving.md table
#: order: every executable :meth:`ServingEngine.warmup` may build.
#: The doc-drift test pins the module docstring's "fixed set of five"
#: and the docs table row count to this tuple, and the ISSUE 13
#: registry (``apex_tpu.analysis.registry``) derives its serving
#: entries from it — docstring, docs, and contract checker cannot
#: disagree on the set.
SERVING_EXECUTABLES = ("prefill", "decode", "admission_scatter",
                       "verify", "chunk")


class AdmissionRefused(RuntimeError):
    """A shipped-prefill admission (:meth:`ServingEngine.
    adopt_prefilled`) was refused for CAPACITY — no decode batch slot
    or no pool pages.  Recoverable by construction, like
    :class:`~apex_tpu.serving.kv_cache.PagePoolExhausted`: the sender
    backs off and retries, or past its budget falls back to migrating
    the request for local prefill.  Validation failures (geometry, rid
    collision, CRC) raise ``ValueError`` instead — those are bugs or
    corruption, not capacity events."""


# -- chaos hook (ISSUE 10) ---------------------------------------------------
# The serving twin of checkpoint.set_fault_hook / data.set_read_hook:
# the chaos tier installs an injector here to raise DeviceLossError /
# sleep / corrupt a page at a named engine event ("decode" before each
# decode launch, "prefill" before each prefill launch).  Production
# never sets it; the slot costs one None-check per step.

_FAULT_HOOK: Optional[Callable[[str, int], None]] = None


def set_fault_hook(hook: Optional[Callable[[str, int], None]]):
    """Install (or clear) the serving fault hook; returns the previous
    hook so context-manager injectors can chain/restore."""
    global _FAULT_HOOK
    prev = _FAULT_HOOK
    _FAULT_HOOK = hook
    return prev


def _fault_point(event: str, info: int) -> None:
    if _FAULT_HOOK is not None:
        _FAULT_HOOK(event, info)


class SimClock:
    """Deterministic virtual clock for tests: ``now()`` returns the
    current virtual time; the engine's step advances it by a fixed
    tick, so a seeded arrival trace replays bit-identically with no
    wall-clock in the loop."""

    def __init__(self, tick: float = 1.0):
        self.t = 0.0
        self.tick = tick

    def __call__(self) -> float:
        return self.t

    def advance(self) -> None:
        self.t += self.tick


def poisson_trace(seed: int, n_requests: int, *, rate: float,
                  prompt_len: Tuple[int, int], max_new: Tuple[int, int],
                  vocab_size: int,
                  eos_id: Optional[int] = None,
                  deadline_s: Optional[Tuple[float, float]] = None,
                  rid_base: int = 0) -> List[Request]:
    """Seeded Poisson arrival trace: exponential inter-arrival gaps at
    ``rate`` requests/s, uniform prompt lengths and generation budgets.
    Deterministic in ``seed`` — the serving bench's workload and the
    scheduler determinism test share this generator.

    ``deadline_s`` — optional (lo, hi) uniform completion-deadline
    range (seconds after arrival; the overload/SLO arcs use this).
    The draw happens only when requested, so deadline-free traces are
    bit-identical to the pre-ISSUE-10 generator.  ``rid_base`` offsets
    request ids so a second trace can be served on the same engine
    (rids are unique per engine lifetime)."""
    rng = np.random.RandomState(seed)
    t = 0.0
    out: List[Request] = []
    for rid in range(n_requests):
        t += float(rng.exponential(1.0 / rate))
        plen = int(rng.randint(prompt_len[0], prompt_len[1] + 1))
        out.append(Request(
            rid=rid_base + rid,
            prompt=[int(x) for x in rng.randint(0, vocab_size, plen)],
            max_new_tokens=int(rng.randint(max_new[0], max_new[1] + 1)),
            eos_id=eos_id,
            arrival_t=t,
            deadline_s=(None if deadline_s is None else
                        float(rng.uniform(deadline_s[0], deadline_s[1]))),
        ))
    return out


class ServingEngine:
    """Continuous-batching inference over a paged KV cache.

    ``num_pages``/``page_size`` size the shared pool;
    ``prefill_budget`` fixes the packed prefill row width (defaults to
    ``cfg.max_position``) and bounds prompt+generation per request;
    ``max_batch`` fixes the decode batch width.  ``telemetry`` is an
    optional :class:`~apex_tpu.telemetry.TelemetryBus`; ``clock`` an
    optional ``() -> float`` (tests pass :class:`SimClock` for
    deterministic timing fields — timing never feeds scheduling
    decisions, only metrics and, when requests carry deadlines, the
    deadline policy).

    Resilience knobs (ISSUE 10 — docs/serving.md "Failure semantics"):
    ``max_queue`` bounds the submit queue (overflow → ``rejected``
    terminal state + ``request_reject`` event, never unbounded growth);
    ``preempt_cap`` is the anti-livelock aging cap on evict-newest
    preemption; ``shed_min_service_s`` is the SLO floor used to shed
    queued requests BEFORE their deadline expires; ``watchdog`` is an
    optional :class:`~apex_tpu.resilience.elastic.Watchdog` armed
    around every engine step (a wedged decode escalates instead of
    hanging the trace); ``validate_pages`` turns on per-page CRC
    read-back validation in the pool; ``recover_on_fault`` lets
    :meth:`serve`/:meth:`run` absorb a mid-decode
    ``DeviceLossError``/``PagePoolCorruption`` via :meth:`recover`
    (at most ``max_recoveries`` times, then the fault re-raises).
    """

    def __init__(self, cfg: ServingModelConfig, params=None, *,
                 num_pages: int, page_size: int = 64,
                 max_batch: int = 8,
                 max_pages_per_request: Optional[int] = None,
                 prefill_budget: Optional[int] = None,
                 telemetry=None,
                 clock: Optional[Callable[[], float]] = None,
                 seed: int = 0,
                 max_queue: Optional[int] = None,
                 preempt_cap: Optional[int] = 4,
                 shed_min_service_s: float = 0.0,
                 watchdog=None,
                 validate_pages: bool = False,
                 recover_on_fault: bool = True,
                 max_recoveries: int = 3,
                 reject_unservable: bool = False,
                 spec: Optional[SpecConfig] = None,
                 tp: int = 1,
                 kv_quant: Optional[str] = None,
                 prefix_sharing: bool = False,
                 prefix_entries: int = 8,
                 prefill_only: bool = False,
                 kv_import: bool = False):
        self.cfg = cfg
        self.params = params if params is not None else init_params(cfg, seed)
        self.prefill_budget = (cfg.max_position if prefill_budget is None
                               else prefill_budget)
        # draft–verify subsystem (ISSUE 12, docs/serving.md
        # "Speculative decoding"): spec.k > 0 adds the verify
        # executable (q_len = k + 1) and a proposer; spec.chunk_size
        # adds chunked prefill.  spec=None is the pre-ISSUE-12 engine,
        # bit-for-bit.
        self.spec = spec
        self.spec_k = spec.k if spec is not None else 0
        self.chunk_size = spec.chunk_size if spec is not None else None
        self.proposer = None
        if self.spec_k > 0:
            self.proposer = (spec.proposer if spec.proposer is not None
                             else NgramProposer())
        # r17 execution modes (docs/serving.md "Tensor-parallel
        # serving" / "Quantized KV pool" / "Prefix sharing"):
        # tp > 1 shards attention heads (and the page pool's head
        # axis) over the parallel_state tensor axis; kv_quant narrows
        # the pool to int8/fp8 codes + fp32 per-(page, slot, head)
        # scales; prefix_sharing admits repeated prompts onto
        # refcounted shared pages.
        self.tp = int(tp)
        self.kv_quant = kv_quant
        self.prefix_entries = int(prefix_entries)
        self._mesh = None
        self._tp_axis = None
        if self.tp > 1:
            from apex_tpu.transformer.parallel_state import (
                TENSOR_AXIS, tensor_parallel_mesh)
            if cfg.num_heads % self.tp:
                raise ValueError(
                    f"num_heads {cfg.num_heads} not divisible by "
                    f"tp={self.tp}")
            self._mesh = tensor_parallel_mesh(self.tp)
            self._tp_axis = TENSOR_AXIS
            self.params = shard_params_tp(self.params, self.tp)
        if max_pages_per_request is None:
            # a chunked engine serves requests WIDER than the prefill
            # row (that is the point of chunking), so its page-table
            # width must default to the max_position ceiling, not the
            # row width — clamped to the allocatable pool so enabling
            # chunking never turns a valid construction into a
            # constructor error (an oversized request still fails
            # submit() with the pages_needed check, loudly)
            cap_tokens = (cfg.max_position if self.chunk_size is not None
                          else self.prefill_budget)
            max_pages_per_request = min(-(-cap_tokens // page_size),
                                        max(1, num_pages - 1))
        self.cache = PagedKVCache(
            num_layers=cfg.num_layers, num_pages=num_pages,
            page_size=page_size, num_heads=cfg.num_heads,
            head_dim=cfg.head_dim,
            max_pages_per_request=max_pages_per_request,
            dtype=cfg.dtype, crc_pages=validate_pages,
            quantize=kv_quant)
        self.prefix_index = (
            PrefixIndex(self.cache, max_entries=self.prefix_entries)
            if prefix_sharing else None)
        self.sched = ContinuousBatchingScheduler(
            self.cache, max_batch=max_batch,
            prefill_budget=self.prefill_budget,
            max_position=cfg.max_position,
            max_queue=max_queue, preempt_cap=preempt_cap,
            chunk_size=self.chunk_size,
            prefix_index=self.prefix_index)
        self.decoder = PagedDecoder(cfg)
        self.max_batch = max_batch
        self.telemetry = telemetry
        self.clock = clock if clock is not None else time.monotonic
        self.shed_min_service_s = float(shed_min_service_s)
        self.watchdog = watchdog
        self.recover_on_fault = recover_on_fault
        self.max_recoveries = int(max_recoveries)
        # ISSUE 16: a router fronting many engines needs permanent
        # refusal as DATA (terminal `rejected` + request_reject
        # reason="unservable"), not a ValueError — default off keeps
        # the single-engine caller-bug contract
        self.reject_unservable = bool(reject_unservable)
        # r18 disaggregation roles (docs/serving.md "Disaggregated
        # prefill/decode"): a prefill_only engine admits and
        # (chunk-)prefills but never decodes — its requests leave via
        # export_request; kv_import warms the shipped-page import
        # executable so adopt_prefilled never compiles on the
        # admission path.  Both off is the colocated engine, bit-for-bit.
        self.prefill_only = bool(prefill_only)
        self.kv_import = bool(kv_import)
        self.recoveries = 0
        self.rejected: List[Request] = []
        self._next_rid = 0
        self.steps = 0
        self.decode_steps = 0
        decoder = self.decoder
        ax = self._tp_axis
        quant = self.kv_quant is not None

        def _prefill(params, tokens, seg, positions, last_index):
            # logits for the last context position only: admission
            # needs one next-token distribution, not S of them
            logits, k, v = decoder.prefill(params, tokens, seg,
                                           positions, last_index,
                                           tp_axis=ax)
            return jnp.argmax(logits[0, 0], axis=-1), k[:, 0], v[:, 0]

        if quant:
            # quantized pool (r17): the scale planes ride as loop
            # carries next to the pools — same donation class, rebound
            # by the engine together with cache.k/v
            def _decode(params, k_pool, v_pool, k_scale, v_scale,
                        tokens, positions, page_table, kv_len):
                (logits, k_pool, v_pool, k_scale,
                 v_scale) = decoder.decode(
                    params, k_pool, v_pool, tokens, positions,
                    page_table, kv_len, k_scale=k_scale,
                    v_scale=v_scale, tp_axis=ax)
                return (jnp.argmax(logits, axis=-1), k_pool, v_pool,
                        k_scale, v_scale)

            def _verify(params, k_pool, v_pool, k_scale, v_scale,
                        tokens, positions, write_pages, write_offsets,
                        page_table, kv_len):
                (logits, k_pool, v_pool, k_scale,
                 v_scale) = decoder.extend(
                    params, k_pool, v_pool, tokens, positions,
                    write_pages, write_offsets, page_table, kv_len,
                    k_scale=k_scale, v_scale=v_scale, tp_axis=ax)
                return (jnp.argmax(logits, axis=-1), k_pool, v_pool,
                        k_scale, v_scale)

            def _chunk(params, k_pool, v_pool, k_scale, v_scale,
                       tokens, positions, write_pages, write_offsets,
                       page_table, kv_len):
                (logits, k_pool, v_pool, k_scale,
                 v_scale) = decoder.extend(
                    params, k_pool, v_pool, tokens, positions,
                    write_pages, write_offsets, page_table, kv_len,
                    last_only=True, k_scale=k_scale, v_scale=v_scale,
                    tp_axis=ax)
                return (jnp.argmax(logits[:, 0], axis=-1), k_pool,
                        v_pool, k_scale, v_scale)

            pool_donate = (1, 2, 3, 4)
        else:
            def _decode(params, k_pool, v_pool, tokens, positions,
                        page_table, kv_len):
                logits, k_pool, v_pool = decoder.decode(
                    params, k_pool, v_pool, tokens, positions,
                    page_table, kv_len, tp_axis=ax)
                return jnp.argmax(logits, axis=-1), k_pool, v_pool

            def _verify(params, k_pool, v_pool, tokens, positions,
                        write_pages, write_offsets, page_table, kv_len):
                # all k+1 positions scored in ONE flash_decode launch;
                # only the argmax ids leave the device
                logits, k_pool, v_pool = decoder.extend(
                    params, k_pool, v_pool, tokens, positions,
                    write_pages, write_offsets, page_table, kv_len,
                    tp_axis=ax)
                return jnp.argmax(logits, axis=-1), k_pool, v_pool

            def _chunk(params, k_pool, v_pool, tokens, positions,
                       write_pages, write_offsets, page_table, kv_len):
                # one chunk of a long context; front-padding pins the
                # chunk's last valid token to the final row, so
                # last_only projects exactly one position through the
                # LM head
                logits, k_pool, v_pool = decoder.extend(
                    params, k_pool, v_pool, tokens, positions,
                    write_pages, write_offsets, page_table, kv_len,
                    last_only=True, tp_axis=ax)
                return jnp.argmax(logits[:, 0], axis=-1), k_pool, v_pool

            pool_donate = (1, 2)

        if self._mesh is not None:
            # place params and pools with their tensor-axis shardings
            # BEFORE anything launches: shard_map pins input shardings,
            # so an unplaced operand would be resharded INSIDE the
            # compiled step — a collective the HLO contract forbids on
            # the decode hot path
            self.params = jax.device_put(self.params,
                                         self._param_shardings())
            self._shard_pools()
            _prefill, _decode, _verify, _chunk = self._shard_map_execs(
                _prefill, _decode, _verify, _chunk)

        # raw step functions + the donation each SHIPS with on TPU,
        # keyed by compiled-shapes-contract name: the ISSUE 13 checker
        # (analysis_executables) re-lowers these with the TPU donation
        # spec forced on, so the committed hlo_contracts.json verifies
        # the contract the production backend actually runs under
        self._exec_defs = {"prefill": (_prefill, ()),
                           "decode": (_decode, pool_donate),
                           "verify": (_verify, pool_donate),
                           "chunk": (_chunk, pool_donate)}
        self._prefill_fn = jax.jit(_prefill)
        # donate the pool buffers on TPU: the decode step would
        # otherwise hold old + new pool alive across every step (the
        # CPU backend doesn't implement donation — gating avoids a
        # warning per test run).  The engine rebinds cache.k/v (and,
        # quantized, the scale planes) to the returned pools
        # immediately, so nothing aliases the donated buffers.
        donate = pool_donate if jax.default_backend() == "tpu" else ()
        self._decode_fn = jax.jit(_decode, donate_argnums=donate)
        self._verify_fn = (jax.jit(_verify, donate_argnums=donate)
                           if self.spec_k > 0 else None)
        self._chunk_fn = (jax.jit(_chunk, donate_argnums=donate)
                          if self.chunk_size is not None else None)

    # -- tensor-parallel plumbing (r17) ------------------------------------

    def _param_specs(self):
        """``PartitionSpec`` pytree mirroring the params pytree:
        wqkv/w1 column-sharded over the tensor axis (each shard owns a
        head slice — see :func:`~apex_tpu.serving.model.
        shard_params_tp` for the wqkv column reorder that makes this
        correct), wo/w2 row-sharded, embeddings / positions / layer
        norms replicated — the Megatron layout, one ``psum`` per
        block."""
        from jax.sharding import PartitionSpec as P
        ax = self._tp_axis
        rep = P()
        ln = {"g": rep, "b": rep}
        layer = {"ln1": dict(ln), "wqkv": P(None, ax),
                 "wo": P(ax, None), "ln2": dict(ln),
                 "w1": P(None, ax), "w2": P(ax, None)}
        return {"embed": rep, "pos": rep, "ln_f": dict(ln),
                "layers": [dict(layer)
                           for _ in range(self.cfg.num_layers)]}

    def _param_shardings(self):
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self._mesh, s), self._param_specs(),
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    def _shard_pools(self) -> None:
        """Place the pool (and scale) arrays on the mesh, sharded on
        their head axis — fresh pools (init / :meth:`recover`) must be
        re-placed or the next step would compile a second, resharding
        executable."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        ax = self._tp_axis
        pool = NamedSharding(self._mesh, P(None, None, None, ax, None))
        self.cache.k = jax.device_put(self.cache.k, pool)
        self.cache.v = jax.device_put(self.cache.v, pool)
        if self.kv_quant is not None:
            sc = NamedSharding(self._mesh, P(None, None, None, ax))
            self.cache.k_scale = jax.device_put(self.cache.k_scale, sc)
            self.cache.v_scale = jax.device_put(self.cache.v_scale, sc)

    def _shard_map_execs(self, _prefill, _decode, _verify, _chunk):
        """Wrap the four step bodies in ``shard_map`` over the tensor
        mesh: pools/scales arrive pre-sharded on their head axis,
        params per :meth:`_param_specs`, everything else replicated.
        The bodies derive their head count from the LOCAL shapes and
        contribute residuals via ``psum`` — the only hot-path
        collectives, pinned per-executable by the HLO contract."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        ax = self._tp_axis
        pool = P(None, None, None, ax, None)
        r = P()
        kv_row = P(None, None, ax, None)
        pspec = self._param_specs()

        def sm(fn, in_specs, out_specs):
            return shard_map(fn, mesh=self._mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)

        pools = ((pool, pool, P(None, None, None, ax),
                  P(None, None, None, ax))
                 if self.kv_quant is not None else (pool, pool))
        outs = (r,) + pools
        _prefill = sm(_prefill, (pspec, r, r, r, r), (r, kv_row, kv_row))
        _decode = sm(_decode, (pspec,) + pools + (r,) * 4, outs)
        _verify = sm(_verify, (pspec,) + pools + (r,) * 6, outs)
        _chunk = sm(_chunk, (pspec,) + pools + (r,) * 6, outs)
        return _prefill, _decode, _verify, _chunk

    # -- quantized-pool plumbing (r17) -------------------------------------

    def _pool_state(self) -> Tuple:
        """The pool loop-carry operands in executable order —
        ``(k, v)`` or, quantized, ``(k, v, k_scale, v_scale)``."""
        if self.kv_quant is not None:
            return (self.cache.k, self.cache.v,
                    self.cache.k_scale, self.cache.v_scale)
        return (self.cache.k, self.cache.v)

    def _bind_pools(self, pools: Tuple) -> None:
        """Rebind the cache to a step's returned pool carries (the
        donated-buffer hand-back)."""
        if self.kv_quant is not None:
            (self.cache.k, self.cache.v,
             self.cache.k_scale, self.cache.v_scale) = pools
        else:
            self.cache.k, self.cache.v = pools

    # -- compiled-artifact exposure (ISSUE 13) -----------------------------

    def _executable_arg_structs(self) -> Dict[str, Tuple]:
        """``jax.ShapeDtypeStruct`` argument tuples per enabled
        executable of the compiled-shapes contract (minus the
        admission scatter, which :class:`PagedKVCache` owns) — the
        same shapes :meth:`warmup` launches, pinned against it by the
        no-drift regression so the analyzed artifacts are the served
        artifacts."""
        sds = jax.ShapeDtypeStruct
        i32 = jnp.int32
        params = jax.tree_util.tree_map(
            lambda a: sds(jnp.shape(a), a.dtype), self.params)
        pool = sds(self.cache.k.shape, self.cache.k.dtype)
        pools = (pool, pool)
        if self.kv_quant is not None:
            scale = sds(self.cache.k_scale.shape, jnp.float32)
            pools = (pool, pool, scale, scale)
        S, b = self.prefill_budget, self.max_batch
        p_max = self.cache.max_pages_per_request
        row = sds((1, S), i32)
        out = {
            "prefill": (params, row, row, row, sds((), i32)),
            "decode": ((params,) + pools
                       + (sds((b,), i32), sds((b,), i32),
                          sds((b, p_max), i32), sds((b,), i32))),
        }
        if self._verify_fn is not None:
            q = sds((b, self.spec_k + 1), i32)
            out["verify"] = ((params,) + pools + (q, q, q, q,
                             sds((b, p_max), i32), sds((b,), i32)))
        if self._chunk_fn is not None:
            c = sds((1, self.chunk_size), i32)
            out["chunk"] = ((params,) + pools + (c, c, c, c,
                            sds((1, p_max), i32), sds((1,), i32)))
        return out

    def analysis_executables(self, *, donate: bool = True) -> Dict[str, Any]:
        """name → ``jax.stages.Lowered`` for every executable of the
        compiled-shapes contract this configuration enables, at the
        engine's exact shapes, with the TPU donation spec FORCED on
        regardless of backend (``__init__`` gates donation off on CPU
        only to avoid the backend-unsupported warning; the shipped
        contract is the TPU one, and that is what the ISSUE 13 checker
        verifies — pool donation machine-checked end-to-end, the PR 8
        768 MB lesson made structural).  ``donate=False`` is the
        checker's own negative control: the donate-stripped artifact
        must FAIL the committed aliasing contract."""
        structs = self._executable_arg_structs()
        lowered: Dict[str, Any] = {}
        for name, (fn, tpu_donate) in self._exec_defs.items():
            if name not in structs:
                continue
            jitted = jax.jit(fn, donate_argnums=tpu_donate if donate else ())
            lowered[name] = jitted.lower(*structs[name])
        lowered["admission_scatter"] = self.cache.analysis_executable(
            self.prefill_budget, donate=donate)
        return {n: lowered[n] for n in SERVING_EXECUTABLES if n in lowered}

    # -- intake ------------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               arrival_t: Optional[float] = None,
               deadline_s: Optional[float] = None) -> Request:
        """Create and queue a request; returns its :class:`Request`
        handle (tokens accumulate on ``.generated``).  ``deadline_s``
        is the completion SLO in seconds after arrival.  A full
        bounded queue does NOT raise: the returned request is already
        terminal (``finish_reason == "rejected"``) and a
        ``request_reject`` event is emitted — the caller checks the
        handle, the trace keeps flowing."""
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not prompt:
            raise ValueError("empty prompt")
        req = Request(rid=self._next_rid, prompt=list(prompt),
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      arrival_t=(self.clock() if arrival_t is None
                                 else arrival_t),
                      deadline_s=deadline_s)
        self._next_rid += 1
        return self._try_submit(req)

    def submit_request(self, req: Request) -> Request:
        """Queue a pre-built request (trace replay); rids must be
        unique per engine.  Same reject semantics as :meth:`submit`."""
        self._next_rid = max(self._next_rid, req.rid + 1)
        return self._try_submit(req)

    def _try_submit(self, req: Request) -> Request:
        """Queue ``req`` or reject it explicitly.  Never-servable
        requests raise ``ValueError`` (caller bug) — unless
        ``reject_unservable`` is set, in which case they finish as
        ``rejected`` with ``reason="unservable"`` so a fleet router
        can tell permanent refusal from backpressure.  A full bounded
        queue is an OVERLOAD signal: the request finishes as
        ``rejected`` with ``reason="queue_full"``, and the engine
        keeps serving what it already accepted."""
        try:
            self.sched.submit(req)
        except QueueFullError:
            self._reject(req, "queue_full")
        except ValueError:
            if not self.reject_unservable:
                raise
            self._reject(req, "unservable")
        return req

    def _reject(self, req: Request, reason: str) -> None:
        req.state = FINISHED
        req.finish_t = self.clock()
        req.finish_reason = "rejected"
        self.rejected.append(req)
        self._emit("request_reject", rid=req.rid, reason=reason,
                   queue_depth=len(self.sched.waiting))

    # -- device steps ------------------------------------------------------

    def warmup(self) -> float:
        """Compile every device executable before any request arrives
        (so TTFT never carries jit-compile wall); returns the seconds
        spent.

        The compiled set is FIXED and documented (docs/serving.md
        "The compiled-shapes contract"): the prefill row, the decode
        step, the admission scatter (``PagedKVCache.write_tokens`` —
        the one warmup originally missed, surfacing as a hidden ~70 ms
        compile on the first admission's TTFT; caught by the
        hot_path_guard serving-lifetime pin, ISSUE 11), plus — when
        the draft–verify subsystem is on (ISSUE 12) — the verify step
        at ``q_len = spec.k + 1`` and the ``[1, chunk_size]`` chunked-
        prefill step.  Every warmup launch writes only into scratch
        page 0, which no reader ever sees; the zero-compiles-after-
        warmup pin runs a speculative + chunked trace too."""
        t0 = time.perf_counter()
        z = jnp.zeros((1, self.prefill_budget), jnp.int32)
        _, wk0, wv0 = self._prefill_fn(
            self.params, z, z, z, np.int32(0))
        # warm the admission scatter with its real shapes: the warmup
        # prefill's K/V row scattered into the scratch page
        S = self.prefill_budget
        self.cache.write_tokens(wk0, wv0, np.zeros((S,), np.int32),
                                np.zeros((S,), np.int32))
        b = self.max_batch
        p_max = self.cache.max_pages_per_request
        out = self._decode_fn(
            self.params, *self._pool_state(),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
            jnp.zeros((b, p_max), jnp.int32), jnp.ones((b,), jnp.int32))
        self._bind_pools(out[1:])
        if self._verify_fn is not None:
            qw = self.spec_k + 1
            zq = jnp.zeros((b, qw), jnp.int32)
            out = self._verify_fn(
                self.params, *self._pool_state(), zq, zq, zq, zq,
                jnp.zeros((b, p_max), jnp.int32),
                jnp.full((b,), qw, jnp.int32))
            self._bind_pools(out[1:])
        if self._chunk_fn is not None:
            cs = self.chunk_size
            zc = jnp.zeros((1, cs), jnp.int32)
            out = self._chunk_fn(
                self.params, *self._pool_state(), zc, zc, zc, zc,
                jnp.zeros((1, p_max), jnp.int32),
                jnp.full((1,), cs, jnp.int32))
            self._bind_pools(out[1:])
        if self.prefix_index is not None:
            # r17: the prefix-sharing engine runs one more executable —
            # the COW page copy — on the admission path; warm it too so
            # the first shared-prefix hit compiles nothing
            self.cache.warm_copy()
        if self.kv_import:
            # r18: a decode replica lands shipped pages through one
            # more executable — warm it so the first inbound shipment
            # compiles nothing (the chaos_disagg zero-recompile pin)
            self.cache.warm_import()
        if self.prefill_only:
            # ... and a prefill replica reads pages OUT through a
            # device-side page-slice gather; warm that too, for the
            # same zero-recompile pin on the export side
            self.cache.warm_export()
        jax.block_until_ready(self.cache.k)
        return time.perf_counter() - t0

    def _prefill_request(self, req: Request) -> None:
        """One fixed-width prefill for one request: compute K/V for the
        whole context (prompt + pre-preemption tokens), scatter it into
        the request's pages, sample the next token."""
        S = self.prefill_budget
        ctx = req.context
        C = len(ctx)
        ps = self.cache.page_size
        # reserve-at-admit invariant (ISSUE 10 satellite): admission
        # allocated this request's context pages; prefill must never
        # find the reservation gone (the admit-then-exhaust window the
        # regression test closes) — a violation here is a scheduler
        # bug, not a capacity event
        need = self.cache.pages_needed(C)
        if len(req.pages) < need:
            raise RuntimeError(
                f"request {req.rid}: prefill found {len(req.pages)} "
                f"reserved pages, context needs {need} — pages must be "
                "reserved at admission")
        _fault_point("prefill", req.rid)
        prefill_t0 = self.clock()
        tokens = np.zeros((1, S), np.int32)
        tokens[0, :C] = ctx
        seg = np.zeros((1, S), np.int32)
        seg[0, :C] = 1
        positions = np.zeros((1, S), np.int32)
        positions[0, :C] = np.arange(C)
        # np.int32 scalar, NOT jnp.asarray(C - 1): converting a python
        # int eagerly compiles a tiny convert executable the warmup
        # never built — a hidden ~60 ms stall on the first admission's
        # TTFT (caught by hot_path_guard's serving-lifetime pin)
        next_tok, k, v = self._prefill_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(seg),
            jnp.asarray(positions), np.int32(C - 1))
        # packed position t -> (page, in-page offset); padding -> scratch
        pages = np.zeros((S,), np.int32)
        offsets = np.zeros((S,), np.int32)
        idx = np.arange(C)
        pages[:C] = np.asarray(req.pages, np.int32)[idx // ps]
        offsets[:C] = idx % ps
        self.cache.write_tokens(k, v, pages, offsets)
        req.kv_len = C
        self._register_prefix(ctx, req.pages)
        req.generated.append(int(next_tok))
        if req.first_token_t is None:
            req.first_token_t = self.clock()
            # colocated path: the token is streamable the instant it
            # is sampled (a shipped request's stream_t is stamped at
            # adoption instead — r19 shipping-aware TTFT)
            req.stream_t = req.first_token_t
        # single-shot prefill = one prefill_chunk span covering the
        # whole context (the chunked path emits one per chunk)
        life = self._life(req)
        self._emit("span", rid=req.rid,
                   span_id=f"{req.rid}:prefill_chunk:{life}:0",
                   parent_id=f"{req.rid}:admit:{life}",
                   kind="prefill_chunk", t_start=prefill_t0,
                   t_end=self.clock())

    def _register_prefix(self, ctx: Sequence[int],
                         pages: List[int]) -> None:
        """Register the PAGE-ALIGNED prefix of a freshly prefilled
        context in the prefix index.  Alignment is deliberate: a
        partial tail page would be shared while its owner's next
        decode append still writes into it, forcing a COW on the
        owner's own hot path — the aligned prefix is immutable by
        construction (every later write lands at positions
        ``>= len(ctx) > aligned``)."""
        if self.prefix_index is None:
            return
        ps = self.cache.page_size
        aligned = (len(ctx) // ps) * ps
        if aligned >= ps:
            self.prefix_index.register(ctx[:aligned],
                                       pages[:aligned // ps])

    def _check_private(self, pages, what: str) -> None:
        """Write-path guard (r17): a device write targeting a page
        with refcount > 1 would corrupt another reader's prefix — COW
        must have swapped in a private copy before the launch.  By
        construction (aligned registration + admission-time COW) this
        never fires; it is the cheap host-side proof."""
        if self.prefix_index is None:
            return
        for p in pages:
            if self.cache.is_shared(int(p)):
                raise RuntimeError(
                    f"{what} would write shared page {int(p)} "
                    "(refcount > 1) — copy-on-write missing")

    def _decode_batch(self, rows: List[Request]) -> None:
        """One decode step for ``rows`` (≤ max_batch), idle-padded to
        the fixed batch width."""
        _fault_point("decode", self.decode_steps)
        # opt-in read-back validation: the pages this step is about to
        # attend over must still match their recorded CRCs
        self.cache.verify_pages([req.pages for req in rows])
        b = self.max_batch
        ps = self.cache.page_size
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        kv_len = np.ones((b,), np.int32)
        written: List[int] = []   # the page each row's new K/V lands in
        for i, req in enumerate(rows):
            tokens[i] = req.generated[-1]
            positions[i] = req.seq_len - 1
            kv_len[i] = req.seq_len
            written.append(req.pages[(req.seq_len - 1) // ps])
        self._check_private(written, "decode append")
        page_table = self.cache.page_table(
            [req.pages for req in rows], rows=b)
        out = self._decode_fn(
            self.params, *self._pool_state(),
            jnp.asarray(tokens), jnp.asarray(positions), page_table,
            jnp.asarray(kv_len))
        next_tok = out[0]
        self._bind_pools(out[1:])
        self.cache.refresh_page_crcs(written)
        next_tok = np.asarray(next_tok)
        for i, req in enumerate(rows):
            req.kv_len = req.seq_len
            req.generated.append(int(next_tok[i]))

    def _verify_batch(self, rows: List[Request],
                      drafts: Dict[int, List[int]]) -> Tuple[int, int, int]:
        """One speculative decode boundary: score every row's last
        committed token + draft in ONE verify launch
        (``q_len = spec.k + 1``), commit each row's longest matching
        prefix + bonus token, roll rejected rows back.

        Rows are FRONT-padded to the fixed window (pad rows scatter
        into scratch and their outputs are discarded), so a row with a
        ``j``-token draft occupies the last ``j + 1`` query rows and
        ``kv_len = seq_len + j`` keeps flash_decode's causal alignment
        exact — a draft-less row (``j = 0``) is literally a plain
        decode step computed through the verify shape.  Rollback is
        plain accounting: ``kv_len`` advances only over committed
        draft rows (stale K/V past it is unreachable and overwritten
        when the sequence grows back), and surplus tail pages return
        to the pool via ``free_tail``.  Returns
        ``(drafted, accepted, committed)`` token counts for the
        ``decode_step`` telemetry fields."""
        _fault_point("decode", self.decode_steps)
        self.cache.verify_pages([req.pages for req in rows])
        b, qw = self.max_batch, self.spec_k + 1
        ps = self.cache.page_size
        tokens = np.zeros((b, qw), np.int32)
        positions = np.zeros((b, qw), np.int32)
        wpages = np.zeros((b, qw), np.int32)
        woffs = np.zeros((b, qw), np.int32)
        kv_len = np.full((b,), qw, np.int32)  # idle rows: kv_len == q_len
        row_draft: List[List[int]] = []
        written: List[int] = []
        for i, req in enumerate(rows):
            d = drafts.get(req.rid, [])
            row_draft.append(d)
            S, j = req.seq_len, len(d)
            pad = qw - (j + 1)
            pos = np.arange(S - 1, S + j)
            tokens[i, pad:] = [req.generated[-1]] + d
            positions[i, pad:] = pos
            pg = np.asarray(req.pages, np.int32)[pos // ps]
            wpages[i, pad:] = pg
            woffs[i, pad:] = pos % ps
            kv_len[i] = S + j
            written.extend(int(p) for p in pg)
        self._check_private(written, "verify append")
        page_table = self.cache.page_table(
            [req.pages for req in rows], rows=b)
        out = self._verify_fn(
            self.params, *self._pool_state(),
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(wpages), jnp.asarray(woffs), page_table,
            jnp.asarray(kv_len))
        next_tok = out[0]
        self._bind_pools(out[1:])
        self.cache.refresh_page_crcs(written)
        next_tok = np.asarray(next_tok)
        drafted = accepted = committed = 0
        for i, req in enumerate(rows):
            d = row_draft[i]
            S, j = req.seq_len, len(d)
            pad = qw - (j + 1)
            out, n_draft_kv, a = commit_tokens(
                d, next_tok[i, pad:].tolist(), eos_id=req.eos_id,
                remaining=req.max_new_tokens - len(req.generated))
            req.generated.extend(out)
            req.kv_len = S + n_draft_kv
            # rollback: pages grown for rejected draft rows go back to
            # the pool (the next boundary's growth re-takes what the
            # committed tokens actually need — lowest-first, so the
            # SAME pages come back, deterministically)
            keep = self.cache.pages_needed(max(req.seq_len, req.kv_len))
            self.cache.free_tail(req.pages, keep)
            drafted += j
            accepted += a
            committed += len(out)
        if self.proposer is not None:
            self.proposer.observe(drafted, accepted)
        return drafted, accepted, committed

    def _chunk_step(self, req: Request, start: int, n: int) -> None:
        """Advance one chunked prefill by ``n <= chunk_size`` tokens:
        compute K/V for context positions ``[start, start + n)``
        against the pages earlier chunks already filled, through the
        fixed ``[1, chunk_size]`` executable (front-padded; pad rows
        scatter into scratch).  The FINAL chunk's last-position argmax
        is the request's first sampled token — earlier chunks never
        pull anything to the host, so a long prefill stays one async
        dispatch per boundary."""
        _fault_point("prefill", req.rid)
        t0 = self.clock()
        # opt-in CRC read-back, like every other pool-reading step:
        # this chunk attends over the pages earlier chunks filled — a
        # corrupted earlier page must raise HERE, before the final
        # chunk could sample the request's first token from damaged
        # K/V and commit it into the stream (review-found, pinned;
        # pages past the filled prefix have no CRC record and are
        # skipped by verify_pages)
        self.cache.verify_pages([req.pages])
        cs = self.chunk_size
        ps = self.cache.page_size
        ctx = req.context
        need = self.cache.pages_needed(start + n)
        if len(req.pages) < need:
            raise RuntimeError(
                f"request {req.rid}: chunk [{start}, {start + n}) found "
                f"{len(req.pages)} reserved pages, needs {need} — pages "
                "must be reserved at admission")
        pad = cs - n
        tokens = np.zeros((1, cs), np.int32)
        positions = np.zeros((1, cs), np.int32)
        wpages = np.zeros((1, cs), np.int32)
        woffs = np.zeros((1, cs), np.int32)
        pos = np.arange(start, start + n)
        tokens[0, pad:] = ctx[start:start + n]
        positions[0, pad:] = pos
        pg = np.asarray(req.pages, np.int32)[pos // ps]
        wpages[0, pad:] = pg
        woffs[0, pad:] = pos % ps
        self._check_private(pg, "chunk scatter")
        page_table = self.cache.page_table([req.pages], rows=1)
        out = self._chunk_fn(
            self.params, *self._pool_state(),
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(wpages), jnp.asarray(woffs), page_table,
            jnp.asarray(np.full((1,), start + n, np.int32)))
        next_tok = out[0]
        self._bind_pools(out[1:])
        self.cache.refresh_page_crcs(int(p) for p in pg)
        req.kv_len = start + n
        req.prefill_pos = start + n
        if req.prefill_pos >= len(ctx):
            # prefill complete: sample the first token and leave
            # chunked mode — the request decodes from the next boundary
            req.prefill_pos = None
            self._register_prefix(ctx, req.pages)
            req.generated.append(int(np.asarray(next_tok)[0]))
            if req.first_token_t is None:
                req.first_token_t = self.clock()
                req.stream_t = req.first_token_t
        life = self._life(req)
        self._emit("span", rid=req.rid,
                   span_id=f"{req.rid}:prefill_chunk:{life}:{start}",
                   parent_id=f"{req.rid}:admit:{life}",
                   kind="prefill_chunk", t_start=t0,
                   t_end=self.clock())

    # -- the engine step ---------------------------------------------------

    def _emit(self, type_: str, **payload) -> None:
        if self.telemetry is not None:
            self.telemetry.emit(type_, step=self.steps, **payload)

    @staticmethod
    def _life(req: Request) -> str:
        """The r19 admission-life discriminator shared by every span
        of one (re)admission — ``preemptions`` alone is not unique
        across a fallback re-admission, ``admit_t`` on the shared
        clock makes it so (docs/tracing.md, "Span identity")."""
        return f"{req.preemptions}:{req.admit_t:.6f}"

    def _retire(self, now: float) -> List[Request]:
        done = self.sched.retire_finished(now)
        for req in done:
            if self.proposer is not None:
                self.proposer.release(req.rid)
            n = len(req.generated)
            ev = dict(rid=req.rid, reason=req.finish_reason,
                      new_tokens=n, preemptions=req.preemptions)
            # r19 shipping-aware TTFT (the PR 18 open item): measure
            # to stream_t — when the first token became STREAMABLE —
            # so a disaggregated request's kv_ship wall lands in TTFT
            # (where the SLO feels it), not hidden inside TPOT.
            # Colocated paths have stream_t == first_token_t; a
            # migrated re-prefill keeps the original first-token time
            # (the client already held those tokens).
            stream_t = (req.stream_t if req.stream_t is not None
                        else req.first_token_t)
            if req.first_token_t is not None:
                ev["ttft_ms"] = round(
                    (stream_t - req.arrival_t) * 1e3, 3)
                if req.ship_s > 0.0:
                    ev["ship_ms"] = round(req.ship_s * 1e3, 3)
                if n > 1 and req.finish_t is not None:
                    ev["tpot_ms"] = round(
                        (req.finish_t - stream_t) / (n - 1) * 1e3,
                        3)
            if req.deadline_t is not None and req.finish_t is not None:
                # a real bool, present only when a deadline existed —
                # optionality explicit, never a sentinel
                ev["deadline_hit"] = bool(req.finish_t <= req.deadline_t)
            self._emit("request_retire", **ev)
            self._emit_retire_spans(req, stream_t, now)
        return done

    def _emit_retire_spans(self, req: Request, stream_t, now: float
                           ) -> None:
        """The decode-side tail of the request's trace (r19), emitted
        once at retirement — spans buffer host-side state only, no
        device fetches, so the decode loop stays host-sync-free:
        ``decode_wait`` (prefill done -> streamable: the export-pump
        wait plus the kv_ship wall on a disaggregated path, ~0
        colocated), ``decode_steps`` (stream -> finish), and the
        ``stream_emit`` point span the TTFT decomposition ends at."""
        if self.telemetry is None or stream_t is None \
                or req.admit_t is None:
            return
        life = self._life(req)
        dw = f"{req.rid}:decode_wait:{life}"
        self._emit("span", rid=req.rid, span_id=dw,
                   parent_id=f"{req.rid}:admit:{life}",
                   kind="decode_wait", t_start=req.first_token_t,
                   t_end=stream_t)
        self._emit("span", rid=req.rid,
                   span_id=f"{req.rid}:decode_steps:{life}",
                   parent_id=dw, kind="decode_steps",
                   t_start=stream_t, t_end=now)
        self._emit("span", rid=req.rid,
                   span_id=f"{req.rid}:stream_emit:{life}",
                   parent_id=dw, kind="stream_emit",
                   t_start=stream_t, t_end=stream_t)

    def _expire(self, now: float) -> bool:
        """Deadline enforcement for this step boundary: shed queued
        requests that can no longer meet their SLO, retire in-flight
        expirations with a ``timeout`` status (pages freed
        immediately).  Each drop is a ``request_timeout`` event saying
        WHERE the request was when its deadline died."""
        shed, timed_out = self.sched.expire_deadlines(
            now, min_service_s=self.shed_min_service_s)
        if self.proposer is not None:
            # deadline deaths are retirements too — every terminal
            # transition must drop per-rid proposer state (the timeout
            # path leaked the suffix cache; review-found, pinned)
            for req in shed + timed_out:
                self.proposer.release(req.rid)
        for req in shed:
            self._emit("request_timeout", rid=req.rid, where="queued",
                       overshoot_ms=round((now - req.deadline_t) * 1e3, 3))
        for req in timed_out:
            self._emit("request_timeout", rid=req.rid, where="running",
                       overshoot_ms=round((now - req.deadline_t) * 1e3, 3))
        return bool(shed or timed_out)

    def step(self) -> bool:
        """One engine iteration: expire deadlines → retire →
        admit+prefill → retire → grow/preempt → decode.  Returns True
        if any work was done.  With a ``watchdog``, the whole step
        (prefill + decode device work included) runs under an armed
        deadline, so a wedged device step escalates instead of
        hanging the trace."""
        if self.watchdog is None:
            return self._step_body()
        with self.watchdog.step(self.steps):
            return self._step_body()

    def _propose_drafts(self) -> Dict[int, List[int]]:
        """Ask the proposer for each decode-ready row's draft, clamped
        so the commit can never overshoot ``max_new_tokens`` (which
        also bounds every written position under ``max_position`` —
        the submit-time ``prompt + max_new <= max_position`` check
        makes the clamp transitive).  Empty drafts mean plain decode."""
        drafts: Dict[int, List[int]] = {}
        for req in self.sched.running:
            if req.prefill_pos is not None:
                continue   # mid-chunk: nothing to decode yet
            k_eff = min(self.spec_k,
                        req.max_new_tokens - len(req.generated) - 1)
            if k_eff <= 0:
                continue
            d = self.proposer.propose(req.rid, req.context, k_eff)
            if d:
                drafts[req.rid] = [int(t) for t in d[:k_eff]]
        return drafts

    def _step_body(self) -> bool:
        now = self.clock()
        progress = self._expire(now)
        progress = bool(self._retire(now)) or progress
        if self.chunk_size is not None:
            chunk_plan, admitted = self.sched.schedule_prefill()
        else:
            chunk_plan, admitted = [], self.sched.admit()
        for req in admitted:
            req.admit_t = now
            ctx_tokens = req.seq_len   # == len(context), O(1)
            if req.prefill_pos is None:
                self._prefill_request(req)
            ev = dict(rid=req.rid, context_tokens=ctx_tokens,
                      pages=len(req.pages), preemptions=req.preemptions)
            if req.prefill_pos is not None:
                ev["chunked"] = True
            if self.prefix_index is not None:
                # a real bool on EVERY admission while sharing is on
                # (hits and misses both) — the summarize hit-rate needs
                # the denominator, and optional-means-absent would make
                # a miss indistinguishable from a sharing-off engine
                ev["prefix_hit"] = bool(req.prefix_hit)
            self._emit("request_admit", **ev)
            # r19 trace: every (re)admission opens a new life —
            # queue_wait is root-level (arrival -> admission), admit
            # covers the admission itself plus a whole-row prefill
            # (a chunked admission's prefill wall rides its
            # prefill_chunk child spans instead)
            life = self._life(req)
            qid = f"{req.rid}:queue_wait:{life}"
            self._emit("span", rid=req.rid, span_id=qid,
                       kind="queue_wait", t_start=req.arrival_t,
                       t_end=now)
            self._emit("span", rid=req.rid,
                       span_id=f"{req.rid}:admit:{life}",
                       parent_id=qid, kind="admit", t_start=now,
                       t_end=self.clock())
            progress = True
        for req, start, n in chunk_plan:
            self._chunk_step(req, start, n)
            progress = True
        # a request whose budget was a single token is done at prefill
        progress = bool(self._retire(now)) or progress
        evicted: List[Request] = []
        drafts: Dict[int, List[int]] = {}
        if self.sched.running and not self.prefill_only:
            if self.proposer is not None:
                drafts = self._propose_drafts()
            # growth covers each drafted row's verify footprint too
            # (seq_len + draft); a row preempted while growing simply
            # drops out of this boundary, draft unused — the proposer
            # is stateless over committed tokens, so nothing leaks
            evicted = self.sched.ensure_decode_capacity(
                extra={rid: len(d) for rid, d in drafts.items()}
                or None)
        # a prefill_only engine never decodes: finished prefills hold
        # their first token and wait for export_request to ship them
        rows = ([] if self.prefill_only else
                [r for r in self.sched.running if r.prefill_pos is None])
        if rows:
            t0 = self.clock()
            spec_fields = {}
            if any(r.rid in drafts for r in rows):
                drafted, accepted, committed = self._verify_batch(
                    rows, drafts)
                new_tokens = committed
                spec_fields = {"spec_verify": True,
                               "spec_drafted": drafted,
                               "spec_accepted": accepted}
            else:
                # every draft came back empty (or speculation is off):
                # the plain q_len=1 decode executable is cheaper
                self._decode_batch(rows)
                new_tokens = len(rows)
            if self.prefix_index is not None:
                # pages with refcount > 1 right now — the live measure
                # of how much pool the sharing is actually saving
                spec_fields["pool_shared_pages"] = self.cache.pages_shared
            self.decode_steps += 1
            # evictions ride the decode_step payload (a preempted
            # request is also visible later: its re-admission's
            # request_admit carries preemptions > 0)
            self._emit("decode_step", batch=len(rows),
                       new_tokens=new_tokens,
                       pool_used=self.cache.pages_used,
                       pool_pages=self.cache.num_pages - 1,
                       evicted=[r.rid for r in evicted],
                       step_ms=round((self.clock() - t0) * 1e3, 3),
                       **spec_fields)
            progress = True
        elif evicted or chunk_plan:
            progress = True
        self.steps += 1
        if isinstance(self.clock, SimClock):
            self.clock.advance()
        return progress

    # -- crash recovery (ISSUE 10) -----------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Serializable capture of the HOST-side serving state: queue
        order (running first, in admission order, then waiting) plus
        each live request's token state and counters.

        KV pages are DELIBERATELY excluded: the PR 8 preemption
        contract makes re-prefill from the kept tokens regenerate a
        request's KV deterministically, so the pool never needs to be
        checkpointed — the snapshot is a few KB of tokens, not
        gigabytes of HBM.  ``restore`` re-prefills live requests
        through that existing path.  JSON-serializable by construction
        (pinned in the round-trip test)."""
        def rec(req: Request, was_running: bool) -> Dict[str, Any]:
            return {
                "rid": req.rid,
                "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "arrival_t": req.arrival_t,
                "deadline_s": req.deadline_s,
                "generated": list(req.generated),
                "preemptions": req.preemptions,
                "admit_t": req.admit_t,
                "first_token_t": req.first_token_t,
                "was_running": was_running,
            }

        return {
            "format": 1,
            "next_rid": self._next_rid,
            "steps": self.steps,
            "decode_steps": self.decode_steps,
            "requests": ([rec(r, True) for r in self.sched.running]
                         + [rec(r, False) for r in self.sched.waiting]),
        }

    def restore(self, snap: Dict[str, Any]) -> List[Request]:
        """Rebuild serving state from a :meth:`snapshot` into THIS
        (idle, freshly constructed) engine.  Every snapshotted request
        — running or waiting at capture — enters the waiting queue in
        snapshot order with no pages; previously-running requests are
        re-admitted first and re-prefilled through the deterministic
        preemption path, so the continued token streams are bitwise
        the uninterrupted run's.  Returns the restored request
        handles."""
        if self.sched.running or self.sched.waiting:
            raise RuntimeError(
                "restore into a busy engine — serving state would be "
                "interleaved; restore only into a fresh engine")
        if snap.get("format") != 1:
            raise ValueError(
                f"unknown serving snapshot format {snap.get('format')!r}")
        restored: List[Request] = []
        for r in snap["requests"]:
            req = Request(
                rid=int(r["rid"]), prompt=list(r["prompt"]),
                max_new_tokens=int(r["max_new_tokens"]),
                eos_id=r["eos_id"], arrival_t=float(r["arrival_t"]),
                deadline_s=r["deadline_s"])
            req.generated = list(r["generated"])
            req.preemptions = int(r["preemptions"])
            req.admit_t = r["admit_t"]
            req.first_token_t = r["first_token_t"]
            restored.append(req)
        # validate BEFORE mutating anything, so a refused restore is
        # atomic (no half-queued engine, no duplicated retire events
        # on a retry into a fresh engine): every live request must be
        # servable by THIS engine's geometry — a chunked engine's
        # snapshot restored into a chunk-less one would otherwise
        # queue a beyond-the-row request admission can never take,
        # starving the whole FIFO forever (review-found, pinned; the
        # twin of recover()'s chunk_size-preserving rebuild)
        live = [req for req in restored if not req.done]
        for req in live:
            self.sched.check_servable(req)
        if self.sched.max_queue is not None and \
                len(live) > self.sched.max_queue:
            # capacity mismatch is refused with the same atomicity as
            # geometry mismatch (ISSUE 16): a migration target that
            # cannot QUEUE the batch must refuse before mutating, so
            # the caller can pick another target with the snapshot
            # intact
            raise ValueError(
                f"snapshot holds {len(live)} live requests > "
                f"max_queue {self.sched.max_queue}")
        for req in restored:
            if req.done:
                # captured between its last decode and its retirement:
                # already complete — re-admitting would overshoot
                # max_new_tokens by re-prefilling + sampling again
                self._finish_restored(req)
            else:
                req.state = WAITING
                self.sched.waiting.append(req)
        self._next_rid = max(self._next_rid, int(snap["next_rid"]))
        self.steps = int(snap["steps"])
        self.decode_steps = int(snap["decode_steps"])
        return restored

    def adopt(self, records: Sequence[Dict[str, Any]]) -> List[Request]:
        """Admit snapshot-format request records into THIS possibly
        BUSY engine — the fleet migration path (ISSUE 16).
        :meth:`restore` refuses a busy target by design; a healthy
        replica receiving a fenced peer's requests is mid-service, so
        migration needs an entry point that merges into live state.

        Validation is ATOMIC: every record must be servable by this
        engine's geometry, must not collide with a live rid, and the
        whole batch must fit the remaining ``max_queue`` headroom —
        all checked before any state mutates, so a refused adopt
        leaves the engine exactly as it was and the caller can try
        another target.  Live records enter the waiting queue pageless
        (the deterministic re-prefill path rebuilds their KV, exactly
        as restore/recover do); already-done records retire
        immediately.  Returns this engine's new request handles — the
        source replica's old handles are dead."""
        adopted: List[Request] = []
        for r in records:
            req = Request(
                rid=int(r["rid"]), prompt=list(r["prompt"]),
                max_new_tokens=int(r["max_new_tokens"]),
                eos_id=r["eos_id"], arrival_t=float(r["arrival_t"]),
                deadline_s=r["deadline_s"])
            req.generated = list(r["generated"])
            req.preemptions = int(r["preemptions"])
            req.admit_t = r["admit_t"]
            req.first_token_t = r["first_token_t"]
            adopted.append(req)
        live = [req for req in adopted if not req.done]
        live_rids = ({q.rid for q in self.sched.running}
                     | {q.rid for q in self.sched.waiting})
        for req in live:
            self.sched.check_servable(req)
            if req.rid in live_rids:
                raise ValueError(
                    f"adopt: rid {req.rid} collides with a live "
                    "request — migration requires a fleet-global rid "
                    "namespace")
        if self.sched.max_queue is not None and \
                len(self.sched.waiting) + len(live) > self.sched.max_queue:
            raise ValueError(
                f"adopt: {len(live)} live records exceed queue "
                f"headroom ({len(self.sched.waiting)}/"
                f"{self.sched.max_queue} waiting)")
        for req in adopted:
            self._next_rid = max(self._next_rid, req.rid + 1)
            if req.done:
                self._finish_restored(req)
            else:
                req.state = WAITING
                self.sched.waiting.append(req)
        return adopted

    # -- disaggregated prefill/decode (r18) --------------------------------

    def export_request(self, rid: int):
        """Detach a freshly prefilled request for shipping (the
        prefill-replica side of r18 disaggregation): serialize its KV
        pages (:meth:`PagedKVCache.export_page_bytes` — per-page CRC
        stamped at export), capture its snapshot-format record
        (first token included in ``generated``), then release its
        local footprint.  Returns ``(record, pages_payload, kv_len)``.

        The request must be RUNNING with prefill complete
        (``prefill_pos is None``) and hold its first token — i.e. it
        is exactly at the point where a colocated engine would start
        decoding.  Locally it finishes as ``"shipped"`` (NOT counted
        in ``sched.finished`` — it retires for real on the decode
        replica); the caller's handle on the DECODE replica is the
        live one after adoption."""
        req = next((r for r in self.sched.running if r.rid == rid), None)
        if req is None:
            raise ValueError(f"export_request: rid {rid} is not running")
        if req.prefill_pos is not None or not req.generated:
            raise ValueError(
                f"export_request: rid {rid} has not finished prefill")
        t0 = self.clock()
        pages_payload = [self.cache.export_page_bytes(p)
                         for p in req.pages]
        # r19 trace: the export span opens the ship segment of the
        # TTFT decomposition (kv_export.start -> kv_import.end);
        # export_t/export_span ride the record so the decode side can
        # account the ship wall and parent its spans without parsing
        # ids (adopt ignores unknown record keys by construction)
        life = self._life(req)
        export_span = f"{req.rid}:kv_export:{life}"
        self._emit("span", rid=req.rid, span_id=export_span,
                   parent_id=f"{req.rid}:admit:{life}",
                   kind="kv_export", t_start=t0, t_end=self.clock())
        record = {
            "rid": req.rid,
            "prompt": list(req.prompt),
            "max_new_tokens": req.max_new_tokens,
            "eos_id": req.eos_id,
            "arrival_t": req.arrival_t,
            "deadline_s": req.deadline_s,
            "generated": list(req.generated),
            "preemptions": req.preemptions,
            "admit_t": req.admit_t,
            "first_token_t": req.first_token_t,
            "was_running": True,
            "export_t": t0,
            "export_span": export_span,
        }
        kv_len = req.kv_len
        self.sched.running.remove(req)
        self.cache.free(req.pages)
        req.pages = []
        req.kv_len = 0
        req.state = FINISHED
        req.finish_reason = "shipped"
        if self.proposer is not None:
            self.proposer.release(req.rid)
        return record, pages_payload, kv_len

    def adopt_prefilled(self, record: Dict[str, Any],
                        pages_payload: Sequence[Dict[str, Any]],
                        kv_len: int) -> Request:
        """Admit one SHIPPED prefill straight into the decode batch
        (the decode-replica side of r18): re-verify each page payload
        host-side, allocate local pages, land the bytes verbatim, and
        enter RUNNING with the source's token state — decode proceeds
        as if this engine had prefilled locally, bitwise.

        Validation is atomic, in the :meth:`adopt` discipline —
        geometry, rid collision, page-count arithmetic, and per-page
        CRC all checked before any state mutates (a corrupted page is
        NEVER adopted; the sender re-ships it).  Capacity refusals
        (no decode batch slot, no pool pages) raise
        :class:`AdmissionRefused` — retryable, leaving the engine
        untouched."""
        kv_len = int(kv_len)
        req = Request(
            rid=int(record["rid"]), prompt=list(record["prompt"]),
            max_new_tokens=int(record["max_new_tokens"]),
            eos_id=record["eos_id"], arrival_t=float(record["arrival_t"]),
            deadline_s=record["deadline_s"])
        req.generated = list(record["generated"])
        req.preemptions = int(record["preemptions"])
        req.admit_t = record["admit_t"]
        req.first_token_t = record["first_token_t"]
        self.sched.check_servable(req)
        live_rids = ({q.rid for q in self.sched.running}
                     | {q.rid for q in self.sched.waiting})
        if req.rid in live_rids:
            raise ValueError(
                f"adopt_prefilled: rid {req.rid} collides with a live "
                "request — shipping requires a fleet-global rid "
                "namespace")
        need = self.cache.pages_needed(kv_len)
        if len(pages_payload) != need:
            raise ValueError(
                f"adopt_prefilled: rid {req.rid} shipped "
                f"{len(pages_payload)} pages for kv_len {kv_len} "
                f"(expected {need})")
        for i, data in enumerate(pages_payload):
            if not verify_page_payload(data):
                raise ValueError(
                    f"adopt_prefilled: rid {req.rid} page {i} failed "
                    "CRC verification — corrupted in flight, refusing "
                    "to adopt")
        if len(self.sched.running) >= self.max_batch:
            raise AdmissionRefused(
                f"adopt_prefilled: decode batch full "
                f"({len(self.sched.running)}/{self.max_batch})")
        try:
            pages = self.cache.allocate(need, req.rid)
        except PagePoolExhausted as e:
            raise AdmissionRefused(str(e)) from e
        for page, data in zip(pages, pages_payload):
            self.cache.import_page_bytes(page, data)
        req.pages = pages
        req.kv_len = kv_len
        req.state = RUNNING
        self.sched.running.append(req)
        self._next_rid = max(self._next_rid, req.rid + 1)
        # r19 shipping-aware SLO accounting: the first token was
        # sampled at export but is only STREAMABLE now that its KV
        # landed here — stamp adoption as stream_t and book the
        # export->adopt wall as the request's kv_ship cost (== its
        # kv_export.start -> kv_import.end span segment); _retire
        # moves that wall into TTFT instead of hiding it in TPOT
        now = self.clock()
        req.stream_t = now
        export_t = record.get("export_t")
        if export_t is not None:
            req.ship_s = max(0.0, now - float(export_t))
        self._emit("request_admit", rid=req.rid,
                   context_tokens=kv_len, pages=len(pages),
                   preemptions=req.preemptions)
        return req

    def _finish_restored(self, req: Request) -> None:
        """Retire a request that was already done when the crash hit
        (its last decode ran, retirement hadn't).  The retire event
        carries no finish timing — the crashed run took those
        measurements down with it; optional means absent."""
        req.state = FINISHED
        req.finish_reason = (
            "eos" if req.eos_id is not None and req.generated
            and req.generated[-1] == req.eos_id else "length")
        self.sched.finished.append(req)
        if self.proposer is not None:
            # every retirement path must drop per-rid proposer state —
            # recovery-path retirements leaked the suffix cache
            self.proposer.release(req.rid)
        self._emit("request_retire", rid=req.rid, reason=req.finish_reason,
                   new_tokens=len(req.generated),
                   preemptions=req.preemptions)

    def recover(self, cause: str) -> None:
        """In-process crash recovery after a device loss / pool
        corruption: discard the device pool (its content is garbage or
        gone), rebuild a fresh one, and put every live request back on
        the waiting queue — running requests first, in admission
        order, tokens kept.  Re-admission re-prefills them through the
        deterministic path, so recovery is output-invisible (the
        acceptance pin: per-request token streams bitwise identical to
        an uninterrupted control).  The caller's :class:`Request`
        handles stay live — this is the in-process twin of
        :meth:`snapshot`/:meth:`restore`."""
        running = list(self.sched.running)
        waiting = list(self.sched.waiting)
        old = self.cache
        self.cache = PagedKVCache(
            num_layers=self.cfg.num_layers, num_pages=old.num_pages,
            page_size=old.page_size, num_heads=self.cfg.num_heads,
            head_dim=self.cfg.head_dim,
            max_pages_per_request=old.max_pages_per_request,
            dtype=self.cfg.dtype, crc_pages=old.crc_pages,
            # the rebuilt pool keeps its quantization mode: re-prefill
            # re-quantizes deterministically (per-(token, head) scales
            # are order-independent), so recovery stays output-
            # invisible at the documented quantized parity bar
            quantize=self.kv_quant)
        if self._mesh is not None:
            self._shard_pools()
        if self.prefix_index is not None:
            # the index pointed into the dead pool; rebuild it EMPTY —
            # shared prefixes re-register as re-admissions complete
            # (warm-cache opportunism is rebuildable, like KV)
            self.prefix_index = PrefixIndex(
                self.cache, max_entries=self.prefix_entries)
        sched = ContinuousBatchingScheduler(
            self.cache, max_batch=self.max_batch,
            prefill_budget=self.prefill_budget,
            max_position=self.cfg.max_position,
            max_queue=self.sched.max_queue,
            preempt_cap=self.sched.preempt_cap,
            # the rebuilt scheduler must keep chunking (ISSUE 12): a
            # chunk-less rebuild would strand any live request whose
            # context exceeds the prefill row — schedule_prefill could
            # never re-admit it, and FIFO admission would starve
            # everything queued behind it (review-found, pinned)
            chunk_size=self.chunk_size,
            prefix_index=self.prefix_index)
        sched.finished = self.sched.finished   # history survives
        self.sched = sched
        for req in running:
            req.pages = []
            req.kv_len = 0
            # a mid-chunk request restarts its chunked prefill after
            # the rebuild — chunk progress is as rebuildable as KV
            req.prefill_pos = None
            if req.done:
                # complete-but-unretired at the fault boundary: finish
                # it here rather than re-prefill past max_new_tokens
                self._finish_restored(req)
            else:
                req.state = WAITING
                sched.waiting.append(req)
        sched.waiting.extend(waiting)
        # re-place the params on the (rebuilt) device; the jitted
        # executables are shape-keyed and survive as-is.  Under tp the
        # re-placement must restore the tensor-axis shardings, or the
        # next step would compile a resharding variant.
        if self._mesh is not None:
            self.params = jax.device_put(self.params,
                                         self._param_shardings())
        else:
            self.params = jax.device_put(self.params)
        self.recoveries += 1
        self._emit("serving_recovery", cause=cause, pool_rebuilt=True,
                   running_restored=len(running),
                   waiting_restored=len(waiting))

    def _handle_fault(self, exc: BaseException) -> None:
        """Absorb a recoverable mid-decode fault via :meth:`recover`,
        or re-raise when recovery is disabled/exhausted — exhaustion
        first dumps the flight-recorder ring as a trace bundle (r19):
        the chaos outcome ships its own post-mortem."""
        if not self.recover_on_fault or self.recoveries >= self.max_recoveries:
            if self.recover_on_fault and self.telemetry is not None:
                from apex_tpu.telemetry.tracing import \
                    maybe_dump_flight_record

                maybe_dump_flight_record(
                    self.telemetry,
                    f"recovery_exhausted:{type(exc).__name__}",
                    step=self.steps)
            raise exc
        device_ids = getattr(exc, "device_ids", None)
        if device_ids is not None:
            self._emit("device_loss", device_ids=list(device_ids))
        cause = ("device_loss" if device_ids is not None
                 else "page_corruption")
        self.recover(cause=cause)

    # -- drivers -----------------------------------------------------------

    def _guarded_step(self) -> None:
        """One step with the ISSUE 10 recovery net: a mid-decode
        device loss or CRC-caught page corruption triggers rebuild +
        restore + continue instead of killing the trace."""
        from apex_tpu.resilience.chaos import DeviceLossError

        try:
            self.step()
        except (DeviceLossError, PagePoolCorruption) as e:
            self._handle_fault(e)

    def run(self, max_steps: int = 100_000, *,
            raise_on_stall: bool = True) -> List[Request]:
        """Step until every queued request has finished; returns the
        finished list (scheduler order).  Exhausting ``max_steps``
        with live requests still queued is a STALL: a
        ``serving_stall`` event is emitted either way (a wedged fleet
        member must be observable, not quietly partial — ISSUE 16),
        then the engine raises, or returns the partial finished list
        under ``raise_on_stall=False``."""
        for _ in range(max_steps):
            if self.sched.idle:
                break
            self._guarded_step()
        else:
            self._emit("serving_stall",
                       waiting=len(self.sched.waiting),
                       running=len(self.sched.running),
                       budget=max_steps)
            if raise_on_stall:
                raise RuntimeError(
                    f"engine did not drain in {max_steps} steps")
        self._retire(self.clock())
        return self.sched.finished

    def serve(self, trace: Sequence[Request], *,
              max_steps: int = 1_000_000,
              raise_on_stall: bool = True) -> List[Request]:
        """Run an arrival trace (requests sorted by ``arrival_t``):
        each request is submitted once the clock passes its arrival
        time; with a real clock the engine sleeps through idle gaps,
        with a :class:`SimClock` it advances virtual time.  Trace
        arrival times are RELATIVE to the start of the call — they are
        rebased in place onto the engine clock, so TTFT (first token
        minus arrival) is measured on one time base.  Requests are
        therefore SINGLE-USE: re-serving a trace object would
        double-rebase its arrivals (and replay half-mutated request
        state), so a non-fresh request is rejected up front —
        regenerate the trace instead."""
        pending = sorted(trace, key=lambda r: (r.arrival_t, r.rid))
        for req in pending:
            if req.state != WAITING or req.generated or req.pages \
                    or req.kv_len:
                raise ValueError(
                    f"request {req.rid} is not fresh "
                    f"(state={req.state!r}) — trace requests are "
                    "single-use; regenerate the trace")
        t_base = self.clock()
        for req in pending:
            req.arrival_t += t_base
        i = 0
        for _ in range(max_steps):
            now = self.clock()
            while i < len(pending) and pending[i].arrival_t <= now:
                self.submit_request(pending[i])
                i += 1
            if not self.sched.idle:
                self._guarded_step()
            elif i < len(pending):
                gap = pending[i].arrival_t - now
                if isinstance(self.clock, SimClock):
                    self.clock.advance()
                elif gap > 0:
                    time.sleep(min(gap, 0.05))
            else:
                break
        else:
            self._emit("serving_stall",
                       waiting=len(self.sched.waiting),
                       running=len(self.sched.running),
                       budget=max_steps)
            if raise_on_stall:
                raise RuntimeError(
                    f"trace did not drain in {max_steps} steps")
        self._retire(self.clock())
        return self.sched.finished
