"""The serving engine: the device-facing half of continuous batching.

:class:`ServingEngine` turns the :class:`~apex_tpu.serving.scheduler.
ContinuousBatchingScheduler`'s host-side decisions into exactly two
compiled device functions, each traced ONCE for the engine's lifetime:

* **prefill** — a fixed-width packed row (``[1, prefill_budget]``
  tokens + segment ids + per-segment positions) through
  :meth:`~apex_tpu.serving.model.PagedDecoder.prefill`, returning the
  greedy next-token per position and per-layer K/V, which the engine
  scatters into the request's freshly allocated pages.
* **decode** — a fixed-width ``[max_batch]`` step through
  :meth:`~apex_tpu.serving.model.PagedDecoder.decode`: append each
  row's newest token's K/V into its current page, attend over the
  row's page list via :func:`~apex_tpu.ops.flash_decode`, sample
  greedily.  Idle rows are pointed at the scratch page and ignored.

Admitting, retiring, growing or preempting requests between steps
never changes a device shape, so the serving lifetime sees exactly two
XLA compilations.

**The isolation contract (and why prefill is one request per row).**
The acceptance bar for this engine is bitwise: batched continuous
decoding must produce exactly the tokens sequential one-request-at-a-
time decoding produces.  Decode is row-wise by construction, but a
packed prefill row holding SEVERAL segments is not offset-invariant —
the attention contraction reduces over the packed axis, and XLA's
blocked reduction groups differently depending on where in the row a
segment starts (measured: a segment at offset 17 differs from offset 0
in the last ulp, enough to flip a greedy tie).  So the engine prefills
each admitted request in its OWN fixed-width row at offset 0: the
varlen packed machinery (segment ids mask the padding) with exactly
one segment per row.  Admission still batches — the scheduler admits
many requests per step — but each prefill launch serves one request.
The multi-segment form of :meth:`PagedDecoder.prefill` remains
available for throughput-over-isolation deployments; the engine does
not use it (docs/serving.md, "Prefill isolation").

Telemetry: every lifecycle edge lands on the PR 4 bus as one of the
three serving event types — ``request_admit``, ``request_retire``
(with per-request TTFT/TPOT), ``decode_step`` (batch width, tokens,
page-pool occupancy) — so ``python -m apex_tpu.telemetry summarize``
renders a serving line and the bench's stream is schema-validated by
the existing ``validate`` CLI.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.kv_cache import PagedKVCache
from apex_tpu.serving.model import (PagedDecoder, ServingModelConfig,
                                    init_params)
from apex_tpu.serving.scheduler import (WAITING,
                                        ContinuousBatchingScheduler,
                                        Request)


class SimClock:
    """Deterministic virtual clock for tests: ``now()`` returns the
    current virtual time; the engine's step advances it by a fixed
    tick, so a seeded arrival trace replays bit-identically with no
    wall-clock in the loop."""

    def __init__(self, tick: float = 1.0):
        self.t = 0.0
        self.tick = tick

    def __call__(self) -> float:
        return self.t

    def advance(self) -> None:
        self.t += self.tick


def poisson_trace(seed: int, n_requests: int, *, rate: float,
                  prompt_len: Tuple[int, int], max_new: Tuple[int, int],
                  vocab_size: int,
                  eos_id: Optional[int] = None) -> List[Request]:
    """Seeded Poisson arrival trace: exponential inter-arrival gaps at
    ``rate`` requests/s, uniform prompt lengths and generation budgets.
    Deterministic in ``seed`` — the serving bench's workload and the
    scheduler determinism test share this generator."""
    rng = np.random.RandomState(seed)
    t = 0.0
    out: List[Request] = []
    for rid in range(n_requests):
        t += float(rng.exponential(1.0 / rate))
        plen = int(rng.randint(prompt_len[0], prompt_len[1] + 1))
        out.append(Request(
            rid=rid,
            prompt=[int(x) for x in rng.randint(0, vocab_size, plen)],
            max_new_tokens=int(rng.randint(max_new[0], max_new[1] + 1)),
            eos_id=eos_id,
            arrival_t=t,
        ))
    return out


class ServingEngine:
    """Continuous-batching inference over a paged KV cache.

    ``num_pages``/``page_size`` size the shared pool;
    ``prefill_budget`` fixes the packed prefill row width (defaults to
    ``cfg.max_position``) and bounds prompt+generation per request;
    ``max_batch`` fixes the decode batch width.  ``telemetry`` is an
    optional :class:`~apex_tpu.telemetry.TelemetryBus`; ``clock`` an
    optional ``() -> float`` (tests pass :class:`SimClock` for
    deterministic timing fields — timing never feeds scheduling
    decisions, only metrics).
    """

    def __init__(self, cfg: ServingModelConfig, params=None, *,
                 num_pages: int, page_size: int = 64,
                 max_batch: int = 8,
                 max_pages_per_request: Optional[int] = None,
                 prefill_budget: Optional[int] = None,
                 telemetry=None,
                 clock: Optional[Callable[[], float]] = None,
                 seed: int = 0):
        self.cfg = cfg
        self.params = params if params is not None else init_params(cfg, seed)
        self.prefill_budget = (cfg.max_position if prefill_budget is None
                               else prefill_budget)
        if max_pages_per_request is None:
            max_pages_per_request = -(-self.prefill_budget // page_size)
        self.cache = PagedKVCache(
            num_layers=cfg.num_layers, num_pages=num_pages,
            page_size=page_size, num_heads=cfg.num_heads,
            head_dim=cfg.head_dim,
            max_pages_per_request=max_pages_per_request,
            dtype=cfg.dtype)
        self.sched = ContinuousBatchingScheduler(
            self.cache, max_batch=max_batch,
            prefill_budget=self.prefill_budget,
            max_position=cfg.max_position)
        self.decoder = PagedDecoder(cfg)
        self.max_batch = max_batch
        self.telemetry = telemetry
        self.clock = clock if clock is not None else time.monotonic
        self._next_rid = 0
        self.steps = 0
        self.decode_steps = 0
        decoder = self.decoder

        def _prefill(params, tokens, seg, positions, last_index):
            # logits for the last context position only: admission
            # needs one next-token distribution, not S of them
            logits, k, v = decoder.prefill(params, tokens, seg,
                                           positions, last_index)
            return jnp.argmax(logits[0, 0], axis=-1), k[:, 0], v[:, 0]

        def _decode(params, k_pool, v_pool, tokens, positions,
                    page_table, kv_len):
            logits, k_pool, v_pool = decoder.decode(
                params, k_pool, v_pool, tokens, positions, page_table,
                kv_len)
            return jnp.argmax(logits, axis=-1), k_pool, v_pool

        self._prefill_fn = jax.jit(_prefill)
        # donate the pool buffers on TPU: the decode step would
        # otherwise hold old + new pool alive across every step (the
        # CPU backend doesn't implement donation — gating avoids a
        # warning per test run).  The engine rebinds cache.k/v to the
        # returned pools immediately, so nothing aliases the donated
        # buffers.
        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        self._decode_fn = jax.jit(_decode, donate_argnums=donate)

    # -- intake ------------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               arrival_t: Optional[float] = None) -> Request:
        """Create and queue a request; returns its :class:`Request`
        handle (tokens accumulate on ``.generated``)."""
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not prompt:
            raise ValueError("empty prompt")
        req = Request(rid=self._next_rid, prompt=list(prompt),
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      arrival_t=(self.clock() if arrival_t is None
                                 else arrival_t))
        self._next_rid += 1
        self.sched.submit(req)
        return req

    def submit_request(self, req: Request) -> Request:
        """Queue a pre-built request (trace replay); rids must be
        unique per engine."""
        self._next_rid = max(self._next_rid, req.rid + 1)
        self.sched.submit(req)
        return req

    # -- device steps ------------------------------------------------------

    def warmup(self) -> float:
        """Compile both device shapes before any request arrives (so
        TTFT never carries jit-compile wall); returns the seconds
        spent.  The decode warmup donates and rebinds the pool
        buffers; its zero K/V lands in scratch page 0, which no reader
        ever sees."""
        t0 = time.perf_counter()
        z = jnp.zeros((1, self.prefill_budget), jnp.int32)
        jax.block_until_ready(self._prefill_fn(
            self.params, z, z, z, jnp.zeros((), jnp.int32)))
        b = self.max_batch
        p_max = self.cache.max_pages_per_request
        _, wk, wv = self._decode_fn(
            self.params, self.cache.k, self.cache.v,
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
            jnp.zeros((b, p_max), jnp.int32), jnp.ones((b,), jnp.int32))
        self.cache.k, self.cache.v = wk, wv
        jax.block_until_ready(wk)
        return time.perf_counter() - t0

    def _prefill_request(self, req: Request) -> None:
        """One fixed-width prefill for one request: compute K/V for the
        whole context (prompt + pre-preemption tokens), scatter it into
        the request's pages, sample the next token."""
        S = self.prefill_budget
        ctx = req.context
        C = len(ctx)
        ps = self.cache.page_size
        tokens = np.zeros((1, S), np.int32)
        tokens[0, :C] = ctx
        seg = np.zeros((1, S), np.int32)
        seg[0, :C] = 1
        positions = np.zeros((1, S), np.int32)
        positions[0, :C] = np.arange(C)
        next_tok, k, v = self._prefill_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(seg),
            jnp.asarray(positions), jnp.asarray(C - 1, jnp.int32))
        # packed position t -> (page, in-page offset); padding -> scratch
        pages = np.zeros((S,), np.int32)
        offsets = np.zeros((S,), np.int32)
        idx = np.arange(C)
        pages[:C] = np.asarray(req.pages, np.int32)[idx // ps]
        offsets[:C] = idx % ps
        self.cache.write_tokens(k, v, pages, offsets)
        req.kv_len = C
        req.generated.append(int(next_tok))
        if req.first_token_t is None:
            req.first_token_t = self.clock()

    def _decode_batch(self, rows: List[Request]) -> None:
        """One decode step for ``rows`` (≤ max_batch), idle-padded to
        the fixed batch width."""
        b = self.max_batch
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        kv_len = np.ones((b,), np.int32)
        for i, req in enumerate(rows):
            tokens[i] = req.generated[-1]
            positions[i] = req.seq_len - 1
            kv_len[i] = req.seq_len
        page_table = self.cache.page_table(
            [req.pages for req in rows], rows=b)
        next_tok, k_pool, v_pool = self._decode_fn(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(tokens), jnp.asarray(positions), page_table,
            jnp.asarray(kv_len))
        self.cache.k, self.cache.v = k_pool, v_pool
        next_tok = np.asarray(next_tok)
        for i, req in enumerate(rows):
            req.kv_len = req.seq_len
            req.generated.append(int(next_tok[i]))

    # -- the engine step ---------------------------------------------------

    def _emit(self, type_: str, **payload) -> None:
        if self.telemetry is not None:
            self.telemetry.emit(type_, step=self.steps, **payload)

    def _retire(self, now: float) -> List[Request]:
        done = self.sched.retire_finished(now)
        for req in done:
            n = len(req.generated)
            ev = dict(rid=req.rid, reason=req.finish_reason,
                      new_tokens=n, preemptions=req.preemptions)
            if req.first_token_t is not None:
                ev["ttft_ms"] = round(
                    (req.first_token_t - req.arrival_t) * 1e3, 3)
                if n > 1 and req.finish_t is not None:
                    ev["tpot_ms"] = round(
                        (req.finish_t - req.first_token_t) / (n - 1) * 1e3,
                        3)
            self._emit("request_retire", **ev)
        return done

    def step(self) -> bool:
        """One engine iteration: retire → admit+prefill → retire →
        grow/preempt → decode.  Returns True if any work was done."""
        now = self.clock()
        progress = bool(self._retire(now))
        admitted = self.sched.admit()
        for req in admitted:
            req.admit_t = now
            ctx_tokens = len(req.context)
            self._prefill_request(req)
            self._emit("request_admit", rid=req.rid,
                       context_tokens=ctx_tokens,
                       pages=len(req.pages),
                       preemptions=req.preemptions)
            progress = True
        # a request whose budget was a single token is done at prefill
        progress = bool(self._retire(now)) or progress
        evicted: List[Request] = []
        if self.sched.running:
            evicted = self.sched.ensure_decode_capacity()
        rows = list(self.sched.running)
        if rows:
            t0 = self.clock()
            self._decode_batch(rows)
            self.decode_steps += 1
            # evictions ride the decode_step payload (a preempted
            # request is also visible later: its re-admission's
            # request_admit carries preemptions > 0)
            self._emit("decode_step", batch=len(rows),
                       new_tokens=len(rows),
                       pool_used=self.cache.pages_used,
                       pool_pages=self.cache.num_pages - 1,
                       evicted=[r.rid for r in evicted],
                       step_ms=round((self.clock() - t0) * 1e3, 3))
            progress = True
        elif evicted:
            progress = True
        self.steps += 1
        if isinstance(self.clock, SimClock):
            self.clock.advance()
        return progress

    # -- drivers -----------------------------------------------------------

    def run(self, max_steps: int = 100_000) -> List[Request]:
        """Step until every queued request has finished; returns the
        finished list (scheduler order)."""
        for _ in range(max_steps):
            if self.sched.idle:
                break
            self.step()
        else:
            raise RuntimeError(f"engine did not drain in {max_steps} steps")
        self._retire(self.clock())
        return self.sched.finished

    def serve(self, trace: Sequence[Request], *,
              max_steps: int = 1_000_000) -> List[Request]:
        """Run an arrival trace (requests sorted by ``arrival_t``):
        each request is submitted once the clock passes its arrival
        time; with a real clock the engine sleeps through idle gaps,
        with a :class:`SimClock` it advances virtual time.  Trace
        arrival times are RELATIVE to the start of the call — they are
        rebased in place onto the engine clock, so TTFT (first token
        minus arrival) is measured on one time base.  Requests are
        therefore SINGLE-USE: re-serving a trace object would
        double-rebase its arrivals (and replay half-mutated request
        state), so a non-fresh request is rejected up front —
        regenerate the trace instead."""
        pending = sorted(trace, key=lambda r: (r.arrival_t, r.rid))
        for req in pending:
            if req.state != WAITING or req.generated or req.pages \
                    or req.kv_len:
                raise ValueError(
                    f"request {req.rid} is not fresh "
                    f"(state={req.state!r}) — trace requests are "
                    "single-use; regenerate the trace")
        t_base = self.clock()
        for req in pending:
            req.arrival_t += t_base
        i = 0
        for _ in range(max_steps):
            now = self.clock()
            while i < len(pending) and pending[i].arrival_t <= now:
                self.submit_request(pending[i])
                i += 1
            if not self.sched.idle:
                self.step()
            elif i < len(pending):
                gap = pending[i].arrival_t - now
                if isinstance(self.clock, SimClock):
                    self.clock.advance()
                elif gap > 0:
                    time.sleep(min(gap, 0.05))
            else:
                break
        else:
            raise RuntimeError(f"trace did not drain in {max_steps} steps")
        self._retire(self.clock())
        return self.sched.finished
