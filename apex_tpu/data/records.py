"""Record files and fault-aware shard readers.

The on-disk dataset model (shared with the native loader,
``_native/dataloader.cpp``): a *shard* is one file holding a contiguous
array of fixed-size ``record_bytes`` records.  This module adds the two
properties the fault-tolerant pipeline needs on top of raw reads:

- **per-record integrity** — :func:`write_checksummed_records` frames
  each record as ``payload || crc32(payload)`` (4-byte little-endian
  trailer).  A flipped bit anywhere in the payload fails the CRC at
  read time, which is what lets the iterator *quarantine* a damaged
  record instead of training on garbage (or crashing);
- **degraded reads** — :class:`RecordFileSet.read` survives flaky and
  dead shard serving: transient read errors retry with the checkpoint
  layer's exponential-backoff :class:`~apex_tpu.checkpoint.RetryPolicy`,
  an optional ``read_timeout`` turns a *hung* read (straggler host)
  into a retryable failure, and when a handle's retries are exhausted
  the shard is **re-assigned** — the file is reopened through a fresh
  handle (in a real deployment: a different serving replica of the same
  shard) and the read retried once more before :class:`DataShardError`
  gives up.  Every degradation is surfaced through the reader's
  ``on_fault`` callback so the iterator can count and emit telemetry.

Test-only fault hook: like ``checkpoint.set_fault_hook``, the chaos
tier installs :func:`set_read_hook` to raise/sleep at named events
(``"read_record"`` before each record read, ``"reopen_shard"`` at
re-assignment) — see ``apex_tpu.resilience.chaos`` (``DropShard``,
``SlowShardRead``).
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Callable, Optional, Sequence

import numpy as np

from apex_tpu.checkpoint.checkpoint import RetryPolicy

#: Byte length of the CRC32 trailer a checksummed record carries.
RECORD_CRC_BYTES = 4


def write_records(path: str, records: np.ndarray) -> None:
    """Write [n, record_bytes] uint8 rows as one raw record file (no
    per-record framing — the native loader's format)."""
    arr = np.ascontiguousarray(records, np.uint8)
    assert arr.ndim == 2
    with open(path, "wb") as f:
        f.write(arr.tobytes())


def write_checksummed_records(path: str, payloads: np.ndarray) -> int:
    """Write [n, payload_bytes] uint8 rows each framed as
    ``payload || crc32(payload)``; returns the on-disk ``record_bytes``
    (``payload_bytes + RECORD_CRC_BYTES``)."""
    arr = np.ascontiguousarray(payloads, np.uint8)
    assert arr.ndim == 2
    framed = np.empty((arr.shape[0], arr.shape[1] + RECORD_CRC_BYTES),
                      np.uint8)
    framed[:, : arr.shape[1]] = arr
    for i in range(arr.shape[0]):
        crc = zlib.crc32(arr[i].tobytes()) & 0xFFFFFFFF
        framed[i, arr.shape[1]:] = np.frombuffer(
            crc.to_bytes(RECORD_CRC_BYTES, "little"), np.uint8)
    write_records(path, framed)
    return int(framed.shape[1])


def check_record_crc(record: bytes) -> bool:
    """True when a checksummed record's payload matches its trailer."""
    payload, trailer = record[:-RECORD_CRC_BYTES], record[-RECORD_CRC_BYTES:]
    return (zlib.crc32(payload) & 0xFFFFFFFF
            == int.from_bytes(trailer, "little"))


class DataShardError(OSError):
    """A shard read failed past retries AND past re-assignment — the
    record is unreachable from this host."""


# Test-only fault-injection point (see apex_tpu.resilience.chaos).  When
# set, called as hook(event, path) at each shard I/O event; it may raise
# (dead shard serving) or sleep (slow shard).  Events: "read_record"
# (before each record read), "reopen_shard" (after a re-assignment
# reopened the file through a fresh handle).
_read_hook: Optional[Callable[[str, str], None]] = None


def set_read_hook(hook: Optional[Callable[[str, str], None]]):
    """Install (or clear, with None) the shard read hook.  Returns the
    previous hook so tests can restore it."""
    global _read_hook
    prev, _read_hook = _read_hook, hook
    return prev


def _hook(event: str, path: str) -> None:
    if _read_hook is not None:
        _read_hook(event, path)


class RecordFileSet:
    """Fixed-size records across one or more shard files, with degraded
    reads (retry → re-assign → fail; see module doc).

    ``on_fault(kind, **info)`` — called on every degradation event:
    ``kind`` in ``{"read_retry", "shard_reassign", "slow_read"}``.
    ``slow_read_threshold`` — seconds a single successful read may take
    before it is reported as a ``slow_read`` fault (None disables).
    ``read_timeout`` — seconds before an in-flight read is abandoned
    and counted as a failed attempt (None = wait forever).
    """

    def __init__(self, paths: Sequence[str], record_bytes: int, *,
                 retry: Optional[RetryPolicy] = None,
                 read_timeout: Optional[float] = None,
                 slow_read_threshold: Optional[float] = None,
                 on_fault: Optional[Callable[..., None]] = None):
        if record_bytes <= 0:
            raise ValueError(f"record_bytes must be > 0, got {record_bytes}")
        self.paths = [os.fspath(p) for p in paths]
        if not self.paths:
            raise ValueError("RecordFileSet needs at least one shard file")
        self.record_bytes = int(record_bytes)
        self.retry = retry or RetryPolicy(max_attempts=3, base_delay=0.01,
                                          max_delay=0.5)
        self.read_timeout = read_timeout
        self.slow_read_threshold = slow_read_threshold
        self.on_fault = on_fault
        self._files: list = []
        self._base: list = []
        self.num_records = 0
        for p in self.paths:
            n = os.path.getsize(p) // self.record_bytes
            self._files.append(open(p, "rb"))
            self._base.append(self.num_records)
            self.num_records += int(n)
        if self.num_records == 0:
            raise ValueError(
                f"no complete {self.record_bytes}-byte records in "
                f"{self.paths}")
        self.reassigns = 0
        self.retries = 0
        self.slow_reads = 0

    def _fault(self, kind: str, **info) -> None:
        if self.on_fault is not None:
            try:
                self.on_fault(kind, **info)
            except Exception:  # observability must not kill the read
                pass

    def locate(self, rec: int) -> tuple:
        """(file index, byte offset) of global record id ``rec``."""
        if not 0 <= rec < self.num_records:
            raise IndexError(f"record {rec} out of range "
                             f"[0, {self.num_records})")
        f = 0
        while f + 1 < len(self._base) and self._base[f + 1] <= rec:
            f += 1
        return f, (rec - self._base[f]) * self.record_bytes

    def _raw_read(self, f: int, off: int) -> bytes:
        _hook("read_record", self.paths[f])
        fh = self._files[f]
        data = os.pread(fh.fileno(), self.record_bytes, off)
        if len(data) != self.record_bytes:
            raise OSError(
                f"short read at {self.paths[f]}:{off}: got {len(data)} of "
                f"{self.record_bytes} bytes (truncated/rotated shard)")
        return data

    def _read_once(self, f: int, off: int) -> bytes:
        if self.read_timeout is None:
            return self._raw_read(f, off)
        # a dedicated daemon thread per timed read: a hung read leaks
        # exactly one parked thread (bounded by the number of timed-out
        # attempts) instead of poisoning a shared pool — a wedged shard
        # must never make reads of HEALTHY shards queue behind it and
        # spuriously time out
        result: dict = {}
        done = threading.Event()

        def _work():
            try:
                result["data"] = self._raw_read(f, off)
            except BaseException as e:
                result["err"] = e
            finally:
                done.set()

        threading.Thread(target=_work, daemon=True,
                         name="apex-tpu-data-read").start()
        if not done.wait(self.read_timeout):
            # the thread stays parked on the hung read; the caller moves
            # on — exactly the straggler-host semantics we want
            raise OSError(
                f"read of {self.paths[f]}:{off} exceeded the "
                f"{self.read_timeout}s read_timeout (straggling shard)")
        if "err" in result:
            raise result["err"]
        return result["data"]

    def _reassign(self, f: int) -> None:
        """Reopen shard ``f`` through a fresh handle — the local stand-in
        for re-assigning the shard to a different serving replica."""
        try:
            self._files[f].close()
        except Exception:
            pass
        self._files[f] = open(self.paths[f], "rb")
        self.reassigns += 1
        _hook("reopen_shard", self.paths[f])
        self._fault("shard_reassign", path=self.paths[f],
                    reassigns=self.reassigns)

    def read(self, rec: int) -> bytes:
        """Read one record, surviving transient errors (retry/backoff),
        hung reads (timeout), and a dead handle (re-assign + one more
        retry round).  Raises :class:`DataShardError` only when the
        re-assigned handle fails its whole retry round too."""
        f, off = self.locate(rec)
        last: Optional[BaseException] = None
        for generation in range(2):
            for attempt in range(self.retry.max_attempts):
                t0 = time.monotonic()
                try:
                    data = self._read_once(f, off)
                except self.retry.retryable as e:
                    last = e
                    self.retries += 1
                    self._fault("read_retry", path=self.paths[f],
                                record=rec, attempt=attempt,
                                error=repr(e)[:120])
                    time.sleep(self.retry.delay(attempt))
                    continue
                dt = time.monotonic() - t0
                if (self.slow_read_threshold is not None
                        and dt > self.slow_read_threshold):
                    self.slow_reads += 1
                    self._fault("slow_read", path=self.paths[f],
                                record=rec, seconds=round(dt, 4))
                return data
            if generation == 0:
                self._reassign(f)
        raise DataShardError(
            f"record {rec} ({self.paths[f]}:{off}) unreadable after "
            f"{self.retry.max_attempts} attempts on each of 2 handles "
            f"(original + re-assigned): {last!r}")

    def close(self) -> None:
        for fh in self._files:
            try:
                fh.close()
            except Exception:
                pass
        self._files = []

    def __enter__(self) -> "RecordFileSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
