"""Native (C++) threaded record loader — the non-checkpointable fast
path.

``apex_tpu/_native/dataloader.cpp`` is the DALI/torch-DataLoader role
from the reference's examples: fixed-size binary records, deterministic
per-epoch reshuffle, a worker-thread pool ``pread``-ing into a prefetch
ring with no Python in the hot path.  It is kept (not deleted — the
ISSUE 7 decision, recorded in docs/data.md) as an **optional fast
path** behind :class:`~apex_tpu.data.prefetch.AsyncPrefetcher`: wrap it
when raw ingest throughput matters and iterator checkpointing does not
(evaluation sweeps, benchmark feeds).  The fault-tolerant,
exactly-once-resumable path is the pure-Python
:class:`~apex_tpu.data.iterator.ShardedRecordIterator` — the native
loader's cursor lives inside the C++ ring and cannot serialize, so it
must never be handed to a checkpointing train loop (the loops reject
any iterator without ``state_dict``).
"""

from __future__ import annotations

import ctypes
import os
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from apex_tpu import _native


def native_available() -> bool:
    return _native.available()


class NativeRecordLoader:
    """Iterator over batches of fixed-size records, prefetched by the C++
    worker pool.

    Yields ``decode(batch_bytes)`` where ``batch_bytes`` is a
    [batch, record_bytes] uint8 array (a fresh buffer each step — safe to
    hand straight to ``jax.device_put``).  The stream is infinite with a
    deterministic per-epoch reshuffle; use :attr:`batches_per_epoch` to
    delimit epochs (the reference CLI's len(loader) role).
    """

    def __init__(self, paths: Sequence[str], record_bytes: int,
                 batch_size: int, *, shuffle: bool = True, seed: int = 0,
                 num_threads: int = 4, queue_depth: int = 4,
                 decode: Optional[Callable[[np.ndarray], object]] = None):
        lib = _native.get_lib()
        if lib is None:
            raise RuntimeError(
                f"native loader unavailable: {_native.build_error()}")
        self._lib = lib
        self.record_bytes = int(record_bytes)
        self.batch_size = int(batch_size)
        self.decode = decode
        enc = [os.fsencode(p) for p in paths]
        arr = (ctypes.c_char_p * len(enc))(*enc)
        self._h = lib.axl_open(arr, len(enc), self.record_bytes,
                               self.batch_size, 1 if shuffle else 0,
                               seed, num_threads, queue_depth)
        if not self._h:
            raise RuntimeError(
                f"axl_open failed for {list(paths)[:3]}... (records must "
                f"be >= batch_size and files readable)")
        self.num_records = lib.axl_num_records(self._h)

    @property
    def batches_per_epoch(self) -> int:
        return self.num_records // self.batch_size

    @property
    def error_count(self) -> int:
        """Records zero-filled because a read failed (truncated/rotated
        file).  Nonzero means delivered data is suspect — check after
        each epoch (or each batch for strict pipelines)."""
        return int(self._lib.axl_error_count(self._h)) if self._h else 0

    def next_batch(self) -> object:
        out = np.empty((self.batch_size, self.record_bytes), np.uint8)
        rc = self._lib.axl_next(self._h, ctypes.c_void_p(out.ctypes.data))
        if rc != 0:
            raise RuntimeError("axl_next failed (loader closed?)")
        return self.decode(out) if self.decode is not None else out

    def __iter__(self) -> Iterator[object]:
        return self

    def __next__(self) -> object:
        return self.next_batch()

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.axl_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
