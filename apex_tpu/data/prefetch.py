"""Double-buffered async host→device prefetcher.

The host-side half of "feed the beast" (ROADMAP item 5a): a background
thread pulls batches from the source iterator (disk read + decode +
optional ``device_put``, so the H2D transfer overlaps the previous
step's compute) into a bounded queue; the train loop's ``next()`` only
blocks when the queue runs dry — and that blocked time is exactly the
``data_wait`` the telemetry ledger books.

Fault surface:

- **backpressure** — the queue is bounded (``depth``, default 2: double
  buffering); a fast producer parks instead of ballooning host memory;
- **stall telemetry** — a ``next()`` that waits longer than
  ``stall_threshold_s`` emits a ``data_stall`` event (cause
  ``queue_dry``) and counts toward :attr:`stalls`;
- **loader death is loud** — an exception in the worker (shard
  unreadable past re-assignment, quarantine overflow, decode bug)
  is captured and re-raised at the consumer's next ``next()`` as
  :class:`DataLoaderError` chained to the original, so the train
  loop's crash path (postmortem flush) sees it like any step failure;
- **exactly-once state** — the worker snapshots the source's
  ``state_dict()`` *after producing each batch* and the snapshot rides
  the queue; :meth:`state_dict` returns the snapshot of the last batch
  the consumer actually took, so in-flight (prefetched but unconsumed)
  batches are never marked consumed.  On restore they are simply
  regenerated — the source's deterministic addressing makes the replay
  bitwise identical.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

_END = "end"
_ERROR = "error"
_ITEM = "item"


class DataLoaderError(RuntimeError):
    """The background loader thread died; the original exception is
    chained (``__cause__``)."""


class AsyncPrefetcher:
    """Wrap an iterator with a background producer thread + bounded
    queue.

    ``source`` — any iterator; if it has ``state_dict``/
    ``load_state_dict`` (the checkpointable-iterator protocol) the
    prefetcher is checkpointable too, with consumed-cursor semantics
    (see module doc).  ``transfer`` — optional callable applied to each
    batch ON THE WORKER THREAD (e.g. ``jax.device_put``; the overlap is
    the point).  ``depth`` — queue bound (2 = double buffering).
    """

    def __init__(self, source: Any, *, depth: int = 2,
                 transfer: Optional[Callable[[Any], Any]] = None,
                 stall_threshold_s: float = 0.1,
                 telemetry=None, start: bool = True):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.source = source
        self.depth = int(depth)
        self.transfer = transfer
        self.stall_threshold_s = float(stall_threshold_s)
        self.telemetry = telemetry
        self._q: queue.Queue = queue.Queue(self.depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._checkpointable = hasattr(source, "state_dict")
        self._consumed_state: Optional[dict] = (
            source.state_dict() if self._checkpointable else None)
        self._exhausted = False
        self.wait_s = 0.0
        self.stalls = 0
        self.batches = 0
        if start:
            self.start()

    # -- worker ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        # fresh per-generation stop flag + queue: a worker that outlived
        # a _halt() join timeout still holds ITS generation's (set) event
        # and orphaned queue, so it can never observe the restart and
        # produce into the new stream as a duplicate producer
        self._stop = threading.Event()
        self._q = queue.Queue(self.depth)
        self._thread = threading.Thread(
            target=self._run, args=(self._stop, self._q),
            name="apex-tpu-prefetch", daemon=True)
        self._thread.start()

    def _put(self, item, stop, q) -> bool:
        """Backpressured put that stays responsive to stop()."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, stop, q) -> None:
        try:
            while not stop.is_set():
                try:
                    batch = next(self.source)
                except StopIteration:
                    self._put((_END, None, None), stop, q)
                    return
                if self.transfer is not None:
                    batch = self.transfer(batch)
                snap = (self.source.state_dict()
                        if self._checkpointable else None)
                if not self._put((_ITEM, batch, snap), stop, q):
                    return
        except BaseException as e:  # loader death must be LOUD
            self._put((_ERROR, e, None), stop, q)

    # -- consumer --------------------------------------------------------

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        t0 = time.monotonic()
        kind, payload, snap = self._q.get()
        wait = time.monotonic() - t0
        self.wait_s += wait
        if wait > self.stall_threshold_s:
            self.stalls += 1
            if self.telemetry is not None:
                try:
                    self.telemetry.emit(
                        "data_stall", wait_ms=round(wait * 1e3, 3),
                        cause="queue_dry", depth=self.depth)
                except Exception:
                    pass
        if kind == _ERROR:
            self._exhausted = True
            raise DataLoaderError(
                f"data loader thread died: {type(payload).__name__}: "
                f"{payload}") from payload
        if kind == _END:
            self._exhausted = True
            raise StopIteration
        self._consumed_state = snap
        self.batches += 1
        return payload

    def __iter__(self):
        return self

    def take_wait(self) -> float:
        """Accumulated consumer wait since the last call (seconds) —
        the train loop books this into the ``data_wait`` bucket."""
        w, self.wait_s = self.wait_s, 0.0
        return w

    # -- checkpointable-iterator protocol --------------------------------

    def state_dict(self) -> dict:
        """Position of the last CONSUMED batch (in-flight prefetched
        batches are not consumed; a restore regenerates them)."""
        if not self._checkpointable:
            raise TypeError(
                f"source {type(self.source).__name__} is not "
                "checkpointable (no state_dict)")
        return self._consumed_state

    def load_state_dict(self, state: dict) -> None:
        """Stop the worker, drop every prefetched batch, restore the
        source position, restart."""
        if not self._checkpointable:
            raise TypeError(
                f"source {type(self.source).__name__} is not "
                "checkpointable (no load_state_dict)")
        if not self._halt():
            # the worker may still be INSIDE next(source); mutating the
            # source's cursors under it would silently break exactly-once
            raise DataLoaderError(
                "loader thread did not stop within 5s (wedged in a "
                "shard read?) — cannot safely restore the iterator "
                "position under a live reader")
        self.source.load_state_dict(state)
        self._consumed_state = self.source.state_dict()
        self._exhausted = False
        self.start()

    # -- lifecycle -------------------------------------------------------

    def _halt(self) -> bool:
        """Stop the worker; True when it actually exited.  A worker that
        outlives the join timeout (wedged in a shard read) is abandoned —
        its generation's stop event stays set and its queue orphaned, so
        it can never produce again — but the source must then be treated
        as possibly still in use (the False return)."""
        self._stop.set()
        # drain so a parked producer's put() can observe the stop flag
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        t, self._thread = self._thread, None
        stopped = True
        if t is not None:
            t.join(timeout=5.0)
            stopped = not t.is_alive()
        while True:  # anything the worker flushed while joining
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        return stopped

    def close(self) -> None:
        self._halt()
        close = getattr(self.source, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass

    def __enter__(self) -> "AsyncPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
