"""Checkpointable sharded deterministic record iterator.

The exactly-once design (docs/data.md "Exactly-once resume") in three
layers:

1. **Deterministic addressing.**  An epoch is a seeded permutation of
   all record ids, computed lazily per *shuffle window*: position ``i``
   of epoch ``e`` lives in window ``w = i // W``, and window ``w``'s
   order is ``Philox(seed, e, w)``'s permutation of its record range —
   every record exactly once per epoch, O(W) state, any position
   addressable without replaying the stream.  Epochs concatenate into
   one infinite global position stream.

2. **Slot substreams.**  A global batch has ``batch_size`` *slots*;
   slot ``j`` owns the global positions ``{k·B + j}`` (round-robin).
   Each slot pulls records from its own substream, skipping quarantined
   records independently, so one damaged record shifts only its own
   slot's cursor — never the composition of other slots (or other
   hosts' shards).  The full iterator position is the ``[B]`` vector of
   per-slot cursors plus the consumed-batch count — the compact
   ``data_state`` record that rides the checkpoint manifest.

3. **Shard ownership = a slot range.**  Data-parallel rank ``r`` of
   ``dp`` materializes slots ``[r·B/dp, (r+1)·B/dp)`` (it reads and
   decodes only those records).  The slot→record mapping is global and
   rank-independent, so re-partitioning across an elastic dp→dp'
   restart is pure re-slicing — the C-order slot linearization is the
   same contract ``multi_tensor.flat`` applies to flat-buffer stacks,
   and the consumed sample-id stream (the union over ranks, per batch)
   is bitwise identical for every dp that divides B.

Degradation: records that fail their CRC (or the caller's
``validate_record``) are **quarantined** — skipped, counted, reported
as a ``data_quarantine`` telemetry event — and the run hard-fails with
:class:`QuarantineOverflowError` only past
:class:`QuarantinePolicy.max_rate`.  Slow/dead shard reads ride
:class:`~apex_tpu.data.records.RecordFileSet`'s retry → re-assign
ladder; the iterator surfaces those as ``data_stall`` events.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from apex_tpu.checkpoint.checkpoint import RetryPolicy
from apex_tpu.data.records import (
    RECORD_CRC_BYTES,
    RecordFileSet,
    check_record_crc,
)

#: data_state schema version (manifest ``data_state.version``).
DATA_STATE_VERSION = 1


class QuarantineOverflowError(RuntimeError):
    """Quarantined-record rate exceeded the policy's ceiling — the
    dataset (or its storage) is damaged beyond what silent skipping
    should paper over."""


@dataclasses.dataclass(frozen=True)
class QuarantinePolicy:
    """When to keep skipping vs when to hard-fail.

    ``max_rate`` — quarantined / pulled ceiling; above it the iterator
    raises :class:`QuarantineOverflowError`.  ``min_count`` — never
    hard-fail before this many quarantined records (a tiny sample must
    not kill a run over one bad record)."""

    max_rate: float = 0.01
    min_count: int = 8


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(msg)


class ShardedRecordIterator:
    """Deterministic, checkpointable batch iterator over record shards.

    Yields ``decode(batch)`` where ``batch`` is the
    ``[local_batch, payload_bytes]`` uint8 matrix of this rank's slots
    (``local_batch = batch_size // dp_size``); the stream is infinite
    unless ``num_batches`` bounds it.  See the module doc for the
    position/exactly-once model and docs/data.md for the state format.

    ``checksummed`` — records carry the :mod:`~apex_tpu.data.records`
    CRC trailer; failures are quarantined.  ``validate_record`` —
    optional ``payload -> bool`` for app-level validation (undecodable
    records); False quarantines.  ``on_ids(batch_index, ids)`` — test /
    audit tap: the record ids this rank consumed for each batch.
    ``telemetry`` — a :class:`~apex_tpu.telemetry.TelemetryBus`;
    quarantines emit ``data_quarantine``, shard degradations emit
    ``data_stall``.
    """

    def __init__(self, paths: Sequence[str], record_bytes: int,
                 batch_size: int, *,
                 checksummed: bool = False,
                 shuffle_window: int = 4096,
                 seed: int = 0,
                 num_batches: Optional[int] = None,
                 dp_rank: int = 0,
                 dp_size: int = 1,
                 decode: Optional[Callable[[np.ndarray], object]] = None,
                 validate_record: Optional[Callable[[bytes], bool]] = None,
                 quarantine: Optional[QuarantinePolicy] = None,
                 retry: Optional[RetryPolicy] = None,
                 read_timeout: Optional[float] = None,
                 slow_read_threshold: Optional[float] = None,
                 telemetry=None,
                 on_ids: Optional[Callable[[int, list], None]] = None):
        _require(batch_size > 0, f"batch_size must be > 0: {batch_size}")
        _require(dp_size > 0 and 0 <= dp_rank < dp_size,
                 f"need 0 <= dp_rank ({dp_rank}) < dp_size ({dp_size})")
        _require(batch_size % dp_size == 0,
                 f"batch_size {batch_size} must divide evenly over "
                 f"dp_size {dp_size} (slot ownership is a contiguous "
                 "per-rank slot range)")
        _require(shuffle_window > 0,
                 f"shuffle_window must be > 0: {shuffle_window}")
        self.telemetry = telemetry
        self.files = RecordFileSet(
            paths, record_bytes, retry=retry, read_timeout=read_timeout,
            slow_read_threshold=slow_read_threshold,
            on_fault=self._shard_fault)
        self.record_bytes = int(record_bytes)
        self.batch_size = int(batch_size)
        self.checksummed = bool(checksummed)
        self.payload_bytes = self.record_bytes - (
            RECORD_CRC_BYTES if self.checksummed else 0)
        _require(self.payload_bytes > 0,
                 f"record_bytes {record_bytes} leaves no payload after "
                 "the CRC trailer")
        self.shuffle_window = int(shuffle_window)
        self.seed = int(seed)
        self.num_batches = num_batches if num_batches is None \
            else int(num_batches)
        self.dp_rank, self.dp_size = int(dp_rank), int(dp_size)
        self.decode = decode
        self.validate_record = validate_record
        self.quarantine = quarantine or QuarantinePolicy()
        self.on_ids = on_ids
        n = self.files.num_records
        _require(n >= batch_size,
                 f"dataset has {n} records < batch_size {batch_size}")
        local = self.batch_size // self.dp_size
        self.slots = list(range(self.dp_rank * local,
                                (self.dp_rank + 1) * local))
        # position state: per-slot substream cursors (this rank's slots
        # only; a global dp_size=1 iterator owns the full vector) + the
        # consumed-batch count.  THIS is the whole resumable position.
        self._cursors = {j: 0 for j in self.slots}
        self.batches_consumed = 0
        self.quarantined = 0
        self.pulled = 0
        self.last_ids: list = []
        self._perm_cache: dict = {}

    # -- deterministic addressing ---------------------------------------

    def _window_perm(self, epoch: int, w: int) -> np.ndarray:
        key = (epoch, w)
        hit = self._perm_cache.get(key)
        if hit is not None:
            return hit
        n = self.files.num_records
        size = min(self.shuffle_window, n - w * self.shuffle_window)
        # Philox takes a 2x64-bit key: (seed, epoch||window) — counter-
        # based, so any (epoch, window) permutation is addressable
        # without sequential state
        rng = np.random.Generator(np.random.Philox(
            key=[self.seed & 0xFFFFFFFFFFFFFFFF,
                 ((epoch & 0xFFFFFFFF) << 32) | (w & 0xFFFFFFFF)]))
        perm = rng.permutation(size)
        if len(self._perm_cache) > 16:  # small LRU-ish bound
            self._perm_cache.pop(next(iter(self._perm_cache)))
        self._perm_cache[key] = perm
        return perm

    def record_at(self, pos: int) -> int:
        """Record id at global stream position ``pos`` (epochs
        concatenate; pure function of (seed, pos))."""
        n = self.files.num_records
        epoch, i = divmod(int(pos), n)
        w, j = divmod(i, self.shuffle_window)
        return w * self.shuffle_window + int(self._window_perm(epoch, w)[j])

    # -- degradation surfacing ------------------------------------------

    def _shard_fault(self, kind: str, **info) -> None:
        if self.telemetry is None:
            return
        if kind in ("slow_read", "shard_reassign"):
            wait_ms = round(float(info.get("seconds", 0.0)) * 1e3, 3)
            self.telemetry.emit("data_stall", wait_ms=wait_ms,
                                cause=kind, **{k: v for k, v in info.items()
                                               if k != "seconds"})

    def _quarantine_record(self, rec: int, reason: str) -> None:
        self.quarantined += 1
        rate = self.quarantined / max(1, self.pulled)
        if self.telemetry is not None:
            self.telemetry.emit("data_quarantine", record_id=int(rec),
                                reason=reason, total=self.quarantined,
                                rate=round(rate, 6))
        if (self.quarantined >= self.quarantine.min_count
                and rate > self.quarantine.max_rate):
            raise QuarantineOverflowError(
                f"{self.quarantined} of {self.pulled} pulled records "
                f"quarantined (rate {rate:.4f} > policy max_rate "
                f"{self.quarantine.max_rate}) — last: record {rec} "
                f"({reason}); the dataset/storage is damaged beyond "
                "skip-and-count")

    # -- pulling ---------------------------------------------------------

    def _pull(self, slot: int) -> tuple:
        """(record id, payload) for ``slot``'s next pull, quarantining
        damaged records (each advances only this slot's cursor)."""
        while True:
            pos = self._cursors[slot] * self.batch_size + slot
            self._cursors[slot] += 1
            rec = self.record_at(pos)
            data = self.files.read(rec)
            self.pulled += 1
            if self.checksummed and not check_record_crc(data):
                self._quarantine_record(rec, "crc_mismatch")
                continue
            payload = data[: self.payload_bytes]
            if (self.validate_record is not None
                    and not self.validate_record(payload)):
                self._quarantine_record(rec, "validate_failed")
                continue
            return rec, payload

    def __next__(self):
        if (self.num_batches is not None
                and self.batches_consumed >= self.num_batches):
            raise StopIteration
        ids, rows = [], []
        for j in self.slots:
            rec, payload = self._pull(j)
            ids.append(rec)
            rows.append(np.frombuffer(payload, np.uint8))
        batch = np.stack(rows)
        self.batches_consumed += 1
        self.last_ids = ids
        if self.on_ids is not None:
            self.on_ids(self.batches_consumed - 1, list(ids))
        return self.decode(batch) if self.decode is not None else batch

    def __iter__(self) -> Iterator:
        return self

    # -- checkpointable-iterator protocol --------------------------------

    def _fingerprint(self) -> str:
        h = hashlib.sha256()
        for p in self.files.paths:
            h.update(os.path.basename(p).encode())
            h.update(str(os.path.getsize(p)).encode())
        h.update(f"{self.record_bytes}:{self.batch_size}:{self.seed}:"
                 f"{self.shuffle_window}:{int(self.checksummed)}".encode())
        return h.hexdigest()[:16]

    def state_dict(self) -> dict:
        """Compact JSON-serializable position record (the checkpoint
        manifest's ``data_state`` key): per-slot cursors for the slots
        this rank owns, consumed-batch count, quarantine counters, and
        a config fingerprint restore validates against."""
        return {
            "version": DATA_STATE_VERSION,
            "fingerprint": self._fingerprint(),
            "batch_size": self.batch_size,
            "batches_consumed": self.batches_consumed,
            "slots": list(self.slots),
            "cursors": [int(self._cursors[j]) for j in self.slots],
            "quarantined": int(self.quarantined),
            "pulled": int(self.pulled),
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore the iterator position.  The state may come from a
        different dp decomposition (an elastic dp→dp' restart): this
        rank adopts the cursors of exactly the slots it now owns —
        shard ownership re-partitions by re-slicing the global slot
        vector (C-order, the flat-contract rule)."""
        if not isinstance(state, dict):
            raise TypeError(f"data_state must be a dict, got "
                            f"{type(state).__name__}")
        if state.get("version") != DATA_STATE_VERSION:
            raise ValueError(
                f"data_state version {state.get('version')!r} != "
                f"{DATA_STATE_VERSION} — saved by an incompatible "
                "pipeline")
        if state.get("batch_size") != self.batch_size:
            raise ValueError(
                f"data_state batch_size {state.get('batch_size')} != "
                f"iterator batch_size {self.batch_size}: slot substreams "
                "are keyed by the GLOBAL batch size; exactly-once resume "
                "cannot re-partition across a batch-size change")
        if state.get("fingerprint") != self._fingerprint():
            raise ValueError(
                "data_state fingerprint mismatch: the checkpoint was "
                "saved against a different dataset/config (files, "
                "record_bytes, seed, shuffle_window, or checksumming "
                "changed) — exactly-once resume would replay a "
                "different stream")
        saved = dict(zip(state["slots"], state["cursors"]))
        missing = [j for j in self.slots if j not in saved]
        if missing:
            raise ValueError(
                f"data_state covers slots {sorted(saved)} but this rank "
                f"owns {self.slots} (missing {missing}) — merge every "
                "rank's state (merge_data_states) before a cross-"
                "topology restore")
        self._cursors = {j: int(saved[j]) for j in self.slots}
        self.batches_consumed = int(state["batches_consumed"])
        self.quarantined = int(state.get("quarantined", 0))
        self.pulled = int(state.get("pulled", 0))
        self.last_ids = []

    def close(self) -> None:
        self.files.close()

    def __enter__(self) -> "ShardedRecordIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def merge_data_states(states: Sequence[dict]) -> dict:
    """Union the per-rank ``data_state`` records of one dp group into
    the full-slot-vector state a cross-topology restore needs (slot
    ownership is disjoint; consumed-batch counts must agree)."""
    if not states:
        raise ValueError("merge_data_states needs at least one state")
    base = states[0]
    merged = {j: c for s in states
              for j, c in zip(s["slots"], s["cursors"])}
    for s in states[1:]:
        for k in ("version", "fingerprint", "batch_size",
                  "batches_consumed"):
            if s.get(k) != base.get(k):
                raise ValueError(
                    f"inconsistent data_state field {k!r} across ranks: "
                    f"{s.get(k)!r} != {base.get(k)!r}")
    slots = sorted(merged)
    return {
        "version": base["version"],
        "fingerprint": base["fingerprint"],
        "batch_size": base["batch_size"],
        "batches_consumed": base["batches_consumed"],
        "slots": slots,
        "cursors": [int(merged[j]) for j in slots],
        "quarantined": int(sum(s.get("quarantined", 0) for s in states)),
        "pulled": int(sum(s.get("pulled", 0) for s in states)),
    }
