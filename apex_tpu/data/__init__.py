"""Fault-tolerant input pipeline (ISSUE 7, ROADMAP item 5a).

The data plane's resilience story, mirroring what
:mod:`apex_tpu.resilience` gives the compute plane:

- :class:`ShardedRecordIterator` — deterministic, **checkpointable**
  sharded iterator: seeded shuffle windows, per-slot substreams, dp-axis
  shard ownership as a slot range; its full position serializes to a
  compact ``data_state`` record saved through
  ``save_checkpoint(..., data_state=...)`` so a killed run resumes
  **exactly-once** (no replayed, no dropped samples), including across
  elastic dp→dp' restarts;
- :class:`AsyncPrefetcher` — double-buffered background prefetch with
  ``device_put`` overlap, bounded-queue backpressure, ``data_wait``
  accounting, ``data_stall`` telemetry, and loud loader-thread death;
- **degradation** — damaged records are quarantined
  (:class:`QuarantinePolicy`, hard-fail via
  :class:`QuarantineOverflowError` above a configurable rate); slow or
  dead shard reads ride a retry → backoff → re-assignment ladder
  (:class:`~apex_tpu.data.records.RecordFileSet`);
- :class:`NativeRecordLoader` — the C++ threaded loader
  (``_native/dataloader.cpp``), kept as the optional non-checkpointable
  fast path behind the prefetcher (decision recorded in docs/data.md).

See docs/data.md for the state format, the exactly-once contract, the
quarantine policy, and the chaos knobs
(:mod:`apex_tpu.resilience.chaos`: ``corrupt_record``,
``SlowShardRead``, ``DropShard``).
"""

from apex_tpu.data.iterator import (  # noqa: F401
    DATA_STATE_VERSION,
    QuarantineOverflowError,
    QuarantinePolicy,
    ShardedRecordIterator,
    merge_data_states,
)
from apex_tpu.data.native import (  # noqa: F401
    NativeRecordLoader,
    native_available,
)
from apex_tpu.data.prefetch import (  # noqa: F401
    AsyncPrefetcher,
    DataLoaderError,
)
from apex_tpu.data.records import (  # noqa: F401
    RECORD_CRC_BYTES,
    DataShardError,
    RecordFileSet,
    check_record_crc,
    set_read_hook,
    write_checksummed_records,
    write_records,
)

__all__ = [
    "AsyncPrefetcher",
    "DATA_STATE_VERSION",
    "DataLoaderError",
    "DataShardError",
    "NativeRecordLoader",
    "QuarantineOverflowError",
    "QuarantinePolicy",
    "RECORD_CRC_BYTES",
    "RecordFileSet",
    "ShardedRecordIterator",
    "check_record_crc",
    "merge_data_states",
    "native_available",
    "set_read_hook",
    "write_checksummed_records",
    "write_records",
]
