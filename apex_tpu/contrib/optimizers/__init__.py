"""apex_tpu.contrib.optimizers — ZeRO-style sharded optimizers
(reference apex/contrib/optimizers/)."""

from apex_tpu.contrib.optimizers.distributed_fused import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
    DistributedShardedOptimizer,
    ShardedOptState,
    reshard_zero_state,
)
