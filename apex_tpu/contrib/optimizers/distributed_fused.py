"""ZeRO-style sharded optimizers over the mesh "data" axis.

TPU-native re-design of the reference's sharded distributed optimizers:

* ``DistributedFusedAdam`` v1-v3
  (reference apex/contrib/optimizers/distributed_fused_adam.py:9-636),
* ``DistributedFusedLAMB``
  (reference apex/contrib/optimizers/distributed_fused_lamb.py:10-975).

Reference architecture: the flat fp16 grad buffer is split into
blocks→chunks→shards (distributed_fused_lamb.py:364-434); per-block
reduce-scatters overlap with backward via grad hooks (:316-362); each rank
runs the optimizer on its shard; updated param shards are all-gathered
(optionally e5m2-compressed).

TPU mapping — the communication pattern survives, the machinery dissolves:

* flat buffer        → one packed superblock (:mod:`apex_tpu.multi_tensor.flat`),
  padded so its length divides the shard count;
* chunked reduce-scatter + hooks → a single ``lax.psum_scatter`` inside the
  jitted step (XLA's scheduler overlaps it with the backward);
* sharded Adam/LAMB step → the fused update on this rank's shard slice;
* allgather of updated shards → ``lax.all_gather(tiled=True)``, optionally
  through an e5m2 cast (same 8-bit-exponent format as the reference's
  compressed allgather);
* LAMB's global grad-norm prepass (fused_lamb.py:121-136) → shard-local
  square-sum + one extra psum term fused into the same step.

Must run inside a region binding ``axis_name`` (shard_map over the mesh).
Optimizer state lives ONLY for this rank's shard — memory per device is
``params + 2·params/N`` instead of ``3·params`` (the ZeRO claim).

Memory-fit knobs (r6, the GPT-1.3B flagship — ISSUE 2): at 1.3B params a
16 GB chip cannot hold fp32 p+g+m+v (21 GB), so the flat-buffer dtypes
are configurable the way the reference's are:

* ``scatter_dtype`` — the flat grad buffer / reduce-scatter transport
  (the reference reduce-scatters its fp16 flat grad buffer,
  distributed_fused_adam.py:316-362); ``None`` keeps fp32.
* ``gather_dtype`` — the updated-shard all_gather transport; ``None``
  keeps fp32.  With bf16 model params, gathering in bf16 halves both
  the transport and the full-parameter transient (the update math still
  runs fp32 inside the fused elementwise chain — only the *stored*
  buffers narrow).
* ``exp_avg_dtype`` — first-moment storage.  bf16 halves the momentum
  buffer (1.3 GB/10⁹ params); the variance stays fp32 (its dynamic
  range IS the adaptive step size — narrowing it changes the update far
  more than momentum rounding does).

All default to the r5 behavior (fp32 everywhere): existing callers and
the parity tests are unchanged.  The fitting sweep behind the choices is
recorded in BASELINE.md (gpt1p3b section).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.multi_tensor.flat import FlatSchema, flatten, make_schema, unflatten


class ShardedOptState(NamedTuple):
    step: jnp.ndarray  # i32 scalar
    exp_avg: jnp.ndarray  # [shard] f32 (momentum)
    exp_avg_sq: jnp.ndarray  # [shard] f32 (2nd moment)


def reshard_zero_state(opt_state: ShardedOptState, *,
                       n_shards: Optional[int] = None,
                       schema: FlatSchema,
                       lead_shape=None) -> ShardedOptState:
    """Re-partition a STACKED per-rank :class:`ShardedOptState` (leading
    stack axes on every leaf, the layout the flagship train step
    carries) onto a new topology — the in-memory half of the elastic
    cross-topology story (the on-disk half lives in
    ``checkpoint.restore_checkpoint``'s sharded-manifest reshard).

    ``n_shards`` — single-axis form: the leading ``[old_n]`` stack
    re-partitions to ``[n_shards, total/n_shards]``.  ``lead_shape`` —
    multi-axis form (e.g. ``(dp, pp, tp)``): the flat leaves re-stack to
    ``[*lead_shape, total/prod(lead_shape)]``, linearizing the old stack
    axes in C order (the linearized-world ZeRO layout).  Either way the
    flat-buffer leaves (``exp_avg``/``exp_avg_sq``) concatenate in rank
    order to the logical superblock, then re-split against the TARGET
    ``schema`` (whose ``total`` is padded to ``128·world`` — per-leaf
    offsets are topology-invariant, only the tail padding moves, so
    growth zero-fills and shrinkage may drop only all-zero tail padding;
    dropping real state raises).  The broadcast ``step`` counter
    re-broadcasts coordinate 0.  Host-side numpy — this runs once per
    mesh rebuild, not per step; routes through
    :func:`apex_tpu.multi_tensor.flat.reshard_stack`, the same
    implementation the checkpoint reshard uses."""
    from apex_tpu.multi_tensor.flat import reshard_stack

    if lead_shape is None:
        if n_shards is None:
            raise ValueError("pass n_shards or lead_shape")
        lead_shape = (int(n_shards),)
    lead_shape = tuple(int(x) for x in lead_shape)
    world = int(np.prod(lead_shape))
    shard = schema.total // world
    old_step = np.asarray(jax.device_get(opt_state.step))
    n_lead_old = old_step.ndim  # step content is scalar per rank

    def _flat(leaf) -> jnp.ndarray:
        a = np.asarray(jax.device_get(leaf))
        out = reshard_stack(a, n_lead_old, (*lead_shape, shard),
                            label=f"opt shard stack ({old_step.shape}->"
                                  f"{lead_shape})")
        return jnp.asarray(out)

    return ShardedOptState(
        step=jnp.asarray(reshard_stack(old_step, n_lead_old, lead_shape,
                                       replicated=True,
                                       label="opt step counter")),
        exp_avg=_flat(opt_state.exp_avg),
        exp_avg_sq=_flat(opt_state.exp_avg_sq),
    )


@dataclasses.dataclass(frozen=True)
class DistributedShardedOptimizer:
    """Common psum_scatter → sharded-update → all_gather engine."""

    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    bias_correction: bool = True
    # multi-axis meshes: ``axis_name`` may be a TUPLE of mesh axes (the
    # linearized-world ZeRO layout — shards/collectives span the whole
    # dp×pp×tp block; the caller feeds REPLICATED global grads, which
    # the mesh-wide psum_scatter sums world-fold and ``grad_average``
    # divides back out — exact for power-of-two worlds)
    axis_name: Any = "data"
    grad_average: bool = True
    e5m2_allgather: bool = False  # reference distributed_fused_lamb.py:93
    # memory-fit knobs (see module docstring); None = fp32 (r5 behavior)
    scatter_dtype: Optional[Any] = None
    gather_dtype: Optional[Any] = None
    exp_avg_dtype: Any = jnp.float32

    # -- host-side setup -----------------------------------------------------

    def make_schema(self, params, n_shards: int) -> FlatSchema:
        """Pack layout whose total length divides ``n_shards``
        (the block/chunk/shard alignment of the reference, :364-434)."""
        return make_schema(params, align=128,
                           total_multiple_of=128 * n_shards)

    def init(self, params, schema: FlatSchema, n_shards: int) -> ShardedOptState:
        """Per-rank shard state (call inside shard_map, or once per rank)."""
        shard = schema.total // n_shards
        return ShardedOptState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=jnp.zeros((shard,), self.exp_avg_dtype),
            exp_avg_sq=jnp.zeros((shard,), jnp.float32),
        )

    # -- the sharded step ----------------------------------------------------

    def _shard_update(self, p, g, state, lr):
        raise NotImplementedError

    def step(self, grads, state: ShardedOptState, params,
             schema: FlatSchema):
        """One ZeRO step; call inside shard_map binding ``axis_name``.

        Returns ``(new_params, new_state)`` with new_params identical
        (bitwise) on every rank of the axis.
        """
        world = jax.lax.psum(1, self.axis_name)
        rank = jax.lax.axis_index(self.axis_name)
        shard = schema.total // world

        flat_g, _ = flatten(grads, schema,
                            dtype=self.scatter_dtype or jnp.float32)
        # reduce-scatter: each rank receives the summed shard it owns
        # (in scatter_dtype — the reference's fp16 flat grad buffer);
        # the update math upcasts to fp32 inside the fused chain
        g_shard = jax.lax.psum_scatter(flat_g, self.axis_name,
                                       tiled=True).astype(jnp.float32)
        if self.grad_average:
            g_shard = g_shard / world

        # e5m2 delta transport needs the fp32 base regardless of
        # gather_dtype (the compressed delta is the transport narrowing)
        flat_dtype = (jnp.float32 if self.e5m2_allgather
                      else self.gather_dtype or jnp.float32)
        flat_p, _ = flatten(params, schema, dtype=flat_dtype)
        p_shard = jax.lax.dynamic_slice_in_dim(
            flat_p, rank * shard, shard).astype(jnp.float32)

        new_p_shard, new_state = self._shard_update(
            p_shard, g_shard, state, flat_g)

        if self.e5m2_allgather:
            # 8-bit-exponent compressed transport (reference e5m2_allgather):
            # ship the *delta* in e5m2 so the fp32 base is preserved
            delta = (new_p_shard - p_shard).astype(jnp.float8_e5m2)
            gathered = jax.lax.all_gather(delta, self.axis_name, axis=0,
                                          tiled=True).astype(jnp.float32)
            new_flat_p = flat_p + gathered
        else:
            new_flat_p = jax.lax.all_gather(
                new_p_shard.astype(flat_dtype), self.axis_name,
                axis=0, tiled=True)
        return unflatten(new_flat_p, schema), new_state

    def step_buckets(self, partial_grads, state: ShardedOptState, params,
                     schema: FlatSchema, plan):
        """Bucketed-overlap twin of :meth:`step` (ISSUE 15; reference
        DistributedFusedAdam's chunked reduce-scatter pipeline,
        distributed_fused_adam.py:316-362).  Call inside shard_map
        binding ``axis_name``.

        Two deliberate differences from :meth:`step`:

        * ``partial_grads`` are this device's UNSUMMED local grads —
          the grad of the device's *local* mean loss w.r.t. the full
          replicated master, taken inside the region.  The summing
          happens in the per-bucket reduce-scatter itself, which is
          the whole point: the per-leaf boundary all-reduces a
          replicated master grad costs (world × the grad bytes, fully
          serialized before the optimizer can start) never exist.
          Under the unreplicated-cotangent convention the mesh-sum of
          those partials is exactly ``world ×`` the grad of the
          data-mean loss — the same normalization :meth:`step` sees
          from ``world`` replicated copies — so ``grad_average``
          divides the same ``world`` back out (exact for power-of-two
          worlds; parity vs the serialized step is pinned bitwise in
          tests/L0/test_bucketed_zero.py).
        * the monolithic psum_scatter/all_gather pair becomes one
          reduce-scatter + all-gather per ``plan`` bucket.  A bucket is
          a span of the per-rank shard (a column block of the
          ``[world, shard]`` view — multi_tensor/buckets.py layout
          contract), so rank ``r`` receives exactly its canonical
          slice of every bucket and the returned state layout is
          IDENTICAL to :meth:`step`'s for every plan: bucket geometry
          cannot leak into the checkpoint/reshard contract.

        ``e5m2_allgather`` is not supported here (the delta transport
        needs the fp32 base resident across the whole gather — exactly
        the transient bucketing exists to retire); use :meth:`step`.
        """
        if self.e5m2_allgather:
            raise NotImplementedError(
                "e5m2_allgather is not supported by the bucketed step; "
                "use step() for the compressed-delta transport")
        world = jax.lax.psum(1, self.axis_name)
        rank = jax.lax.axis_index(self.axis_name)
        # axis sizes are static, so this catches a stale plan (e.g.
        # cached across an elastic mesh reshape) at trace time instead
        # of as an opaque XLA shape error inside the gather
        if plan.world != world or plan.shard != schema.total // world:
            raise ValueError(
                f"bucket plan (world={plan.world}, shard={plan.shard}) "
                f"does not match this axis: world={world}, shard="
                f"{schema.total // world} — re-plan after a mesh change")
        # hand-built plans are allowed (the registry builds one):
        # a permuted/gapped span set would reassemble the concat in
        # the wrong order with no shape error — refuse at trace time
        plan.validate()
        shard = plan.shard

        flat_g, _ = flatten(partial_grads, schema,
                            dtype=self.scatter_dtype or jnp.float32)
        flat_dtype = self.gather_dtype or jnp.float32
        flat_p, _ = flatten(params, schema, dtype=flat_dtype)
        # the canonical [world, shard] view: column block [:, lo:hi]
        # flattened rank-major is bucket b's reduce-scatter payload
        g_view = flat_g.reshape(plan.world, shard)

        new_m, new_v, new_cols = [], [], []
        for lo, hi in plan.spans:
            k = hi - lo
            g_b = jax.lax.psum_scatter(
                g_view[:, lo:hi].reshape(-1), self.axis_name,
                tiled=True).astype(jnp.float32)
            if self.grad_average:
                g_b = g_b / world
            p_b = jax.lax.dynamic_slice_in_dim(
                flat_p, rank * shard + lo, k).astype(jnp.float32)
            m_b = jax.lax.dynamic_slice_in_dim(state.exp_avg, lo, k)
            v_b = jax.lax.dynamic_slice_in_dim(state.exp_avg_sq, lo, k)
            # every bucket updates off the same pre-step counter;
            # _shard_update increments internally, so each bucket's
            # bias correction sees the identical step number
            sub = ShardedOptState(state.step, m_b, v_b)
            new_p_b, sub = self._shard_update(p_b, g_b, sub, None)
            new_m.append(sub.exp_avg)
            new_v.append(sub.exp_avg_sq)
            gathered = jax.lax.all_gather(
                new_p_b.astype(flat_dtype), self.axis_name,
                axis=0, tiled=True)
            new_cols.append(gathered.reshape(plan.world, k))

        new_state = ShardedOptState(
            step=state.step + 1,
            exp_avg=jnp.concatenate(new_m),
            exp_avg_sq=jnp.concatenate(new_v))
        new_flat_p = jnp.concatenate(new_cols, axis=1).reshape(-1)
        return unflatten(new_flat_p, schema), new_state


@dataclasses.dataclass(frozen=True)
class DistributedFusedAdam(DistributedShardedOptimizer):
    """Sharded AdamW (reference distributed_fused_adam.py:9; the update math
    is multi_tensor_distopt_adam_kernel.cu's)."""

    adam_w_mode: bool = True

    def _shard_update(self, p, g, state, flat_g):
        del flat_g
        b1, b2 = self.betas
        step = state.step + 1
        if not self.adam_w_mode:
            # classic-Adam mode: L2-style decay folded into the gradient
            # before the moment updates (reference non-AdamW branch)
            g = g + self.weight_decay * p
        # moments compute in fp32 and store in exp_avg_dtype: the
        # rounding happens once per step on the stored value only
        m = b1 * state.exp_avg.astype(jnp.float32) + (1 - b1) * g
        v = b2 * state.exp_avg_sq + (1 - b2) * g * g
        if self.bias_correction:
            c1 = 1 - b1 ** step.astype(jnp.float32)
            c2 = 1 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = 1.0
        update = (m / c1) / (jnp.sqrt(v / c2) + self.eps)
        if self.adam_w_mode:
            update = update + self.weight_decay * p
        new_p = p - self.lr * update
        return new_p, ShardedOptState(step, m.astype(self.exp_avg_dtype), v)


@dataclasses.dataclass(frozen=True)
class DistributedFusedLAMB(DistributedShardedOptimizer):
    """Sharded LAMB (reference distributed_fused_lamb.py:10): global grad
    norm for clipping, per-shard trust ratio over the shard's param/update
    norms.

    Divergence note: the reference computes the trust ratio per *tensor*
    (multi_tensor_lamb_compute_update_term); sharded layout makes per-shard
    the natural granularity here.  Per-tensor trust ratios remain available
    via the unsharded :class:`apex_tpu.optimizers.FusedLAMB`.
    """

    max_grad_norm: float = 1.0
    weight_decay: float = 0.01

    def step_buckets(self, partial_grads, state, params, schema, plan):
        """LAMB's global grad-norm prepass needs the WHOLE grad before
        any shard can clip — under bucketing that norm would silently
        become per-bucket (a different optimizer).  Refuse rather than
        diverge; the bucketed flagship path is Adam's."""
        raise NotImplementedError(
            "DistributedFusedLAMB has a global grad-norm prepass that "
            "a per-bucket pipeline cannot honor; use step(), or "
            "DistributedFusedAdam for the bucketed path")

    def _shard_update(self, p, g, state, flat_g):
        b1, b2 = self.betas
        step = state.step + 1
        # global grad norm: shard-local square-sum, psum'd (the reference's
        # fused L2-norm prepass + allreduce, distributed_fused_lamb.py:592)
        local_sq = jnp.sum(g * g)
        global_norm = jnp.sqrt(jax.lax.psum(local_sq, self.axis_name))
        if self.max_grad_norm > 0:
            clip = jnp.maximum(1.0, global_norm / self.max_grad_norm)
            g = g / clip
        m = b1 * state.exp_avg.astype(jnp.float32) + (1 - b1) * g
        v = b2 * state.exp_avg_sq + (1 - b2) * g * g
        if self.bias_correction:
            c1 = 1 - b1 ** step.astype(jnp.float32)
            c2 = 1 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = 1.0
        update = (m / c1) / (jnp.sqrt(v / c2) + self.eps)
        update = update + self.weight_decay * p
        p_norm = jnp.linalg.norm(p)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0)
        new_p = p - self.lr * trust * update
        return new_p, ShardedOptState(step, m.astype(self.exp_avg_dtype), v)
