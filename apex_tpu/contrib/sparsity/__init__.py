"""apex_tpu.contrib.sparsity — ASP structured sparsity
(reference apex/contrib/sparsity/)."""

from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.sparse_masklib import (  # noqa: F401
    create_mask,
    m4n2_1d,
    unstructured_fraction,
)
