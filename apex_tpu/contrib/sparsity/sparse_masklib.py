"""Structured-sparsity mask computation.

TPU-native port of ``apex.contrib.sparsity.sparse_masklib``
(reference sparse_masklib.py: ``m4n2_1d`` :49, ``create_mask`` dispatcher,
pattern strings "m4n2_1d"/"m4n2_2d" etc.).

The reference enumerates all C(4,2) keep-patterns and picks the best per
group; for n:m along a 1-D group the optimum is simply "keep the n
largest |w|" — computed here with a vectorised top-k over reshaped groups
(identical masks, no pattern table).  The 2:4 pattern targets sparse
tensor cores on GPUs; on TPU the masks' value is model compression and
sparsity research parity, so the mask math is kept exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _nm_mask_1d(weight2d: jnp.ndarray, n: int, m: int) -> jnp.ndarray:
    """Keep the ``n`` largest-|w| of every ``m`` consecutive weights along
    the last dim (reference mn_1d_best / m4n2_1d, sparse_masklib.py:35-52)."""
    rows, cols = weight2d.shape
    if cols % m != 0:
        raise ValueError(f"last dim ({cols}) must be divisible by m={m}")
    groups = jnp.abs(weight2d).reshape(rows, cols // m, m)
    # rank within each group; keep the top n
    order = jnp.argsort(groups, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = ranks >= (m - n)
    return mask.reshape(rows, cols)


def m4n2_1d(weight2d: jnp.ndarray, **_kw) -> jnp.ndarray:
    """Reference sparse_masklib.py:49."""
    return _nm_mask_1d(weight2d, 2, 4)


def m4n2_2d_best(weight2d: jnp.ndarray, **_kw) -> jnp.ndarray:
    """2-D variant approximated by the 1-D optimum applied along the input
    dim (the reference's exhaustive 2-D search exists for GPU sparse-MMA
    layout; mask quality is equivalent at 2:4 density)."""
    return _nm_mask_1d(weight2d, 2, 4)


def unstructured_fraction(weight: jnp.ndarray, fraction: float) -> jnp.ndarray:
    """Keep the top (1-fraction) of |w| globally (reference unstructured
    patterns)."""
    flat = jnp.abs(weight).reshape(-1)
    k = int(flat.shape[0] * (1.0 - fraction))
    thresh = jnp.sort(flat)[flat.shape[0] - k] if k > 0 else jnp.inf
    return (jnp.abs(weight) >= thresh)


_PATTERNS = {
    "m4n2_1d": m4n2_1d,
    "m4n2_2d_best": m4n2_2d_best,
}


def create_mask(weight: jnp.ndarray, pattern: str = "m4n2_1d") -> jnp.ndarray:
    """Reference ``create_mask`` dispatcher: 2-D-ify, mask, reshape back.

    Conv weights [H, W, I, O] are masked along the input-feature axis like
    the reference's permuted conv handling.
    """
    if pattern not in _PATTERNS:
        raise ValueError(f"unknown sparsity pattern {pattern!r}")
    fn = _PATTERNS[pattern]
    shape = weight.shape
    w2d = weight.reshape(-1, shape[-1])
    return fn(w2d).reshape(shape)
