"""ASP — automatic structured (2:4) sparsity.

TPU-native re-design of ``apex.contrib.sparsity.ASP``
(reference asp.py: ``init_model_for_pruning`` :139, mask re-application on
every optimizer step :139-153, ``prune_trained_model`` :212).

The reference monkey-patches ``optimizer.step`` to re-apply masks after
every update.  Functionally, masks are just another pytree: compute them
once from trained weights, then multiply into the params after each
optimizer step (``apply_masks``) — the composition point the reference's
patching simulates.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask


def _default_allowed(path, leaf) -> bool:
    """Reference default: prune 2-D+ weights with both dims ≥ 16 and
    divisible group dims (asp.py allowed_layer_names/whitelist logic)."""
    if not hasattr(leaf, "ndim") or leaf.ndim < 2:
        return False
    if leaf.shape[-1] % 4 != 0:
        return False
    return leaf.shape[-1] >= 16 and int(jnp.prod(
        jnp.array(leaf.shape[:-1]))) >= 16


class ASP:
    """Functional ASP. Typical use (mirrors reference asp.py:212
    ``prune_trained_model(model, optimizer)``)::

        asp = ASP(mask_pattern="m4n2_1d")
        masks = asp.compute_sparse_masks(params)     # from trained weights
        params = asp.apply_masks(params, masks)      # prune
        ...
        params = opt.step(...); params = asp.apply_masks(params, masks)
    """

    def __init__(self, mask_pattern: str = "m4n2_1d",
                 allowed_predicate: Optional[Callable] = None,
                 verbosity: int = 0):
        self.mask_pattern = mask_pattern
        self.allowed = allowed_predicate or _default_allowed
        self.verbosity = verbosity

    def compute_sparse_masks(self, params: Any) -> Any:
        """Masks pytree: boolean per prunable leaf, ``None`` elsewhere
        (reference compute_sparse_masks asp.py:139-160)."""
        def mask_leaf(path, leaf):
            if self.allowed(path, leaf):
                return create_mask(leaf, self.mask_pattern)
            return None

        return jax.tree_util.tree_map_with_path(mask_leaf, params)

    def apply_masks(self, params: Any, masks: Any) -> Any:
        """Multiply masks in (the step the reference re-runs after every
        optimizer update, asp.py:139-153)."""
        return jax.tree_util.tree_map(
            lambda p, m: p if m is None else p * m.astype(p.dtype),
            params, masks, is_leaf=lambda x: x is None)

    def prune_trained_model(self, params: Any) -> Any:
        """One-shot prune (reference asp.py:212): compute + apply."""
        masks = self.compute_sparse_masks(params)
        return self.apply_masks(params, masks), masks

    @staticmethod
    def sparsity(params: Any) -> float:
        leaves = [l for l in jax.tree_util.tree_leaves(params)
                  if hasattr(l, "size")]
        zeros = sum(float(jnp.sum(l == 0)) for l in leaves)
        total = sum(l.size for l in leaves)
        return zeros / max(total, 1)
