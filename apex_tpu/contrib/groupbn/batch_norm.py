"""GroupBN — NHWC batch norm with group statistics + fused add/relu.

Capability parity with the reference contrib groupbn
(apex/contrib/groupbn/batch_norm.py:7-234 over csrc/groupbn/, 2,855 LoC:
persistent NHWC kernels, cross-GPU IPC peer-stat exchange keyed by "magic"
tokens, occupancy tuning), re-designed for TPU:

- ``bn_group`` peer statistics: the reference moves per-GPU partial sums
  through CUDA IPC buffers between explicit peer ranks
  (batch_norm.py:120-160 my_data/pair_data plumbing). On a mesh this is
  just a ``psum`` over a *sub-axis* — the same
  ``create_syncbn_process_group`` mapping used by
  :mod:`apex_tpu.parallel.sync_batchnorm`, which provides the stats math
  (Welford-merge-equivalent moment combination).
- ``fuse_relu`` and the ``bn_addrelu`` variant (forward takes a residual
  ``z``, applies relu after the add; backward re-derives the relu mask —
  the reference materialises a bitmask buffer, batch_norm.py:57-60): here
  plain expressions that XLA fuses into the normalize epilogue; AD
  recomputes the mask, no bitmask storage.
- ``minibatch_mean`` / ``minibatch_riv`` buffers (reference
  batch_norm.py:110-111) are carried in the state dict for parity — the
  last training-step batch statistics.

Occupancy knobs (max_cta_per_sm, cta_launch_margin, multi_stream) are
accepted and ignored: grid scheduling belongs to XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import (
    AxisName,
    sync_batch_norm_stats,
    update_running_stats,
)


class BatchNorm2d_NHWC:
    """NHWC BatchNorm2d with group stats and fused (add+)relu
    (reference BatchNorm2d_NHWC, batch_norm.py:103-234).

    ``bn_group > 1`` requires ``axis_name`` — the mesh (sub-)axis whose
    devices pool their statistics; the caller shapes the mesh so that axis
    has size ``bn_group`` (create_syncbn_process_group pattern).
    """

    def __init__(
        self,
        num_features: int,
        fuse_relu: bool = False,
        bn_group: int = 1,
        axis_name: AxisName = None,
        eps: float = 1e-5,
        momentum: float = 0.1,
        max_cta_per_sm: int = 2,
        cta_launch_margin: int = 12,
        multi_stream: bool = False,
    ):
        del max_cta_per_sm, cta_launch_margin, multi_stream
        if bn_group > 1 and axis_name is None:
            raise ValueError("bn_group > 1 requires axis_name (mesh sub-axis)")
        self.num_features = num_features
        self.fuse_relu = fuse_relu
        self.bn_group = bn_group
        self.axis_name = axis_name if bn_group > 1 else None
        self.eps = eps
        self.momentum = momentum

    def init(self, dtype=jnp.float32):
        c = self.num_features
        return {
            "params": {
                "weight": jnp.ones((c,), dtype),
                "bias": jnp.zeros((c,), dtype),
            },
            "state": {
                "running_mean": jnp.zeros((c,), jnp.float32),
                "running_var": jnp.ones((c,), jnp.float32),
                "minibatch_mean": jnp.zeros((c,), jnp.float32),
                "minibatch_riv": jnp.ones((c,), jnp.float32),
            },
        }

    def apply(self, variables, x, z=None, *, training: bool = True):
        """Returns ``(y, new_variables)``. ``z`` is the optional residual
        added before relu (the bn_addrelu path, batch_norm.py:53-99;
        passing ``z`` implies relu, as in the reference's forward at
        :200-214)."""
        params, state = variables["params"], variables["state"]
        if training:
            mean, var, n = sync_batch_norm_stats(x, self.axis_name, channel_axis=-1)
            invstd = jax.lax.rsqrt(var + self.eps)
            rm, rv = update_running_stats(
                state["running_mean"], state["running_var"], mean, var, n,
                self.momentum)
            new_state = {
                "running_mean": rm,
                "running_var": rv,
                "minibatch_mean": mean,
                "minibatch_riv": invstd,
            }
        else:
            mean = state["running_mean"]
            invstd = jax.lax.rsqrt(state["running_var"] + self.eps)
            new_state = dict(state)

        w = params["weight"].astype(jnp.float32)
        b = params["bias"].astype(jnp.float32)
        y = (x.astype(jnp.float32) - mean) * invstd * w + b
        if z is not None:
            y = y + z.astype(jnp.float32)
            y = jax.nn.relu(y)
        elif self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(x.dtype), {"params": params, "state": new_state}

    __call__ = apply
