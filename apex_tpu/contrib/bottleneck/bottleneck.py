"""ResNet bottleneck block + spatial (H-dim) parallelism.

Capability parity with the reference contrib bottleneck
(apex/contrib/bottleneck/bottleneck.py: ``Bottleneck`` :64-216 and
``SpatialBottleneck`` :218-510 over csrc/bottleneck/bottleneck.cpp, 2,486
LoC of cuDNN-frontend fused conv-scale-bias-relu), re-designed for TPU:

- The block is conv1x1 → conv3x3(stride) → conv1x1, each followed by a
  *frozen-BN* affine (scale·y + bias) and relu, with a residual add (and an
  optional strided 1x1 downsample path). The reference fuses
  conv+scale+bias+relu via cuDNN runtime fusion; XLA's epilogue fusion does
  the same from the plain expression — no hand-built graph needed.
- **Spatial parallelism**: the reference shards the H dimension across a
  process group and hand-rolls a halo exchange for the 3x3 conv — an
  allgather of 2-row halo buffers plus dedicated halo-conv kernel launches
  on a side stream (bottleneck.py:239-268), with mirrored halo terms in
  dgrad/wgrad (:289-510). Here each rank's halo rows move with two
  ``lax.ppermute`` steps over the mesh axis and the 3x3 conv runs once on
  the halo-extended shard with VALID padding in H. Gradients need no
  hand-written halo path at all: the transpose of ``ppermute`` is the
  reverse ``ppermute``, so AD derives the reference's backward halo
  exchange automatically.

Halo geometry: XLA "SAME" padding is TF-style — for kernel k and stride s
the total pad is k−s (k≥s), split pad_lo = (k−s)//2, pad_hi = k−s−pad_lo.
For k=3, s=1 that is (1, 1); for k=3, s=2 it is **(0, 1)** — asymmetric.
The halo exchange mirrors exactly that: ``halo_lo`` rows from the rank
above, ``halo_hi`` from the rank below, with global-edge ranks receiving
zeros (ppermute's no-source default == the conv's zero padding).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


def halo_exchange(x, axis_name: str, halo_lo: int = 1, halo_hi: int = 1):
    """Extend an H-sharded NHWC shard with neighbor rows.

    (N, H_local, W, C) → (N, halo_lo + H_local + halo_hi, W, C).
    Ranks at the global edge receive zeros (ppermute leaves targets with no
    source at zero), matching SAME-conv zero padding. TPU mapping of the
    reference's send-buffer + all_gather halo path (bottleneck.py:243-252):
    two point-to-point ``ppermute`` streams over ICI instead of a gather of
    every rank's halos.
    """
    n = lax.psum(1, axis_name)
    parts = []
    if halo_lo:
        # my bottom rows become the rank below's top halo
        btm = x[:, -halo_lo:]
        parts.append(lax.ppermute(btm, axis_name, [(i, i + 1) for i in range(n - 1)]))
    parts.append(x)
    if halo_hi:
        # my top rows become the rank above's bottom halo
        top = x[:, :halo_hi]
        parts.append(lax.ppermute(top, axis_name, [(i, i - 1) for i in range(1, n)]))
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else x


def spatial_conv2d(x, w, *, stride: int = 1, axis_name: Optional[str] = None):
    """2-D conv (NHWC · HWIO), SAME-padded globally, with the H dimension
    optionally sharded over ``axis_name``.

    Unsharded it is a plain ``conv_general_dilated``. Sharded, the halo
    exchange supplies exactly the rows SAME padding would read across the
    shard boundary, and the conv runs VALID in H. Requires
    ``H_local % stride == 0`` (same contract as the reference's equal
    H-split across the spatial group).
    """
    kh, kw = w.shape[0], w.shape[1]
    if axis_name is None:
        return lax.conv_general_dilated(
            x, w, (stride, stride), "SAME", dimension_numbers=_DIMNUMS
        )
    if x.shape[1] % stride:
        raise ValueError("local H must be divisible by the stride")
    # TF-SAME split for kernel k, stride s (input divisible by s):
    # total = k - s, lo = total // 2 — asymmetric when strided
    pad_h = max(kh - stride, 0)
    halo_lo, halo_hi = pad_h // 2, pad_h - pad_h // 2
    pad_w = max(kw - stride, 0)
    xh = halo_exchange(x, axis_name, halo_lo, halo_hi)
    return lax.conv_general_dilated(
        xh,
        w,
        (stride, stride),
        [(0, 0), (pad_w // 2, pad_w - pad_w // 2)],
        dimension_numbers=_DIMNUMS,
    )


def _scale_bias_relu(y, scale, bias, relu=True):
    y = y * scale.astype(y.dtype) + bias.astype(y.dtype)
    return jax.nn.relu(y) if relu else y


class SpatialBottleneck:
    """Bottleneck block with optional H-dim spatial parallelism.

    ``axis_name=None`` reproduces the reference ``Bottleneck``
    (bottleneck.py:64-216); with an axis name it is ``SpatialBottleneck``
    (:218-510) — same parameters, H-sharded input/output shards.

    Frozen-BN semantics as the reference: BN is folded to per-channel
    (scale, bias); there are no running stats (the use case is
    detection-style fine-tuning with frozen BN).
    ``stride_1x1=True`` places the stride on the first 1x1 conv
    (reference arg, bottleneck.py:77 ``use_cudnn_bottleneck`` path); False
    (torchvision style) strides the 3x3.
    """

    def __init__(self, in_channels: int, bottleneck_channels: int,
                 out_channels: int, stride: int = 1, stride_1x1: bool = False,
                 axis_name: Optional[str] = None):
        self.in_channels = in_channels
        self.bottleneck_channels = bottleneck_channels
        self.out_channels = out_channels
        self.stride = stride
        self.stride_1x1 = stride_1x1
        self.axis_name = axis_name
        self.has_downsample = stride != 1 or in_channels != out_channels

    def init(self, key, dtype=jnp.float32):
        c_in, c_b, c_out = self.in_channels, self.bottleneck_channels, self.out_channels
        ks = jax.random.split(key, 4)

        def he(k, shape):
            fan_in = shape[0] * shape[1] * shape[2]
            return jax.random.normal(k, shape, dtype) * math.sqrt(2.0 / fan_in)

        params = {
            "conv1": he(ks[0], (1, 1, c_in, c_b)),
            "conv2": he(ks[1], (3, 3, c_b, c_b)),
            "conv3": he(ks[2], (1, 1, c_b, c_out)),
        }
        for i in (1, 2, 3):
            params[f"scale{i}"] = jnp.ones((params[f"conv{i}"].shape[-1],), dtype)
            params[f"bias{i}"] = jnp.zeros((params[f"conv{i}"].shape[-1],), dtype)
        if self.has_downsample:
            params["conv4"] = he(ks[3], (1, 1, c_in, c_out))
            params["scale4"] = jnp.ones((c_out,), dtype)
            params["bias4"] = jnp.zeros((c_out,), dtype)
        return params

    def apply(self, params, x):
        s1 = self.stride if self.stride_1x1 else 1
        s2 = 1 if self.stride_1x1 else self.stride
        ax = self.axis_name
        if ax is not None and self.stride > 1 and x.shape[1] % self.stride:
            # a shard-local strided conv only equals the global one when each
            # shard keeps the global stride phase (1x1 SAME stride-s reads
            # rows s*o, so the shard's first row must sit at an s-aligned
            # global offset — guaranteed iff H_local % s == 0)
            raise ValueError(
                f"local H ({x.shape[1]}) must be divisible by stride "
                f"({self.stride}) under spatial sharding")
        # 1x1 convs and the affine/relu epilogues are purely local in H
        out = lax.conv_general_dilated(
            x, params["conv1"], (s1, s1), "SAME", dimension_numbers=_DIMNUMS)
        out = _scale_bias_relu(out, params["scale1"], params["bias1"])
        # only the 3x3 sees neighbor rows
        out = spatial_conv2d(out, params["conv2"], stride=s2, axis_name=ax)
        out = _scale_bias_relu(out, params["scale2"], params["bias2"])
        out = lax.conv_general_dilated(
            out, params["conv3"], (1, 1), "SAME", dimension_numbers=_DIMNUMS)
        out = _scale_bias_relu(out, params["scale3"], params["bias3"], relu=False)
        if self.has_downsample:
            resid = lax.conv_general_dilated(
                x, params["conv4"], (self.stride, self.stride), "SAME",
                dimension_numbers=_DIMNUMS)
            resid = _scale_bias_relu(resid, params["scale4"], params["bias4"],
                                     relu=False)
        else:
            resid = x
        return jax.nn.relu(out + resid)

    __call__ = apply


class Bottleneck(SpatialBottleneck):
    """Unsharded block (reference apex/contrib/bottleneck/bottleneck.py:64)."""

    def __init__(self, in_channels, bottleneck_channels, out_channels,
                 stride=1, stride_1x1: bool = False):
        super().__init__(in_channels, bottleneck_channels, out_channels,
                         stride=stride, stride_1x1=stride_1x1, axis_name=None)
