"""Bottleneck block + spatial (H-dim) parallelism
(reference apex/contrib/bottleneck/)."""

from apex_tpu.contrib.bottleneck.bottleneck import (
    Bottleneck,
    SpatialBottleneck,
    halo_exchange,
    spatial_conv2d,
)

__all__ = ["Bottleneck", "SpatialBottleneck", "halo_exchange", "spatial_conv2d"]
