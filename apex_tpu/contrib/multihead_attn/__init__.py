"""apex_tpu.contrib.multihead_attn — fused MHA modules
(reference apex/contrib/multihead_attn/, 8 CUDA extensions)."""

from apex_tpu.contrib.multihead_attn.attn import (  # noqa: F401
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
