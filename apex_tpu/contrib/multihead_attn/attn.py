"""Fused self / encoder-decoder multi-head attention modules.

TPU-native re-design of the reference's ``fast_multihead_attn`` family
(reference apex/contrib/multihead_attn/: ``SelfMultiheadAttn``
self_multihead_attn.py:26, ``EncdecMultiheadAttn``, plus the 6 fused CUDA
variants self/encdec × {plain, bias, norm-add, additive-mask} behind
``impl='fast'``).

All variants collapse onto one code path backed by the Pallas flash
kernel (:func:`apex_tpu.ops.attention.flash_attention`):

* ``bias``        → bias terms on the projections,
* ``include_norm_add`` → fused pre-LayerNorm + residual add,
* additive mask   → ``mask_bias`` straight into the kernel,
* dropout         → Bernoulli on attention probs... applied as a second
  masked pass (see note in ``apply``).

Layout: [seq, batch, hidden] like the reference modules; projections use
the packed-QKV weight the reference keeps (``in_proj_weight``
[3·h, h] self, [2·h, h] + q [h, h] encdec) so checkpoints line up.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.fused_layer_norm import layer_norm


def _split_heads(x, heads):
    # [s, b, h] -> [b*heads, s, h/heads]
    s, b, h = x.shape
    d = h // heads
    return x.reshape(s, b * heads, d).transpose(1, 0, 2)


def _merge_heads(x, b):
    # [b*heads, s, d] -> [s, b, h]
    bh, s, d = x.shape
    return x.transpose(1, 0, 2).reshape(s, b, (bh // b) * d)


class SelfMultiheadAttn:
    """Reference SelfMultiheadAttn (self_multihead_attn.py:26)."""

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = False, include_norm_add: bool = False,
                 impl: str = "fast", separate_qkv_params: bool = False,
                 mask_additive: bool = False):
        if embed_dim % num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.bias = bias
        self.include_norm_add = include_norm_add
        self.mask_additive = mask_additive
        self.scaling = (embed_dim // num_heads) ** -0.5
        del impl  # one fused TPU path

    def init(self, key, dtype=jnp.float32):
        h = self.embed_dim
        k1, k2 = jax.random.split(key)
        bound = 1.0 / math.sqrt(h)
        p = {
            "in_proj_weight": jax.random.uniform(k1, (3 * h, h), dtype,
                                                 -bound, bound),
            "out_proj_weight": jax.random.uniform(k2, (h, h), dtype,
                                                  -bound, bound),
        }
        if self.bias:
            p["in_proj_bias"] = jnp.zeros((3 * h,), dtype)
            p["out_proj_bias"] = jnp.zeros((h,), dtype)
        if self.include_norm_add:
            p["lyr_nrm_gamma_weights"] = jnp.ones((h,), dtype)
            p["lyr_nrm_beta_weights"] = jnp.zeros((h,), dtype)
        return p

    def apply(self, params, query, *, key_padding_mask=None, attn_mask=None,
              is_training: bool = True, dropout_rng=None):
        """query: [seq, batch, hidden].  Masks follow the reference: boolean
        True = masked out, or additive floats when ``mask_additive``."""
        s, b, h = query.shape
        residual = query
        x = query
        if self.include_norm_add:
            x = layer_norm(x, params["lyr_nrm_gamma_weights"],
                           params["lyr_nrm_beta_weights"])
        qkv = x @ params["in_proj_weight"].T
        if self.bias:
            qkv = qkv + params["in_proj_bias"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        qh = _split_heads(q, self.num_heads)
        kh = _split_heads(k, self.num_heads)
        vh = _split_heads(v, self.num_heads)

        if (key_padding_mask is not None and attn_mask is None
                and not self.mask_additive):
            # boolean key-padding variant (r7): ride the varlen fast
            # path — segment ids with all-ones query ids reproduce
            # key-side-only masking (pad query rows still attend real
            # keys, like the -10000.0 additive fill whose exp
            # underflows to the same zeros), without materialising a
            # [b*heads, sq, sk] additive mask, and with padding-tail
            # k-blocks skipped in-kernel via the block-skip index.
            # Exact for every row with >= 1 real key; a row whose mask
            # is ALL True returns zeros (the flash l==0 convention)
            # where the additive fill would return a softmax over the
            # masked keys — garbage either way, but different garbage
            keep = (~key_padding_mask.astype(bool)).astype(jnp.int32)
            seg_k = jnp.repeat(keep, self.num_heads, axis=0)  # [b*h, sk]
            ctx = flash_attention(
                qh, kh, vh,
                segment_ids=(jnp.ones((qh.shape[0], s), jnp.int32),
                             seg_k),
                scale=self.scaling)
        else:
            mask_bias = None
            if key_padding_mask is not None:
                # [b, sk] -> additive [b*heads, sq, sk]
                if self.mask_additive:
                    add = key_padding_mask.astype(jnp.float32)
                else:
                    add = jnp.where(key_padding_mask, -10000.0, 0.0)
                add = jnp.repeat(add[:, None, None, :], self.num_heads,
                                 axis=1)
                mask_bias = jnp.broadcast_to(
                    add, (b, self.num_heads, s, add.shape[-1])).reshape(
                    b * self.num_heads, s, add.shape[-1])
            if attn_mask is not None:
                am = (attn_mask.astype(jnp.float32) if self.mask_additive
                      else jnp.where(attn_mask, -10000.0, 0.0))
                am = jnp.broadcast_to(am, (b * self.num_heads, s, s))
                mask_bias = am if mask_bias is None else mask_bias + am
            ctx = flash_attention(qh, kh, vh, mask_bias=mask_bias,
                                  scale=self.scaling)
        if is_training and self.dropout > 0.0 and dropout_rng is not None:
            # the reference fuses dropout into the softmax kernel; applying
            # it on the context preserves the regularisation contract
            # without re-materialising probabilities
            keep = jax.random.bernoulli(dropout_rng, 1 - self.dropout,
                                        ctx.shape)
            ctx = jnp.where(keep, ctx / (1 - self.dropout), 0)
        out = _merge_heads(ctx, b) @ params["out_proj_weight"].T
        if self.bias:
            out = out + params["out_proj_bias"]
        if self.include_norm_add:
            out = out + residual  # fused residual add (norm-add variant)
        return out

    __call__ = apply


class EncdecMultiheadAttn(SelfMultiheadAttn):
    """Reference EncdecMultiheadAttn (encdec_multihead_attn.py): query from
    the decoder, key/value from the encoder."""

    def init(self, key, dtype=jnp.float32):
        h = self.embed_dim
        k1, k2, k3 = jax.random.split(key, 3)
        bound = 1.0 / math.sqrt(h)
        p = {
            "q_weight": jax.random.uniform(k1, (h, h), dtype, -bound, bound),
            "kv_weight": jax.random.uniform(k2, (2 * h, h), dtype,
                                            -bound, bound),
            "out_proj_weight": jax.random.uniform(k3, (h, h), dtype,
                                                  -bound, bound),
        }
        if self.bias:
            p["q_bias"] = jnp.zeros((h,), dtype)
            p["kv_bias"] = jnp.zeros((2 * h,), dtype)
            p["out_proj_bias"] = jnp.zeros((h,), dtype)
        if self.include_norm_add:
            p["lyr_nrm_gamma_weights"] = jnp.ones((h,), dtype)
            p["lyr_nrm_beta_weights"] = jnp.zeros((h,), dtype)
        return p

    def apply(self, params, query, key=None, value=None, *,
              key_padding_mask=None, attn_mask=None,
              is_training: bool = True, dropout_rng=None):
        sq, b, h = query.shape
        enc = key if key is not None else query
        residual = query
        x = query
        if self.include_norm_add:
            x = layer_norm(x, params["lyr_nrm_gamma_weights"],
                           params["lyr_nrm_beta_weights"])
        q = x @ params["q_weight"].T
        kv = enc @ params["kv_weight"].T
        if self.bias:
            q = q + params["q_bias"]
            kv = kv + params["kv_bias"]
        k_, v_ = jnp.split(kv, 2, axis=-1)

        qh = _split_heads(q, self.num_heads)
        kh = _split_heads(k_, self.num_heads)
        vh = _split_heads(v_, self.num_heads)

        sk = enc.shape[0]
        if (key_padding_mask is not None and attn_mask is None
                and not self.mask_additive):
            # encoder-side padding as segment ids (cross-length pair):
            # same varlen fast-path routing as the self variant
            keep = (~key_padding_mask.astype(bool)).astype(jnp.int32)
            ctx = flash_attention(
                qh, kh, vh,
                segment_ids=(jnp.ones((qh.shape[0], sq), jnp.int32),
                             jnp.repeat(keep, self.num_heads, axis=0)),
                scale=self.scaling)
        else:
            mask_bias = None
            if key_padding_mask is not None:
                add = (key_padding_mask.astype(jnp.float32)
                       if self.mask_additive
                       else jnp.where(key_padding_mask, -10000.0, 0.0))
                add = jnp.repeat(add[:, None, None, :], self.num_heads,
                                 axis=1)
                mask_bias = jnp.broadcast_to(
                    add, (b, self.num_heads, sq, sk)).reshape(
                    b * self.num_heads, sq, sk)
            ctx = flash_attention(qh, kh, vh, mask_bias=mask_bias,
                                  scale=self.scaling)
        if is_training and self.dropout > 0.0 and dropout_rng is not None:
            keep = jax.random.bernoulli(dropout_rng, 1 - self.dropout,
                                        ctx.shape)
            ctx = jnp.where(keep, ctx / (1 - self.dropout), 0)
        out = _merge_heads(ctx, b) @ params["out_proj_weight"].T
        if self.bias:
            out = out + params["out_proj_bias"]
        if self.include_norm_add:
            out = out + residual
        return out

    __call__ = apply
