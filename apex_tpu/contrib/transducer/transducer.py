"""RNN-T transducer joint + loss, TPU-native.

Capability parity with the reference transducer extension
(apex/contrib/transducer/transducer.py:5-196 over ~1,906 LoC of CUDA in
apex/contrib/csrc/transducer/), re-designed for XLA:

- **Joint** (`TransducerJoint`, reference transducer.py:5-68): the fused
  "f + g outer sum (+ relu, + dropout, + packing)" — here a broadcast add
  that XLA fuses with the epilogue; packing is a static-shape scatter
  (compact output for variable (f_len, g_len), same batch_offset contract
  as the reference).
- **Loss** (`TransducerLoss`, reference transducer.py:70-196): alpha/beta
  dynamic programming over the (T, U) lattice. The CUDA kernels walk the
  lattice with per-batch thread blocks; here both DPs run as ONE
  `lax.scan` over anti-diagonals (wavefront parallelism: every cell of a
  diagonal is independent, vectorized over batch x diagonal on the VPU),
  over pre-sheared transition matrices so each step is a contiguous slice,
  not a gather.
- The backward is a `custom_vjp` with the **analytic** alpha-beta gradient
  fused with the softmax backward (reference ``fuse_softmax_backward=True``
  path, transducer.py:133-162): one pass producing dL/dx directly from
  (x_log, alpha, beta) — no saved softmax output, no second DP.

Numerics note: invalid lattice transitions carry ``_NEG_INF = -1e30``
(not literal -inf) so fp32 sums stay finite; ``exp`` of them underflows
to exactly 0.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1.0e30


# ---------------------------------------------------------------------------
# Joint
# ---------------------------------------------------------------------------


def transducer_joint(
    f,
    g,
    f_len,
    g_len,
    *,
    pack_output: bool = False,
    relu: bool = False,
    dropout_prob: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
    batch_offset=None,
    packed_batch: int = 0,
):
    """Transducer joint: ``h[b,t,u] = f[b,t] + g[b,u]`` with optional fused
    relu/dropout and optional packing (reference TransducerJointFunc,
    transducer.py:164-196).

    f: (B, T, H) transcription (encoder) vectors.
    g: (B, U, H) prediction (decoder) vectors; ``g_len = y_len + 1``.
    Don't-care cells (t >= f_len or u >= g_len) are zeroed (the reference
    kernel leaves them unwritten; zero keeps AD NaN-free).

    With ``pack_output=True``, ``batch_offset = cumsum(f_len * g_len)`` and
    ``packed_batch`` (a static int >= batch_offset[-1]) must be given —
    same contract as the reference (transducer.py:43-66) — and the result
    is (packed_batch, H).
    """
    B, T, H = f.shape
    U = g.shape[1]
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout_prob:
        if dropout_key is None:
            raise ValueError("dropout_prob > 0 requires dropout_key")
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_prob, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_prob), 0.0)
    valid = (jnp.arange(T)[None, :, None] < f_len[:, None, None]) & (
        jnp.arange(U)[None, None, :] < g_len[:, None, None]
    )
    h = jnp.where(valid[..., None], h, 0.0)
    if not pack_output:
        return h
    if batch_offset is None or not packed_batch:
        raise ValueError("pack_output=True requires batch_offset and packed_batch")
    return _pack(h, f_len, g_len, batch_offset, packed_batch, valid)


def _cell_index(f_len, g_len, batch_offset, T: int, U: int):
    """The packed-cell addressing contract, in one place:
    ``idx[b,t,u] = batch_offset[b-1] + t*g_len[b] + u`` with validity mask
    ``(t < f_len[b]) & (u < g_len[b])``. Returns ``(idx, valid)``."""
    start = batch_offset - f_len * g_len  # offset of batch b's first cell
    t_idx = jnp.arange(T)[None, :, None]
    u_idx = jnp.arange(U)[None, None, :]
    idx = start[:, None, None] + t_idx * g_len[:, None, None] + u_idx
    valid = (t_idx < f_len[:, None, None]) & (u_idx < g_len[:, None, None])
    return idx, valid


def _pack(h, f_len, g_len, batch_offset, packed_batch: int, valid=None):
    """Scatter the valid (b,t,u) cells of ``h`` into a compact
    (packed_batch, H) buffer."""
    B, T, U, H = h.shape
    dest, v = _cell_index(f_len, g_len, batch_offset, T, U)
    if valid is None:
        valid = v
    # invalid cells scatter out of bounds and are dropped
    dest = jnp.where(valid, dest, packed_batch)
    out = jnp.zeros((packed_batch, H), h.dtype)
    return out.at[dest.reshape(-1)].set(h.reshape(-1, H), mode="drop")


def _unpack(x_packed, f_len, g_len, batch_offset, B: int, T: int, U: int):
    """Inverse of :func:`_pack` (gather); used to adapt packed loss inputs
    to the dense lattice layout the DP wants."""
    src, valid = _cell_index(f_len, g_len, batch_offset, T, U)
    src = jnp.where(valid, src, 0)
    out = x_packed[src.reshape(-1)].reshape(B, T, U, x_packed.shape[-1])
    return jnp.where(valid[..., None], out, 0.0)


class TransducerJoint:
    """Module-style wrapper mirroring the reference class
    (transducer.py:5-68). ``opt``/``fwd_tile_size`` are accepted for API
    parity and ignored — tiling is XLA's job."""

    def __init__(self, pack_output=False, relu=False, dropout=False, opt=1,
                 fwd_tile_size=4, dropout_prob=0.0, probe_mask=False):
        del opt, fwd_tile_size
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob
        if probe_mask:
            raise NotImplementedError("probe_mask: pass dropout_key and regenerate the mask")

    def __call__(self, f, g, f_len, g_len, batch_offset=None, packed_batch=0,
                 dropout_key=None, training=True):
        p = self.dropout_prob if (self.dropout and training) else 0.0
        return transducer_joint(
            f, g, f_len, g_len,
            pack_output=self.pack_output, relu=self.relu, dropout_prob=p,
            dropout_key=dropout_key, batch_offset=batch_offset,
            packed_batch=packed_batch,
        )


# ---------------------------------------------------------------------------
# Loss: alpha/beta wavefront DP
# ---------------------------------------------------------------------------


def _shear(m, fill):
    """(B, T, U) -> (D, B, T) with D = T+U-1, sheared so that
    ``out[d, b, t] = m[b, t, d - t]`` (anti-diagonal d as a contiguous
    slice). Cells off the lattice get ``fill``."""
    B, T, U = m.shape
    D = T + U - 1
    d = jnp.arange(D)[:, None]
    t = jnp.arange(T)[None, :]
    u = d - t  # (D, T)
    ok = (u >= 0) & (u < U)
    gathered = m[:, t, jnp.clip(u, 0, U - 1)]  # (B, D, T)
    return jnp.where(ok[None], gathered, fill).transpose(1, 0, 2)


def _unshear(diags, U: int):
    """(D, B, T) diagonals -> (B, T, U): ``out[b, t, u] = diags[t+u, b, t]``."""
    D, B, T = diags.shape
    t = jnp.arange(T)[:, None]
    u = jnp.arange(U)[None, :]
    return diags.transpose(1, 2, 0)[:, t, t + u]  # (B, T, U) via gather on d


def _wavefront(V, H, init):
    """Run the lattice recurrence

        a[t, u] = logaddexp(a[t-1, u] + V[t, u],  a[t, u-1] + H[t, u])

    with ``a[0, 0] = init`` (per batch), V/H of shape (B, T, U) already
    encoding boundary -infs. Returns the full ``a`` (B, T, U).

    One ``lax.scan`` over the T+U-1 anti-diagonals; each step is two
    shifted adds + a logaddexp over a (B, T) slab — wavefront parallelism,
    the XLA analog of the reference's per-diagonal CUDA grid sync.
    """
    B, T, U = V.shape
    Vs = _shear(V, _NEG_INF)  # (D, B, T)
    Hs = _shear(H, _NEG_INF)

    diag0 = jnp.full((B, T), _NEG_INF).at[:, 0].set(init)

    def step(prev, vh):
        v_d, h_d = vh
        from_top = jnp.concatenate(
            [jnp.full((B, 1), _NEG_INF), prev[:, :-1]], axis=1
        ) + v_d  # a[t-1, u] + V[t, u]
        from_left = prev + h_d  # a[t, u-1] + H[t, u]
        new = jnp.logaddexp(from_top, from_left)
        return new, new

    _, diags = jax.lax.scan(step, diag0, (Vs[1:], Hs[1:]))
    diags = jnp.concatenate([diag0[None], diags], axis=0)  # (D, B, T)
    return _unshear(diags, U)


def _lattice_terms(x_log, label, blank_idx):
    """blank[b,t,u] = x_log[...,blank]; emit[b,t,u] = x_log[b,t,u,label[b,u]]
    (emit at u = U-1 is never a valid transition; filled with -inf)."""
    B, T, U, V = x_log.shape
    blank = x_log[..., blank_idx]
    lbl = jnp.concatenate([label[:, : U - 1], jnp.zeros((B, 1), label.dtype)], axis=1)
    emit = jnp.take_along_axis(
        x_log, jnp.broadcast_to(lbl[:, None, :, None], (B, T, U, 1)), axis=-1
    )[..., 0]
    emit = emit.at[:, :, U - 1].set(_NEG_INF)
    return blank, emit


def _alpha_beta(x_log, label, f_len, y_len, blank_idx, need_alpha=True):
    """Both DPs (reference forward_alpha/forward_beta in
    contrib/test/transducer/transducer_ref.py are the spec; the CUDA
    kernels in contrib/csrc/transducer compute the same lattice).
    ``need_alpha=False`` skips the alpha scan (the primal only needs beta;
    under jit XLA would DCE it anyway, but eager callers shouldn't pay)."""
    B, T, U, V = x_log.shape
    blank, emit = _lattice_terms(x_log, label, blank_idx)
    t_ax = jnp.arange(T)[None, :, None]
    u_ax = jnp.arange(U)[None, None, :]

    # ----- alpha: transitions INTO (t,u) read the source cell -----
    # vertical (t-1,u)->(t,u) weight blank[t-1,u]; horizontal emit[t,u-1]
    alpha = None
    if need_alpha:
        Va = jnp.concatenate([jnp.full((B, 1, U), _NEG_INF), blank[:, :-1]], axis=1)
        Ha = jnp.concatenate([jnp.full((B, T, 1), _NEG_INF), emit[:, :, :-1]], axis=2)
        alpha = _wavefront(Va, Ha, jnp.zeros((B,)))

    # ----- beta: reverse per-batch around (f_len-1, y_len) -----
    # beta'[t',u'] = beta[f_len-1-t', y_len-u'] turns the backward DP into
    # the same forward wavefront with dest-cell weights.
    rt = jnp.clip(f_len[:, None, None] - 1 - t_ax, 0, T - 1)  # (B,T,1)
    ru = jnp.clip(y_len[:, None, None] - u_ax, 0, U - 1)  # (B,1,U)
    gather = lambda m: m[jnp.arange(B)[:, None, None], rt, ru]
    blank_r, emit_r = gather(blank), gather(emit)
    in_lat = (t_ax < f_len[:, None, None]) & (u_ax <= y_len[:, None, None])
    Vb = jnp.where(in_lat, blank_r, _NEG_INF)
    Hb = jnp.where(in_lat, emit_r, _NEG_INF)
    # beta'[0,0] = blank[f_len-1, y_len]
    init_b = blank[jnp.arange(B), f_len - 1, y_len]
    beta_rev = _wavefront(Vb, Hb, init_b)
    # un-reverse: beta[t,u] = beta'[f_len-1-t, y_len-u] (invalid cells -> -inf)
    beta = gather(beta_rev)
    beta = jnp.where(in_lat, beta, _NEG_INF)
    return alpha, beta


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _loss_from_logits(x, label, f_len, y_len, blank_idx):
    y = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    _, beta = _alpha_beta(y, label, f_len, y_len, blank_idx, need_alpha=False)
    return -beta[:, 0, 0]


def _loss_fwd(x, label, f_len, y_len, blank_idx):
    y = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    alpha, beta = _alpha_beta(y, label, f_len, y_len, blank_idx)
    # save x (input precision), not the fp32 log-softmax: for bf16 logits —
    # the dominant (B,T,U,V) activation — that halves residual memory; the
    # backward recomputes the softmax (one cheap VPU pass)
    return -beta[:, 0, 0], (x, alpha, beta, label, f_len, y_len)


def _loss_bwd(blank_idx, res, loss_grad):
    """Analytic gradient fused with the softmax backward (reference
    fuse_softmax_backward path: transducer.py:133-141 + the
    transducer_loss_cuda.backward kernel; math per
    contrib/test/transducer/transducer_ref.py backward())."""
    x, alpha, beta, label, f_len, y_len = res
    in_dtype = x.dtype
    y = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    B, T, U, V = y.shape
    t_ax = jnp.arange(T)[None, :, None]
    u_ax = jnp.arange(U)[None, None, :]
    f = f_len[:, None, None]
    yl = y_len[:, None, None]
    common = alpha - beta[:, :1, :1]  # alpha[t,u] - beta[0,0]
    blank, emit = _lattice_terms(y, label, blank_idx)

    # d(-loss)/d(y) per lattice cell, before the softmax-backward correction
    # emit arcs: (t, u) -> (t, u+1) for u < y_len, t < f_len
    g_emit = -jnp.exp(
        common
        + jnp.concatenate([beta[:, :, 1:], jnp.full((B, T, 1), _NEG_INF)], axis=2)
        + emit
    )
    g_emit = jnp.where((u_ax < yl) & (t_ax < f), g_emit, 0.0)
    # blank arcs: (t, u) -> (t+1, u) for t < f_len-1, u <= y_len
    g_blank = -jnp.exp(
        common
        + jnp.concatenate([beta[:, 1:], jnp.full((B, 1, U), _NEG_INF)], axis=1)
        + blank
    )
    g_blank = jnp.where((t_ax < f - 1) & (u_ax <= yl), g_blank, 0.0)
    # terminal blank at (f_len-1, y_len)
    term = -jnp.exp(common + blank)
    g_blank = jnp.where((t_ax == f - 1) & (u_ax == yl), term, g_blank)

    lbl = jnp.concatenate([label[:, : U - 1], jnp.zeros((B, 1), label.dtype)], axis=1)
    g_y = jnp.zeros((B, T, U, V), jnp.float32)
    g_y = g_y.at[..., blank_idx].add(g_blank)
    g_y = g_y + g_emit[..., None] * jax.nn.one_hot(lbl, V, dtype=jnp.float32)[:, None]

    # fused log-softmax backward: dL/dx = g_y - exp(y) * sum_v g_y
    g_x = g_y - jnp.exp(y) * jnp.sum(g_y, axis=-1, keepdims=True)
    g_x = g_x * loss_grad[:, None, None, None]
    return (g_x.astype(in_dtype), None, None, None)


_loss_from_logits.defvjp(_loss_fwd, _loss_bwd)


def transducer_loss(
    x,
    label,
    f_len,
    y_len,
    blank_idx: int,
    *,
    packed_input: bool = False,
    batch_offset=None,
    max_f_len: Optional[int] = None,
    g_len=None,
):
    """Per-sequence RNN-T loss (B,) = -log P(label | x).

    x: (B, T, U, V) joint logits (U = max y_len + 1), or packed (N, V) when
    ``packed_input`` (then ``batch_offset = cumsum(f_len*(y_len+1))``,
    ``max_f_len`` static, matching reference transducer.py:96-129).
    """
    if packed_input:
        if batch_offset is None or max_f_len is None:
            raise ValueError("packed_input requires batch_offset and max_f_len")
        B = label.shape[0]
        U = label.shape[1] + 1
        gl = y_len + 1 if g_len is None else g_len
        x = _unpack(x, f_len, gl, batch_offset, B, max_f_len, U)
    blank_idx = int(blank_idx)
    return _loss_from_logits(x, label, f_len, y_len, blank_idx)


class TransducerLoss:
    """Module-style wrapper (reference transducer.py:70-129).
    ``fuse_softmax_backward`` / ``opt`` accepted for parity; the fused path
    is the only path here."""

    def __init__(self, fuse_softmax_backward=True, opt=1, packed_input=False):
        del fuse_softmax_backward, opt
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx, batch_offset=None,
                 max_f_len=None, debug_list=None):
        if debug_list is not None:
            y = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
            debug_list += list(_alpha_beta(y, label, f_len, y_len, int(blank_idx)))
        return transducer_loss(
            x, label, f_len, y_len, blank_idx,
            packed_input=self.packed_input, batch_offset=batch_offset,
            max_f_len=max_f_len,
        )
