"""Keyed tensor-dict broadcast across the TP axis.

TPU-native re-design of ``apex.transformer.tensor_parallel.data``
(reference data.py:77-113): the reference broadcasts sizes then a flattened
payload from TP-rank-0 so every rank in a TP group trains on identical data.

Under SPMD the inputs arrive already replicated across the tensor axis (the
data pipeline shards over "data" only), so broadcast_data reduces to an
*enforcement*: every rank adopts tp-rank-0's values via masked psum — the
same mechanism as :func:`apex_tpu.parallel.broadcast_params`.  dtype checks
mirror _check_data_types (reference data.py:17-27).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


def broadcast_data(keys: Sequence[str], data: Dict[str, jnp.ndarray], datatype,
                   axis_name: str = TENSOR_AXIS) -> Dict[str, jnp.ndarray]:
    """Return ``{key: tp-rank-0's value}`` for each key (reference data.py:77).

    Must run inside a region binding ``axis_name``.
    """
    out = {}
    rank = jax.lax.axis_index(axis_name)
    for k in keys:
        v = data[k]
        if v.dtype != datatype:
            raise ValueError(
                f"{k} has data type {v.dtype} which is different than {datatype}")
        # integer payloads ride the same masked-psum path in their own dtype
        masked = jnp.where(rank == 0, v, jnp.zeros_like(v))
        out[k] = jax.lax.psum(masked, axis_name)
    return out
