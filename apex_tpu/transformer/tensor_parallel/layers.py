"""Tensor-parallel layers: Column/Row-parallel linear, vocab-parallel embedding.

TPU-native re-design of ``apex.transformer.tensor_parallel.layers``
(reference layers.py:127-477).

Each layer is a functional module (init/apply) whose parameters are the
*local shard* for the device's TP rank — matching the reference's
per-rank ``Parameter`` shapes so checkpoints line up:

* ``ColumnParallelLinear`` (:243-362): weight [out/tp, in] per rank; input is
  copied to the TP region (backward all-reduce), output optionally gathered.
* ``RowParallelLinear`` (:365-477): weight [out, in/tp]; input optionally
  scattered; local GEMM then forward all-reduce; bias added *after* the
  reduce on every rank.
* ``VocabParallelEmbedding`` (:127-203): vocab dim sharded; out-of-shard
  tokens masked to 0 and the gathered embeddings all-reduced.

Init uses the reference's master-weight-then-shard scheme
(``_initialize_affine_weight_cpu`` :78-124): materialise the full weight
from one seed, slice this rank's shard — so results are independent of tp
size, which the parity tests rely on (run_layers_test.py master-weight
equivalence).

``apply`` must run inside a region binding the "tensor" axis (shard_map
over the mesh).  Parameter *init* is host-side: call ``init_shard`` with an
explicit rank to build each shard (or ``init_master`` + ``shard_master``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
)


def _master_init(key, shape, dtype, init_method):
    if init_method is None:
        # reference default: xavier-style normal (init.xavier_normal_)
        fan_in, fan_out = shape[-1], shape[0]
        std = (2.0 / (fan_in + fan_out)) ** 0.5
        return (jax.random.normal(key, shape) * std).astype(dtype)
    return init_method(key, shape).astype(dtype)


class ColumnParallelLinear:
    """Y = XA + b with A sharded along its output (column) dimension
    (reference layers.py:243).  ``gather_output=True`` returns the full Y on
    every rank; ``False`` leaves Y sharded for a following RowParallel layer.
    """

    def __init__(self, input_size: int, output_size: int, *, bias: bool = True,
                 gather_output: bool = True, init_method=None,
                 stride: int = 1, keep_master_weight_for_test: bool = False,
                 skip_bias_add: bool = False,
                 tp_size: Optional[int] = None, axis_name: str = TENSOR_AXIS):
        from apex_tpu.transformer import parallel_state
        self.input_size = input_size
        self.output_size = output_size
        self.use_bias = bias
        self.gather_output = gather_output
        self.skip_bias_add = skip_bias_add
        self.init_method = init_method
        self.axis_name = axis_name
        self.tp = (tp_size if tp_size is not None
                   else parallel_state.get_tensor_model_parallel_world_size())
        if output_size % self.tp != 0:
            raise ValueError("output_size must be divisible by tp size")
        self.output_size_per_partition = output_size // self.tp

    def init_master(self, key, dtype=jnp.float32):
        w = _master_init(key, (self.output_size, self.input_size), dtype,
                         self.init_method)
        p = {"weight": w}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.output_size,), dtype)
        return p

    def shard_master(self, master, rank: int):
        o = self.output_size_per_partition
        p = {"weight": master["weight"][rank * o:(rank + 1) * o]}
        if self.use_bias:
            p["bias"] = master["bias"][rank * o:(rank + 1) * o]
        return p

    def init_shard(self, key, rank: int, dtype=jnp.float32):
        return self.shard_master(self.init_master(key, dtype), rank)

    def apply(self, params, x):
        x = copy_to_tensor_model_parallel_region(x, self.axis_name)
        y = jax.lax.dot_general(
            x, params["weight"], (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        bias = params.get("bias")
        if bias is not None and not self.skip_bias_add:
            y = y + bias.astype(jnp.float32)
        y = y.astype(x.dtype)
        if self.gather_output:
            y = gather_from_tensor_model_parallel_region(y, self.axis_name)
        if self.skip_bias_add:
            # reference returns (output, bias) for downstream fused add
            return y, bias
        return y

    __call__ = apply


class RowParallelLinear:
    """Y = XA + b with A sharded along its input (row) dimension
    (reference layers.py:365).  ``input_is_parallel=True`` means X is already
    sharded (the output of a ColumnParallel layer with gather_output=False).
    """

    def __init__(self, input_size: int, output_size: int, *, bias: bool = True,
                 input_is_parallel: bool = False, init_method=None,
                 stride: int = 1, keep_master_weight_for_test: bool = False,
                 skip_bias_add: bool = False,
                 tp_size: Optional[int] = None, axis_name: str = TENSOR_AXIS):
        from apex_tpu.transformer import parallel_state
        self.input_size = input_size
        self.output_size = output_size
        self.use_bias = bias
        self.input_is_parallel = input_is_parallel
        self.skip_bias_add = skip_bias_add
        self.init_method = init_method
        self.axis_name = axis_name
        self.tp = (tp_size if tp_size is not None
                   else parallel_state.get_tensor_model_parallel_world_size())
        if input_size % self.tp != 0:
            raise ValueError("input_size must be divisible by tp size")
        self.input_size_per_partition = input_size // self.tp

    def init_master(self, key, dtype=jnp.float32):
        w = _master_init(key, (self.output_size, self.input_size), dtype,
                         self.init_method)
        p = {"weight": w}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.output_size,), dtype)
        return p

    def shard_master(self, master, rank: int):
        i = self.input_size_per_partition
        p = {"weight": master["weight"][:, rank * i:(rank + 1) * i]}
        if self.use_bias:
            p["bias"] = master["bias"]  # bias is replicated (applied post-reduce)
        return p

    def init_shard(self, key, rank: int, dtype=jnp.float32):
        return self.shard_master(self.init_master(key, dtype), rank)

    def apply(self, params, x):
        if not self.input_is_parallel:
            x = scatter_to_tensor_model_parallel_region(x, self.axis_name)
        y = jax.lax.dot_general(
            x, params["weight"], (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        y = reduce_from_tensor_model_parallel_region(y, self.axis_name)
        bias = params.get("bias")
        if self.skip_bias_add:
            return y, bias
        if bias is not None:
            y = (y.astype(jnp.float32) + bias.astype(jnp.float32)).astype(y.dtype)
        return y

    __call__ = apply


class VocabParallelEmbedding:
    """Embedding table sharded along the vocab dimension
    (reference layers.py:127-203): tokens outside this rank's range produce
    zeros; the per-rank partial lookups are summed with one all-reduce.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int, *,
                 init_method=None, tp_size: Optional[int] = None,
                 axis_name: str = TENSOR_AXIS):
        from apex_tpu.transformer import parallel_state
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.init_method = init_method
        self.axis_name = axis_name
        self.tp = (tp_size if tp_size is not None
                   else parallel_state.get_tensor_model_parallel_world_size())
        if num_embeddings % self.tp != 0:
            raise ValueError("num_embeddings must be divisible by tp size")
        self.num_embeddings_per_partition = num_embeddings // self.tp

    def init_master(self, key, dtype=jnp.float32):
        if self.init_method is None:
            w = jax.random.normal(
                key, (self.num_embeddings, self.embedding_dim)).astype(dtype)
        else:
            w = self.init_method(
                key, (self.num_embeddings, self.embedding_dim)).astype(dtype)
        return {"weight": w}

    def shard_master(self, master, rank: int):
        n = self.num_embeddings_per_partition
        return {"weight": master["weight"][rank * n:(rank + 1) * n]}

    def init_shard(self, key, rank: int, dtype=jnp.float32):
        return self.shard_master(self.init_master(key, dtype), rank)

    def apply(self, params, token_ids):
        n = self.num_embeddings_per_partition
        rank = jax.lax.axis_index(self.axis_name)
        start = rank * n
        # mask + clamp local ids (reference layers.py:168-177)
        local = token_ids - start
        in_range = (local >= 0) & (local < n)
        local = jnp.clip(local, 0, n - 1)
        emb = jnp.take(params["weight"], local, axis=0)
        emb = jnp.where(in_range[..., None], emb, 0)
        return reduce_from_tensor_model_parallel_region(emb, self.axis_name)

    __call__ = apply


# Parameter TP metadata (reference layers.py:37-75) — in JAX sharding is
# carried by the arrays themselves / the mesh spec, but the attribute API is
# kept for porting convenience.

def set_tensor_model_parallel_attributes(param_meta: dict, is_parallel: bool,
                                         dim: int, stride: int = 1) -> dict:
    param_meta.update(tensor_model_parallel=is_parallel,
                      partition_dim=dim, partition_stride=stride)
    return param_meta


def param_is_not_tensor_parallel_duplicate(param_meta: dict) -> bool:
    """Reference layers.py:44-47: a param is "not a duplicate" if it is TP
    (every shard unique) OR we are tp-rank 0 (the canonical copy of a
    replicated param)."""
    from apex_tpu.transformer import parallel_state

    if param_meta.get("tensor_model_parallel", False):
        return True
    rank = parallel_state.get_tensor_model_parallel_rank()
    return bool(rank == 0) if isinstance(rank, int) else (rank == 0)
