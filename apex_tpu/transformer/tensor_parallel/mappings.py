"""The four tensor-parallel region primitives.

TPU-native re-design of ``apex.transformer.tensor_parallel.mappings``
(reference mappings.py:77-159).

The reference implements each mapping as a ``torch.autograd.Function`` pair
because torch's autograd cannot transpose process-group collectives — the
backward all-reduce of ``copy_to`` (:77-91) and friends must be written by
hand.  JAX *can* transpose collectives: inside ``shard_map``,
``psum``/``all_gather``/``dynamic_slice`` each have the correct adjoint
(psum ↔ cotangent-psum, all_gather ↔ reduce-scatter, slice ↔ masked
scatter-add), so the mappings here are plain forward functions and autodiff
derives exactly the backward table of the reference:

=============================  ============  =======================
 primitive                      forward       derived backward
=============================  ============  =======================
 copy_to_...    (ref :77)       identity      psum (via the producing
                                              collective's transpose)
 reduce_from_...(ref :93)       psum          identity
 scatter_to_... (ref :109)      split last    all-gather
 gather_from_...(ref :125)      all-gather    split last
=============================  ============  =======================

Writing custom VJPs for these (as a torch port would) *breaks* gradients
under ``shard_map``, which scales cotangents at region boundaries assuming
true adjoints — a worked example lives in tests/L0/test_tensor_parallel.py.

Splits are along the last dimension in equal chunks per TP rank
(reference utils.split_tensor_along_last_dim).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


def _split_last_dim(x, axis_name):
    """This rank's chunk of the last dim (reference mappings.py:29-41)."""
    world = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[-1] // world
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=x.ndim - 1)


def copy_to_tensor_model_parallel_region(x, axis_name: str = TENSOR_AXIS):
    """Identity forward; the conjugate all-reduce appears in the backward of
    whatever collective produced the replicated ``x`` (reference :77-91)."""
    del axis_name
    return x


def reduce_from_tensor_model_parallel_region(x, axis_name: str = TENSOR_AXIS):
    """All-reduce forward, identity backward (reference :93-107)."""
    return jax.lax.psum(x, axis_name)


def scatter_to_tensor_model_parallel_region(x, axis_name: str = TENSOR_AXIS):
    """Split the last dim, keep own chunk; backward all-gathers
    (reference :109-123)."""
    return _split_last_dim(x, axis_name)


def gather_from_tensor_model_parallel_region(x, axis_name: str = TENSOR_AXIS):
    """All-gather along the last dim; backward splits (reference :125-139)."""
    return jax.lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)
