"""Vocab-parallel cross entropy.

TPU-native re-design of ``apex.transformer.tensor_parallel.cross_entropy``
(reference cross_entropy.py:23-103): numerically-stable CE over logits whose
vocab (last) dimension is sharded across the TP axis.

Collective structure matches the reference exactly:

1. all-reduce MAX of per-rank logit maxima (:29-33),
2. masked gather of the target logit on the owning rank, all-reduce SUM
   (:35-57),
3. all-reduce SUM of the local exp-sums (:59-63),
4. loss = log(sum_exp) − target_logit.

The reference hand-writes the backward (softmax minus one-hot, :76-103);
here the forward is built from differentiable psums and JAX derives the
same gradient (psum's transpose is identity; the masked gather transposes
to the masked scatter the reference implements by hand).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


def vocab_parallel_cross_entropy(vocab_parallel_logits: jnp.ndarray,
                                 target: jnp.ndarray,
                                 axis_name: str = TENSOR_AXIS) -> jnp.ndarray:
    """Per-token loss. ``vocab_parallel_logits`` [..., vocab/tp] (this rank's
    shard), ``target`` int [...] with *global* vocab ids."""
    n_local = vocab_parallel_logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    start = rank * n_local

    z = vocab_parallel_logits.astype(jnp.float32)
    # 1. global max for stability (non-differentiable path, like the
    # reference's detached logits_max)
    zmax = jax.lax.pmax(
        jnp.max(jax.lax.stop_gradient(z), axis=-1), axis_name)
    z = z - zmax[..., None]

    # 2. target logit: owned by exactly one rank, psum broadcasts it
    local_t = target - start
    in_range = (local_t >= 0) & (local_t < n_local)
    local_t = jnp.clip(local_t, 0, n_local - 1)
    t_logit = jnp.take_along_axis(z, local_t[..., None], axis=-1)[..., 0]
    t_logit = jax.lax.psum(jnp.where(in_range, t_logit, 0.0), axis_name)

    # 3. global sum of exp
    sum_exp = jax.lax.psum(jnp.sum(jnp.exp(z), axis=-1), axis_name)

    # 4.
    return jnp.log(sum_exp) - t_logit
