"""apex_tpu.transformer.tensor_parallel — Megatron TP over the mesh "tensor"
axis (reference apex/transformer/tensor_parallel/__init__.py:18-74)."""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    param_is_not_tensor_parallel_duplicate,
    set_tensor_model_parallel_attributes,
)
from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RngStatesTracker,
    checkpoint,
    gather_split_1d_tensor,
    get_cuda_rng_tracker,
    get_rng_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_seed,
    split_tensor_into_1d_equal_chunks,
)
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    VocabUtility,
    divide,
    ensure_divisibility,
    split_tensor_along_last_dim,
)
