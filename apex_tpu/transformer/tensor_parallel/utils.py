"""Tensor-parallel helpers (reference apex/transformer/tensor_parallel/utils.py
and apex/transformer/utils.py)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    """Reference utils.py:9-11."""
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Reference utils.py:14-17."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x: jnp.ndarray, num_partitions: int
                                ) -> Tuple[jnp.ndarray, ...]:
    """Reference tensor_parallel/utils.py split helper: equal chunks of the
    last dimension."""
    last = x.shape[-1]
    chunk = divide(last, num_partitions)
    return tuple(x[..., i * chunk:(i + 1) * chunk] for i in range(num_partitions))


class VocabUtility:
    """Reference layers.py vocab range helpers (used by the embedding and CE)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(per_partition_vocab_size: int,
                                                  rank, world_size: int):
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank,
                                           world_size: int):
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size)
