"""TP-aware RNG and activation checkpointing.

TPU-native re-design of ``apex.transformer.tensor_parallel.random``
(reference random.py).

The reference maintains a ``CudaRNGStatesTracker`` (:113-190) of named CUDA
RNG states so dropout can be *identical* across TP ranks for replicated
activations and *different* for sharded ones, seeded by
``model_parallel_cuda_manual_seed`` (:193-221): data-parallel seed = seed,
tensor-parallel seed = seed + 2718 + tp_rank.  JAX RNG is functional, so
"states" become named base keys and forking is ``jax.random.fold_in`` —
no mutation, no state capture/restore.

Activation checkpointing (``CheckpointFunction`` :224-308) — recompute in
backward with RNG replay — is ``jax.checkpoint``: recompute is what it does,
and RNG replay is free because keys are values.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


class RngStatesTracker:
    """Named RNG keys (reference CudaRNGStatesTracker random.py:113).

    ``add(name, seed)`` registers a stream; ``fork(name)`` returns a fresh
    key for this trace step (callers thread a step/counter via ``fold_in``).
    """

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"seed {name} already exists")
        key = jax.random.PRNGKey(seed)
        for existing in self.states_.values():
            if bool(jnp.all(existing == key)):
                raise Exception(f"seed {seed} already exists")
        self.states_[name] = key

    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME,
             counter: int = 0) -> jax.Array:
        """Return the named key folded with ``counter``.  Unlike the
        reference's context manager (which mutates global CUDA state), the
        caller passes the returned key into its random op."""
        if name not in self.states_:
            raise Exception(f"seed {name} is not added")
        return jax.random.fold_in(self.states_[name], counter)


_RNG_STATE_TRACKER = RngStatesTracker()


def get_cuda_rng_tracker() -> RngStatesTracker:
    """Name kept for porting convenience (reference random.py:188)."""
    return _RNG_STATE_TRACKER


get_rng_tracker = get_cuda_rng_tracker


def model_parallel_cuda_manual_seed(seed: int, tp_rank=None) -> None:
    """Seed both streams (reference random.py:193-221):
    default stream = ``seed`` (same across TP for data parallelism),
    model-parallel stream = ``seed + 2718 + tp_rank`` (different per rank).

    ``tp_rank`` may be a traced ``axis_index`` — fold_in handles tracers, so
    this works inside shard_map; host-side it defaults to 0.
    """
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.states_["default"] = jax.random.PRNGKey(seed)
    tp_key = jax.random.PRNGKey(seed + 2718)
    if tp_rank is None:
        try:
            tp_rank = jax.lax.axis_index(TENSOR_AXIS)
        except NameError:
            tp_rank = 0
    _RNG_STATE_TRACKER.states_[_MODEL_PARALLEL_RNG_TRACKER_NAME] = (
        jax.random.fold_in(tp_key, tp_rank))


model_parallel_seed = model_parallel_cuda_manual_seed


def model_parallel_dropout_key(key: jax.Array,
                               axis_name: str = TENSOR_AXIS) -> jax.Array:
    """Per-TP-rank dropout key from a replicated base key — the
    ``get_cuda_rng_tracker().fork()`` discipline (reference random.py:
    193-221: model-parallel seed = seed + 2718 + tp_rank): activations
    *sharded* over TP (attention probs, 4h MLP activations) must drop
    different elements per rank.  Outside any ``axis_name`` binding the
    rank folds in as 0 (single-rank)."""
    key = jax.random.fold_in(key, 2718)
    try:
        rank = jax.lax.axis_index(axis_name)
    except Exception as e:  # unbound axis — tolerate the exception TYPE
        # changing across jax versions (today NameError), but only for
        # errors that actually say the axis is unbound: silently folding
        # rank 0 on every rank would drop identical elements on
        # TP-sharded activations, the exact bug this discipline prevents
        # (guarded by the TP mask property test)
        unbound = "unbound axis" in str(e).lower()
        if not unbound and not isinstance(e, NameError):
            raise  # unrelated failure: do not mask it as "unbound"
        rank = 0
    return jax.random.fold_in(key, rank)


def dropout(x: jnp.ndarray, rate: float, key: jax.Array) -> jnp.ndarray:
    """Inverted dropout (train-mode): zero with prob ``rate``, scale kept
    elements by 1/(1-rate).  Callers choose the key stream: the *base*
    (replicated) key for TP-replicated activations, or
    :func:`model_parallel_dropout_key` for TP-sharded ones — that split is
    the whole point of the reference's RNG tracker."""
    if rate <= 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def checkpoint(function, *args, policy=None):
    """Activation checkpointing (reference CheckpointFunction random.py:224 +
    ``checkpoint`` :291): recompute ``function`` in the backward pass.

    ``policy`` is a ``jax.checkpoint_policies`` entry for selective
    rematerialisation — strictly more control than the reference's
    all-or-nothing recompute."""
    return jax.checkpoint(function, policy=policy)(*args)


def split_tensor_into_1d_equal_chunks(x: jnp.ndarray,
                                      axis_name: str = TENSOR_AXIS):
    """Shard a flattened activation across TP ranks
    (reference random.py:247-266 — the distributed hidden-state buffer of
    memory-efficient checkpointing, precursor of sequence parallelism)."""
    flat = x.reshape(-1)
    world = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = flat.shape[0] // world
    return jax.lax.dynamic_slice_in_dim(flat, rank * chunk, chunk)


def gather_split_1d_tensor(x: jnp.ndarray, axis_name: str = TENSOR_AXIS):
    """Inverse of :func:`split_tensor_into_1d_equal_chunks`
    (reference utils.py:34-46)."""
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)
