"""apex_tpu.transformer — Megatron-style model parallelism over the mesh.

TPU-native re-design of ``apex.transformer`` (SURVEY.md §2.7): the
TP × PP × DP decomposition is one ``jax.sharding.Mesh`` with axes
("data", "pipeline", "tensor"); tensor-parallel layers are plain-collective
functions whose backwards are derived by JAX AD; pipeline schedules are
compiled ``ppermute`` loops.
"""

from apex_tpu.transformer import amp  # noqa: F401
from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer.enums import (  # noqa: F401
    AttnMaskType,
    AttnType,
    LayerType,
)
from apex_tpu.transformer.log_util import (  # noqa: F401
    get_transformer_logger,
    set_logging_level,
)
