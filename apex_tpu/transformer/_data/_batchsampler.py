"""DP-sharded pretraining batch samplers.

TPU-native port of ``apex.transformer._data._batchsampler``
(reference _batchsampler.py:38-180): iterate global-batch index lists,
yielding each data-parallel rank's contiguous (or shuffled) slice of a
``local_minibatch_size = global_batch_size / data_parallel_size`` batch.
Pure index arithmetic — identical semantics, no torch Sampler base.
"""

from __future__ import annotations

import random
from typing import Iterator, List


class MegatronPretrainingSampler:
    """Contiguous per-rank slices of each global batch
    (reference _batchsampler.py:38-99)."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, drop_last: bool = True):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.drop_last = drop_last
        if total_samples <= 0:
            raise ValueError(f"no sample to consume: {total_samples}")
        if consumed_samples >= total_samples:
            raise ValueError(
                f"no samples left to consume: {consumed_samples}, {total_samples}")
        if local_minibatch_size <= 0:
            raise ValueError(f"local minibatch size must be greater than 0: "
                             f"{local_minibatch_size}")
        if data_parallel_size <= 0:
            raise ValueError("data parallel size must be greater than 0")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                f"data_parallel_rank should be smaller than data size: "
                f"{data_parallel_rank}, {data_parallel_size}")

    def __len__(self) -> int:
        return self.total_samples

    def get_start_end_idx(self):
        start_idx = self.data_parallel_rank * self.local_minibatch_size
        end_idx = start_idx + self.local_minibatch_size
        return start_idx, end_idx

    def __iter__(self) -> Iterator[List[int]]:
        batch: List[int] = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.local_minibatch_times_data_parallel_size:
                start_idx, end_idx = self.get_start_end_idx()
                yield batch[start_idx:end_idx]
                batch = []
        if len(batch) > 0 and not self.drop_last:
            start_idx, end_idx = self.get_start_end_idx()
            yield batch[start_idx:end_idx]


class MegatronPretrainingRandomSampler:
    """Shuffled within-epoch buckets, deterministic by epoch seed
    (reference _batchsampler.py:102-180)."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.last_batch_size = (
            self.total_samples % self.local_minibatch_times_data_parallel_size)
        if total_samples <= 0:
            raise ValueError(f"no sample to consume: {total_samples}")
        if local_minibatch_size <= 0:
            raise ValueError(f"local minibatch size must be greater than 0: "
                             f"{local_minibatch_size}")
        if data_parallel_size <= 0:
            raise ValueError("data parallel size must be greater than 0")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                f"data_parallel_rank should be smaller than data size: "
                f"{data_parallel_rank}, {data_parallel_size}")

    def __len__(self) -> int:
        return self.total_samples

    def __iter__(self) -> Iterator[List[int]]:
        active_total_samples = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples
        if (current_epoch_samples
                % self.local_minibatch_times_data_parallel_size != 0):
            raise RuntimeError("consumed samples must align to a global batch")

        # data sharding and random sampling
        bucket_size = ((self.total_samples
                        // self.local_minibatch_times_data_parallel_size)
                       * self.local_minibatch_size)
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        rng = random.Random(self.epoch)
        random_idx = list(range(bucket_size))
        rng.shuffle(random_idx)
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch: List[int] = []
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += self.local_minibatch_times_data_parallel_size
                yield batch
                batch = []
