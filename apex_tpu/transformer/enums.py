"""Transformer enums — mirror of apex/transformer/enums.py.

``AttnMaskType`` is defined once in :mod:`apex_tpu.ops.fused_softmax` (the
consumer) and re-exported here so the two import paths compare equal.
"""

import enum

from apex_tpu.ops.fused_softmax import AttnMaskType  # noqa: F401


class LayerType(enum.Enum):
    encoder = 1
    decoder = 2


class AttnType(enum.Enum):
    self_attn = 1
    cross_attn = 2
