"""apex_tpu.transformer.amp — model-parallel-aware grad scaling
(reference apex/transformer/amp/grad_scaler.py)."""

from apex_tpu.transformer.amp.grad_scaler import GradScaler  # noqa: F401
