"""Model-parallel-aware gradient scaler.

TPU-native re-design of ``apex.transformer.amp.GradScaler``
(reference amp/grad_scaler.py:8-106): a ``torch.cuda.amp.GradScaler``
subclass whose only change is all-reducing ``found_inf`` across the
model-parallel group in ``step`` (:25-36) and ``update`` (:88-98), so a TP/PP
shard that overflows makes *every* rank skip the step.

Here the scaler composes :class:`apex_tpu.amp.LossScaler` (the pure
loss-scale state machine) with a finite-check that psums across the
model-parallel axes — one fused collective instead of a D2H poll.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, LossScaleState
from apex_tpu.transformer.parallel_state import PIPELINE_AXIS, TENSOR_AXIS


@dataclasses.dataclass(frozen=True)
class GradScaler(LossScaler):
    """LossScaler whose overflow verdict is agreed across the model-parallel
    block (reference grad_scaler.py:25-36, :88-98)."""

    model_parallel_axes: Sequence[str] = (PIPELINE_AXIS, TENSOR_AXIS)

    def found_inf(self, grads) -> jnp.ndarray:
        """True if any grad anywhere in the MP block is non-finite.  Reduces
        over whichever of the model-parallel axes are bound in the current
        region (TP-only regions still agree across "tensor"); purely local
        outside any."""
        from apex_tpu.utils.tree import tree_isfinite

        verdict = jnp.logical_not(tree_isfinite(grads)).astype(jnp.int32)
        for axis in self.model_parallel_axes:
            try:
                verdict = jax.lax.pmax(verdict, axis)
            except NameError:
                continue  # axis not bound here
        return verdict.astype(bool)


def all_finite(tree) -> jnp.ndarray:
    """Alias of :func:`apex_tpu.utils.tree.tree_isfinite` (one fused
    all-finite reduction, floating leaves only)."""
    from apex_tpu.utils.tree import tree_isfinite

    return tree_isfinite(tree)
