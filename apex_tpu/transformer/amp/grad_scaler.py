"""Model-parallel-aware gradient scaler.

TPU-native re-design of ``apex.transformer.amp.GradScaler``
(reference amp/grad_scaler.py:8-106): a ``torch.cuda.amp.GradScaler``
subclass whose only change is all-reducing ``found_inf`` across the
model-parallel group in ``step`` (:25-36) and ``update`` (:88-98), so a TP/PP
shard that overflows makes *every* rank skip the step.

Here the scaler composes :class:`apex_tpu.amp.LossScaler` (the pure
loss-scale state machine) with a finite-check that psums across the
model-parallel axes — one fused collective instead of a D2H poll.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, LossScaleState
from apex_tpu.transformer.parallel_state import PIPELINE_AXIS, TENSOR_AXIS


@dataclasses.dataclass(frozen=True)
class GradScaler(LossScaler):
    """LossScaler whose overflow verdict is agreed across the model-parallel
    block (reference grad_scaler.py:25-36, :88-98)."""

    model_parallel_axes: Sequence[str] = (PIPELINE_AXIS, TENSOR_AXIS)

    def found_inf(self, grads) -> jnp.ndarray:
        """True if any grad anywhere in the MP block is non-finite.  Must run
        inside a region binding the model-parallel axes; falls back to the
        local check outside one."""
        local = jnp.logical_not(all_finite(grads))
        try:
            # max over the MP block: any rank's overflow poisons all
            return jax.lax.pmax(local.astype(jnp.int32),
                                self.model_parallel_axes).astype(bool)
        except NameError:
            return local


def all_finite(tree) -> jnp.ndarray:
    """Single fused all-finite reduction over a pytree."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.array(True)
    finite = [jnp.all(jnp.isfinite(l)) for l in leaves]
    return jnp.stack(finite).all()
