"""Transformer logger with env-var level
(reference apex/transformer/log_util.py:1-19)."""

from __future__ import annotations

import logging
import os


def get_transformer_logger(name: str) -> logging.Logger:
    name_wo_ext = os.path.splitext(name)[0]
    return logging.getLogger(name_wo_ext)


def set_logging_level(verbosity) -> None:
    """Reference: APEX_TRANSFORMER_LOG_LEVEL env var override."""
    logging.getLogger("apex_tpu.transformer").setLevel(verbosity)


_level = os.environ.get("APEX_TPU_TRANSFORMER_LOG_LEVEL",
                        os.environ.get("APEX_TRANSFORMER_LOG_LEVEL"))
if _level is not None:
    set_logging_level(int(_level) if _level.isdigit() else _level)
