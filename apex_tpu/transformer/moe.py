"""Expert parallelism: a Switch-style Mixture-of-Experts MLP.

The 2021 reference predates MoE (no analog in apex; Megatron grew
SwitchMLP later), but expert parallelism is a first-class axis of the
modern parallelism surface (tp/pp/dp/sp/**ep**) and shapes the same
collective design the rest of :mod:`apex_tpu.transformer` builds on —
so it lives here as a TPU-first extension rather than a parity item.

Design (token-choice top-1, Switch Transformer):

- gate: ``logits = h @ wg`` → per-token expert id + gate weight;
- **static-shape dispatch**: each expert has a fixed capacity
  ``C = ceil(T · capacity_factor / E)``; tokens scatter into an
  ``[E, C, H]`` buffer by (expert, position-within-expert) with
  overflow dropped (they ride the residual), the standard
  compile-friendly formulation — no dynamic shapes anywhere;
- **all_to_all over the "expert" mesh axis** re-buckets the dispatch
  buffer so each rank holds ``E/world`` whole experts applied to every
  rank's tokens (one ICI all_to_all each way, the MoE communication
  pattern);
- per-expert FFN as one batched einsum over the local experts (MXU
  sees ``[E_local, world·C, H] × [E_local, H, F]``);
- combine: the returning buffer is gathered back per token and scaled
  by the gate weight.

Everything runs inside ``shard_map``; with ``axis_name=None`` the same
code is a single-device MoE (world=1), which is what the unit tests
exercise against a dense per-token reference.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["MoEConfig", "SwitchMLP"]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    capacity_factor: float = 1.25
    # auxiliary load-balancing loss coefficient (Switch eq. 4)
    aux_loss_coeff: float = 1e-2
    init_method_std: float = 0.02


class SwitchMLP:
    """Top-1 routed MLP.  ``num_experts`` must divide by the expert-axis
    world size; each rank owns ``num_experts / world`` experts."""

    def __init__(self, cfg: MoEConfig):
        self.cfg = cfg

    def init_master(self, key):
        cfg = self.cfg
        kg, k1, k2 = jax.random.split(key, 3)
        std = cfg.init_method_std
        return {
            "gate": {"weight": jax.random.normal(
                kg, (cfg.hidden_size, cfg.num_experts)) * std},
            "experts": {
                "w1": jax.random.normal(
                    k1, (cfg.num_experts, cfg.hidden_size,
                         cfg.ffn_hidden_size)) * std,
                "b1": jnp.zeros((cfg.num_experts, cfg.ffn_hidden_size)),
                "w2": jax.random.normal(
                    k2, (cfg.num_experts, cfg.ffn_hidden_size,
                         cfg.hidden_size)) * std,
                "b2": jnp.zeros((cfg.num_experts, cfg.hidden_size)),
            },
        }

    def shard_master(self, master, rank, world: int):
        """Slice this rank's experts (gate is replicated)."""
        e_local = self.cfg.num_experts // world
        sl = slice(rank * e_local, (rank + 1) * e_local)
        return {
            "gate": master["gate"],
            "experts": jax.tree_util.tree_map(
                lambda a: a[sl], master["experts"]),
        }

    def capacity(self, n_tokens: int) -> int:
        """Per-expert slot count for ``n_tokens`` LOCAL tokens (capacity
        is per dispatching rank; world size does not enter)."""
        return max(1, math.ceil(
            n_tokens * self.cfg.capacity_factor / self.cfg.num_experts))

    def apply(self, params, h, *, axis_name: Optional[str] = None):
        """h: [T, H] (this rank's tokens).  Returns ``(out, aux_loss)``.

        Inside ``shard_map`` with ``axis_name`` bound, experts are
        sharded over that axis and two ``all_to_all`` collectives move
        tokens to their experts and back.  ``aux_loss`` is the Switch
        load-balancing loss (already mean-normalized; add
        ``cfg.aux_loss_coeff * aux_loss`` to the model loss).
        """
        cfg = self.cfg
        T, H = h.shape
        E = cfg.num_experts
        world = 1 if axis_name is None else jax.lax.psum(1, axis_name)
        e_local = E // world
        C = self.capacity(T)

        logits = h.astype(jnp.float32) @ params["gate"]["weight"].astype(
            jnp.float32)                                   # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                # [T]
        gate_w = jnp.max(probs, axis=-1)                   # [T]

        # position of each token in its expert's queue; overflow drops
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)      # [T, E]
        pos = (jnp.cumsum(onehot, axis=0) - 1)                   # [T, E]
        pos = jnp.sum(pos * onehot, axis=-1)                     # [T]
        keep = pos < C

        # Switch aux loss: E * sum_e(frac_tokens_e * mean_prob_e).
        # Under expert parallelism the statistics are averaged over the
        # axis so every rank adds the SAME aux term — the gate weight is
        # replicated, and a rank-local term would give each replica a
        # different gradient and silently desync them after one step.
        frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
        mean_p = jnp.mean(probs, axis=0)
        if axis_name is not None and world > 1:
            frac = jax.lax.pmean(frac, axis_name)
            mean_p = jax.lax.pmean(mean_p, axis_name)
        aux_loss = E * jnp.sum(frac * mean_p)

        disp = jnp.zeros((E, C, H), h.dtype)
        disp = disp.at[expert, jnp.where(keep, pos, 0)].add(
            jnp.where(keep[:, None], h, 0), mode="drop")

        if axis_name is not None and world > 1:
            # [E, C, H] -> peers; receive [world, e_local, C, H]:
            # every rank's tokens for MY experts
            disp = jax.lax.all_to_all(
                disp.reshape(world, e_local, C, H), axis_name,
                split_axis=0, concat_axis=0, tiled=True)
        x = disp.reshape(world, e_local, C, H)
        x = jnp.moveaxis(x, 0, 1).reshape(e_local, world * C, H)

        ex = params["experts"]
        inter = jnp.einsum("ech,ehf->ecf", x.astype(jnp.float32),
                           ex["w1"].astype(jnp.float32)) + ex["b1"][:, None]
        inter = jax.nn.gelu(inter, approximate=True)
        out = jnp.einsum("ecf,efh->ech", inter,
                         ex["w2"].astype(jnp.float32)) + ex["b2"][:, None]
        out = out.astype(h.dtype)

        out = jnp.moveaxis(out.reshape(e_local, world, C, H), 1, 0)
        out = out.reshape(world * e_local, C, H)
        if axis_name is not None and world > 1:
            out = jax.lax.all_to_all(
                out.reshape(world, e_local, C, H), axis_name,
                split_axis=0, concat_axis=0, tiled=True).reshape(E, C, H)
        else:
            out = out.reshape(E, C, H)

        # combine: gather each token's expert output, gate-scale; dropped
        # tokens contribute zero (caller's residual carries them)
        tok_out = out[expert, jnp.where(keep, pos, 0)]
        tok_out = jnp.where(keep[:, None], tok_out, 0)
        return (tok_out * gate_w[:, None].astype(h.dtype)), aux_loss
