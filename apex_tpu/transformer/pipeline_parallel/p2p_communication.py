"""Pipeline-stage point-to-point transfer.

TPU-native re-design of
``apex.transformer.pipeline_parallel.p2p_communication``
(reference p2p_communication.py:31-404).

The reference wraps batched NCCL ``isend/irecv`` (``_run_p2pops`` :31-69)
in eight directional helpers, with a scatter-gather transport optimisation
(send 1/TP of the tensor, allgather after receive, :116-178).  On TPU a
stage transfer is one ``lax.ppermute`` over the mesh "pipeline" axis — a
static, compiler-scheduled ICI neighbor exchange; the scatter-gather trick
is unnecessary because GSPMD keeps sharded tensors sharded across the hop.

The eight reference wrappers are kept (same names, :183-404) so schedule
code reads identically; each is a thin view over :func:`send_recv_next` /
:func:`send_recv_prev`.  "Receiving nothing" yields zeros — callers mask by
stage, matching the schedules' fill/drain accounting.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS


def _perm(n: int, shift: int):
    return [(i, (i + shift) % n) for i in range(n)]


def send_recv_next(x: jnp.ndarray, axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    """Every stage sends ``x`` to stage+1 (ring); stage s receives stage
    s-1's tensor.  The wrap-around edge (last→first) carries fill garbage
    that schedules mask out."""
    n = jax.lax.psum(1, axis_name)
    return jax.lax.ppermute(x, axis_name, _perm(n, 1))


def send_recv_prev(x: jnp.ndarray, axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    """Every stage sends ``x`` to stage-1 (ring); used by the backward pass."""
    n = jax.lax.psum(1, axis_name)
    return jax.lax.ppermute(x, axis_name, _perm(n, -1))


# --- reference-named wrappers (p2p_communication.py:183-404) ----------------


def recv_forward(input_tensor: jnp.ndarray,
                 axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    """Receive the activation from the previous stage.  In the compiled
    schedule the 'receive' is the permuted value of what the previous stage
    just produced — so this takes the stage *output* grid and rotates it."""
    return send_recv_next(input_tensor, axis_name)


def send_forward(output_tensor: jnp.ndarray,
                 axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    return send_recv_next(output_tensor, axis_name)


def recv_backward(output_tensor_grad: jnp.ndarray,
                  axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    return send_recv_prev(output_tensor_grad, axis_name)


def send_backward(input_tensor_grad: jnp.ndarray,
                  axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    return send_recv_prev(input_tensor_grad, axis_name)


def send_forward_recv_backward(output_tensor: jnp.ndarray,
                               output_tensor_grad: jnp.ndarray,
                               axis_name: str = PIPELINE_AXIS):
    return send_recv_next(output_tensor, axis_name), send_recv_prev(
        output_tensor_grad, axis_name)


def send_backward_recv_forward(input_tensor_grad: jnp.ndarray,
                               input_tensor: jnp.ndarray,
                               axis_name: str = PIPELINE_AXIS):
    return send_recv_prev(input_tensor_grad, axis_name), send_recv_next(
        input_tensor, axis_name)


def send_forward_recv_forward(output_tensor: jnp.ndarray,
                              axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    return send_recv_next(output_tensor, axis_name)


def send_backward_recv_backward(input_tensor_grad: jnp.ndarray,
                                axis_name: str = PIPELINE_AXIS) -> jnp.ndarray:
    return send_recv_prev(input_tensor_grad, axis_name)
