"""Pipeline-parallel schedules.

TPU-native re-design of
``apex.transformer.pipeline_parallel.schedules`` (reference
schedules/__init__.py:16-34 and the three schedule modules).

The reference schedules are eager Python loops issuing blocking NCCL
send/recv per microbatch (1F1B warmup/steady/cooldown,
fwd_bwd_pipelining_without_interleaving.py:22-170).  Under XLA the whole
schedule is *one compiled program*: a ``lax.scan`` over time steps in which
every stage applies its layer block and hands its activation to the next
stage via ``ppermute`` over the mesh "pipeline" axis.  Differentiating the
scanned forward yields the backward pipeline automatically (the transpose
of ``ppermute`` is the reverse ``ppermute``), so 1F1B's hand-managed
backward scheduling collapses into ``jax.value_and_grad`` — microbatch
grad accumulation, stage transfer, and cooldown come from the scan's
transpose, with XLA's latency-hiding scheduler overlapping compute and ICI
transfers.

Scheduling cost model (same accounting as the reference): with ``p`` stages
and ``m`` microbatches the compiled loop runs ``m + p - 1`` steps; the
fill/drain bubble fraction is ``(p-1)/(m+p-1)``.  The interleaved variant
runs virtual stages ``v = p·vpp`` in a ring, bubble ``(p-1)/(m·vpp + ...)``
— smaller, exactly as the reference's interleaved 1F1B
(fwd_bwd_pipelining_with_interleaving.py).

SPMD note: every stage runs the same program, so stage-special work
(embedding on the first stage, loss head on the last) is expressed with
``jnp.where`` on ``parallel_state.get_pipeline_model_parallel_rank()``
inside the user's ``stage_fn``.  Fill/drain steps compute on zero buffers
and are masked out of the loss — wasted FLOPs identical to the reference's
bubble, not extra.

All schedule functions must run **inside shard_map** binding the
"pipeline" axis (plus "tensor"/"data" if the stage uses them).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_recv_next,
    send_recv_prev,
)

StageFn = Callable[[Any, jnp.ndarray, Any], jnp.ndarray]
# loss_fn receives the stage-local params so the last stage can apply its head
LossFn = Callable[[Any, jnp.ndarray, Any], jnp.ndarray]


def _get_microbatch(microbatches, m):
    """Dynamic-index microbatch ``m`` (clipped) out of the stacked batch."""
    def idx(a):
        mm = jnp.clip(m, 0, a.shape[0] - 1)
        return jax.lax.dynamic_index_in_dim(a, mm, axis=0, keepdims=False)

    return jax.tree_util.tree_map(idx, microbatches)


def forward_backward_no_pipelining(
    forward_step_fn: Callable[[Any, Any], jnp.ndarray],
    loss_fn: Optional[LossFn] = None,
    params: Any = None,
    microbatches: Any = None,
    *,
    n_microbatches: int,
    tensor_shape: Optional[Sequence[int]] = None,
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    forward_only: bool = False,
    remat: bool = False,
):
    """Microbatched gradient accumulation, no pipelining
    (reference fwd_bwd_no_pipelining.py:29-91: grad-accum under
    ``model.no_sync`` then a final sync step).

    Two calling conventions, so :func:`get_forward_backward_func` is
    swappable across pipeline sizes exactly like the reference selector:

    * simple: ``forward_step_fn(params, microbatch) -> scalar loss`` with
      ``loss_fn=None`` (pass params/microbatches positionally or by name);
    * schedule-compatible: the pipelined ``(stage_fn, loss_fn, params,
      microbatches, ..., tensor_shape=...)`` signature — the stage runs as
      the single stage and ``loss_fn`` applies the head.

    Returns ``(mean_loss, grads)`` — grads averaged over microbatches — or
    ``(mean_loss,)`` if ``forward_only`` (same shape as the pipelined
    schedules).
    """
    del axis_name  # single-stage: no pipeline collective needed
    if loss_fn is not None:
        if tensor_shape is None:
            raise ValueError("tensor_shape is required with a loss_fn")
        buf0 = jnp.zeros(tuple(tensor_shape), dtype)

        def step(p, mb):
            return loss_fn(p, forward_step_fn(p, buf0, mb), mb)
    else:
        step = forward_step_fn
    if remat:
        step = jax.checkpoint(step)

    if forward_only:
        def body(_, m):
            return None, step(params, _get_microbatch(microbatches, m))

        _, losses = jax.lax.scan(body, None, jnp.arange(n_microbatches))
        return (jnp.mean(losses),)

    grad_fn = jax.value_and_grad(step)

    def body(acc, m):
        loss_acc, grad_acc = acc
        loss, g = grad_fn(params, _get_microbatch(microbatches, m))
        return (loss_acc + loss,
                jax.tree_util.tree_map(jnp.add, grad_acc, g)), None

    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.result_type(p)), params)
    (loss_sum, grad_sum), _ = jax.lax.scan(
        body, (jnp.zeros(()), zero_grads), jnp.arange(n_microbatches))
    inv = 1.0 / n_microbatches
    return loss_sum * inv, jax.tree_util.tree_map(
        lambda g: g * inv, grad_sum)


def _pipelined_loss(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = False,
):
    """Compiled fill-steady-drain pipeline forward; returns mean loss
    (replicated across stages via masked psum)."""
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    is_last = stage == n_stages - 1
    T = n_microbatches + n_stages - 1

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(carry, t):
        buf, loss_sum = carry
        m = t - stage  # microbatch index this stage handles at step t
        mb = _get_microbatch(microbatches, m)
        y = fn(params, buf, mb)
        valid = (m >= 0) & (m < n_microbatches)
        step_loss = jnp.where(valid & is_last,
                              loss_fn(params, y, mb).astype(jnp.float32), 0.0)
        # transfer to the next stage; stage 0's incoming slot carries
        # wrap-around garbage it never reads (its stage_fn embeds from mb)
        buf = send_recv_next(y, axis_name)
        return (buf, loss_sum + step_loss), None

    buf0 = jnp.zeros(tuple(tensor_shape), dtype)
    (_, loss_sum), _ = jax.lax.scan(
        body, (buf0, jnp.zeros((), jnp.float32)), jnp.arange(T))
    # Return the *local* mean loss (nonzero only on the last stage).  The
    # caller psums it for reporting.  Differentiating the local loss is what
    # makes grads correct when value_and_grad runs inside shard_map: every
    # device seeds cotangent 1.0, so a psum here would transpose into a
    # pp-fold overcount; with the local loss, only the last stage's
    # cotangent is live and the ppermute transposes route it backward
    # through the stages — the compiled backward pipeline.
    return loss_sum / n_microbatches


def _one_f_one_b(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    n_stages: Optional[int] = None,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
):
    """True 1F1B: one compiled scan doing forward AND backward together,
    with manually threaded cotangents — the memory behavior of the
    reference schedule (fwd_bwd_pipelining_without_interleaving.py:112-149),
    not just its loss.

    Schedule (static, SPMD-uniform; p = n_stages, m = n_microbatches):

    - forward of µbatch ``m`` at stage ``s`` runs at step ``t = m + s``;
    - backward of µbatch ``m`` at stage ``s`` runs at
      ``t = m + 2(p-1) - s`` (at the last stage forward and backward of the
      same µbatch share a step, exactly 1F1B's turn-around);
    - total ``T = m + 2(p-1)`` steps — the reference's fill + steady +
      drain accounting in fwd/bwd slot units.

    Memory: the only saved activations are each in-flight µbatch's stage
    *input*, held in a ring buffer of ``2p-1`` slots — stage ``s`` keeps a
    residual alive for ``2(p-1-s)`` steps, the reference's
    num_warmup_microbatches bound — so live activations are **O(p)**,
    independent of ``m``. The backward step recomputes the stage from the
    saved input and pulls gradients out with ``jax.vjp`` (activation
    recompute is inherent, as with the reference running under
    ``torch.utils.checkpoint``); cotangents ride a second ``ppermute``
    stream in the reverse direction.

    Implemented as the ``vpp=1`` case of the generalized
    :func:`_interleaved_one_f_one_b` (one mechanism, both schedules).

    Returns ``(local mean loss, param grads)``.
    """
    chunked = jax.tree_util.tree_map(lambda a: a[None], params)
    loss, grads = _interleaved_one_f_one_b(
        lambda pk, h, mb, k: stage_fn(pk, h, mb), loss_fn,
        chunked, microbatches,
        n_microbatches=n_microbatches, num_model_chunks=1,
        n_stages=n_stages, tensor_shape=tensor_shape, dtype=dtype,
        axis_name=axis_name)
    return loss, jax.tree_util.tree_map(lambda g: g[0], grads)


def forward_backward_pipelining_without_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    forward_only: bool = False,
    remat: bool = True,
    n_stages: Optional[int] = None,
):
    """Non-interleaved 1F1B pipelining (reference
    fwd_bwd_pipelining_without_interleaving.py:22-170).

    ``stage_fn(params, hidden_in, microbatch) -> hidden_out`` — the user's
    per-stage block; it must select embedding/identity input by stage (see
    module docstring).  ``loss_fn(params, hidden_out, microbatch) ->
    scalar`` — evaluated on the last stage only (``params`` is that stage's
    local tree, carrying the head weights).  ``tensor_shape`` is the inter-stage
    activation shape, exactly the reference's ``tensor_shape`` argument
    (seq, microbatch, hidden) passed to its p2p layer.

    The backward path is the explicit compiled 1F1B of :func:`_one_f_one_b`
    — live activations bounded O(p) by a ring buffer, with per-stage
    recompute (``remat`` is accepted for API stability; recompute is
    inherent). ``n_stages`` defaults to the shard_map axis size.

    Returns ``(mean_loss, grads)``; ``forward_only=True`` returns
    ``(mean_loss,)`` (reference's losses_reduced).
    """
    if forward_only:
        run = functools.partial(
            _pipelined_loss, stage_fn, loss_fn,
            n_microbatches=n_microbatches, tensor_shape=tensor_shape,
            dtype=dtype, axis_name=axis_name, remat=remat)
        return (jax.lax.psum(run(params, microbatches), axis_name),)
    loss, grads = _one_f_one_b(
        stage_fn, loss_fn, params, microbatches,
        n_microbatches=n_microbatches, n_stages=n_stages,
        tensor_shape=tensor_shape, dtype=dtype, axis_name=axis_name)
    return jax.lax.psum(loss, axis_name), grads


def _interleaved_loss(
    chunk_fn: Callable[[Any, jnp.ndarray, Any, int], jnp.ndarray],
    loss_fn: LossFn,
    chunked_params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    num_model_chunks: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = False,
):
    """Ring pipeline over p·vpp virtual stages (interleaved schedule).

    Device ``d`` owns virtual stages ``d + p·k`` for local chunk
    ``k < vpp`` (the reference's model-chunk assignment,
    fwd_bwd_pipelining_with_interleaving.py).  Activations travel the ring
    0→1→…→p-1→0→…; crossing the wrap edge advances the chunk index.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    vpp = num_model_chunks
    total_virtual = n_stages * vpp
    is_last = stage == n_stages - 1
    T = n_microbatches + total_virtual - 1

    fn = jax.checkpoint(chunk_fn, static_argnums=(3,)) if remat else chunk_fn

    def body(carry, t):
        bufs, loss_sum = carry  # bufs: [vpp, *tensor_shape]
        ys = []
        for k in range(vpp):
            m = t - (stage + n_stages * k)  # µbatch at virtual stage d+p·k
            mb = _get_microbatch(microbatches, m)
            pk = jax.tree_util.tree_map(lambda a: a[k], chunked_params)
            y = fn(pk, bufs[k], mb, k)
            # last *virtual* stage: local chunk vpp-1 on last device
            valid = (m >= 0) & (m < n_microbatches)
            if k == vpp - 1:
                loss_sum = loss_sum + jnp.where(
                    valid & is_last, loss_fn(pk, y, mb).astype(jnp.float32), 0.0)
            ys.append(y)
        y_stack = jnp.stack(ys)
        r = send_recv_next(y_stack, axis_name)  # ring by device
        # crossing p-1 → 0 advances the chunk: device 0's chunk k input is
        # the wrapped output of chunk k-1; other devices keep chunk index
        r_shifted = jnp.concatenate([jnp.zeros_like(r[:1]), r[:-1]], axis=0)
        bufs = jnp.where(stage == 0, r_shifted, r)
        return (bufs, loss_sum), None

    bufs0 = jnp.zeros((vpp, *tensor_shape), dtype)
    (_, loss_sum), _ = jax.lax.scan(
        body, (bufs0, jnp.zeros((), jnp.float32)), jnp.arange(T))
    # local mean loss — see the matching note in _pipelined_loss
    return loss_sum / n_microbatches


def _interleaved_one_f_one_b(
    chunk_fn: Callable[[Any, jnp.ndarray, Any, int], jnp.ndarray],
    loss_fn: LossFn,
    chunked_params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    num_model_chunks: int,
    n_stages: Optional[int] = None,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
):
    """Interleaved 1F1B over the virtual-stage ring — the compiled-1F1B
    mechanism per local model chunk (the non-interleaved schedule is the
    ``vpp=1`` case, see :func:`_one_f_one_b`).

    Virtual stage ``v = d + p·k`` (device d, local chunk k < vpp);
    forward of µbatch ``m`` at ``v`` runs at ``t = m + v``, backward at
    ``t = m + 2(V-1) - v`` with ``V = p·vpp``.  Each chunk keeps its
    in-flight stage inputs in a ``2V-1``-slot ring, so live activations
    are **O(p·vpp)**, independent of ``m`` (vs the AD-through-scan
    formulation's O(m)).  Activations ride the device ring forward with
    a chunk advance at the 0-wrap; cotangents ride it backward with the
    mirrored chunk retreat at the (p-1)-wrap.

    Returns ``(local mean loss, chunked param grads)``.
    """
    p = (int(jax.lax.psum(1, axis_name)) if n_stages is None
         else n_stages)
    vpp = num_model_chunks
    V = p * vpp
    m_total = n_microbatches
    stage = jax.lax.axis_index(axis_name)
    is_last = stage == p - 1
    T = m_total + 2 * (V - 1)
    inv_m = 1.0 / m_total
    # chunk k's residual lives 2(V-1-v) steps, v = stage + p·k; size each
    # ring for its own worst case (stage 0) instead of a uniform 2V-1 —
    # total slots sum_k 2(V-1-p·k)+1 ≈ p·vpp² vs the quadratic-waste
    # uniform vpp·(2V-1)
    Rs = [max(2 * (V - 1 - p * k) + 1, 1) for k in range(vpp)]

    bufs0 = jnp.zeros((vpp, *tensor_shape), dtype)
    rings0 = tuple(jnp.zeros((Rs[k], *tensor_shape), dtype)
                   for k in range(vpp))
    grads0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), chunked_params)

    def body(carry, t):
        bufs, dys, rings, grad_acc, loss_sum = carry
        ys, dbufs = [], []
        rings = list(rings)
        for k in range(vpp):
            v = stage + p * k
            R = Rs[k]
            pk = jax.tree_util.tree_map(lambda a: a[k], chunked_params)

            # ---- forward slot of virtual stage v ----
            m_f = t - v
            mb_f = _get_microbatch(microbatches, m_f)
            with jax.named_scope("pp_forward_slot"):
                ys.append(chunk_fn(pk, bufs[k], mb_f, k))
            rings[k] = jax.lax.dynamic_update_index_in_dim(
                rings[k], bufs[k], t % R, axis=0)

            # ---- backward slot of virtual stage v ----
            m_b = t - 2 * (V - 1) + v
            b_valid = (m_b >= 0) & (m_b < m_total)
            mb_b = _get_microbatch(microbatches, m_b)
            slot = (m_b + v) % R  # the step its input was saved
            buf_b = jax.lax.dynamic_index_in_dim(rings[k], slot, axis=0,
                                                 keepdims=False)

            def fwd_chain(pp, bb, mb_b=mb_b, k=k):
                yy = chunk_fn(pp, bb, mb_b, k)
                step_loss = loss_fn(pp, yy, mb_b).astype(jnp.float32)
                return yy, step_loss

            with jax.named_scope("pp_backward_slot"):
                (y_b, step_loss), vjp = jax.vjp(fwd_chain, pk, buf_b)
                if k == vpp - 1:
                    # last virtual stage lives here on the last device:
                    # loss-seeded; elsewhere the cotangent arrives
                    seed_y = (jnp.where(is_last, 0.0, 1.0)
                              * dys[k].astype(y_b.dtype))
                    seed_loss = jnp.where(is_last, inv_m, 0.0)
                else:
                    seed_y = dys[k].astype(y_b.dtype)
                    seed_loss = jnp.zeros(())
                dparams, dbuf = vjp(
                    (seed_y, jnp.asarray(seed_loss, jnp.float32)))

            # where-mask (not multiply): a vjp on stale ring-buffer inputs
            # may yield inf/nan, and 0*nan would poison the accumulator
            grad_acc = jax.tree_util.tree_map(
                lambda acc, g, k=k, b_valid=b_valid: acc.at[k].add(
                    jnp.where(b_valid, g.astype(jnp.float32), 0.0)),
                grad_acc, dparams)
            dbufs.append(jnp.where(b_valid, dbuf, jnp.zeros_like(dbuf)))
            if k == vpp - 1:
                loss_sum = loss_sum + jnp.where(
                    b_valid & is_last, step_loss, 0.0)

        # ---- transfers ----
        # activations: device ring forward; crossing p-1 → 0 advances the
        # chunk (device 0's chunk k input is the wrapped output of k-1)
        r = send_recv_next(jnp.stack(ys), axis_name)
        r_shifted = jnp.concatenate([jnp.zeros_like(r[:1]), r[:-1]], axis=0)
        bufs_next = jnp.where(stage == 0, r_shifted, r)
        # cotangents: device ring backward; crossing 0 → p-1 retreats the
        # chunk (device p-1's chunk k cotangent is device 0's chunk k+1);
        # the last virtual stage's slot is zeroed — it is loss-seeded
        rb = send_recv_prev(jnp.stack(dbufs).astype(dtype), axis_name)
        rb_shifted = jnp.concatenate(
            [rb[1:], jnp.zeros_like(rb[:1])], axis=0)
        dys_next = jnp.where(is_last, rb_shifted, rb)
        return (bufs_next, dys_next, tuple(rings), grad_acc, loss_sum), None

    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        body, (bufs0, bufs0, rings0, grads0, jnp.zeros((), jnp.float32)),
        jnp.arange(T))
    return loss_sum * inv_m, grads


def forward_backward_pipelining_with_interleaving(
    chunk_fn: Callable[[Any, jnp.ndarray, Any, int], jnp.ndarray],
    loss_fn: LossFn,
    chunked_params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    num_model_chunks: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    forward_only: bool = False,
    remat: bool = True,
):
    """Interleaved (virtual-pipeline) schedule — reference
    fwd_bwd_pipelining_with_interleaving.py:1-308.

    ``chunk_fn(chunk_params, hidden_in, microbatch, local_chunk_idx) ->
    hidden_out``; ``chunked_params`` has a leading ``[vpp]`` axis per leaf
    (this device's model chunks).  The first virtual stage embeds, the last
    computes the head — chunk_fn selects by
    ``(get_pipeline_model_parallel_rank(), local_chunk_idx)``.

    The backward path is the explicit interleaved 1F1B of
    :func:`_interleaved_one_f_one_b` — live activations bounded
    O(p·vpp) by per-chunk ring buffers, per-chunk recompute via
    ``jax.vjp`` (``remat`` is accepted for API stability; recompute is
    inherent).
    """
    if forward_only:
        run = functools.partial(
            _interleaved_loss, chunk_fn, loss_fn,
            n_microbatches=n_microbatches, num_model_chunks=num_model_chunks,
            tensor_shape=tensor_shape, dtype=dtype, axis_name=axis_name,
            remat=remat)
        return (jax.lax.psum(run(chunked_params, microbatches), axis_name),)
    loss, grads = _interleaved_one_f_one_b(
        chunk_fn, loss_fn, chunked_params, microbatches,
        n_microbatches=n_microbatches, num_model_chunks=num_model_chunks,
        tensor_shape=tensor_shape, dtype=dtype, axis_name=axis_name)
    return jax.lax.psum(loss, axis_name), grads


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: int = 1,
):
    """Schedule selector (reference schedules/__init__.py:16-34)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
