"""Pipeline-parallel schedules.

TPU-native re-design of
``apex.transformer.pipeline_parallel.schedules`` (reference
schedules/__init__.py:16-34 and the three schedule modules).

The reference schedules are eager Python loops issuing blocking NCCL
send/recv per microbatch (1F1B warmup/steady/cooldown,
fwd_bwd_pipelining_without_interleaving.py:22-170).  Under XLA the whole
schedule is *one compiled program*: a ``lax.scan`` over time steps in which
every stage applies its layer block and hands its activation to the next
stage via ``ppermute`` over the mesh "pipeline" axis.  Differentiating the
scanned forward yields the backward pipeline automatically (the transpose
of ``ppermute`` is the reverse ``ppermute``), so 1F1B's hand-managed
backward scheduling collapses into ``jax.value_and_grad`` — microbatch
grad accumulation, stage transfer, and cooldown come from the scan's
transpose, with XLA's latency-hiding scheduler overlapping compute and ICI
transfers.

Scheduling cost model (same accounting as the reference): with ``p`` stages
and ``m`` microbatches the compiled loop runs ``m + p - 1`` steps; the
fill/drain bubble fraction is ``(p-1)/(m+p-1)``.  The interleaved variant
runs virtual stages ``v = p·vpp`` in a ring, bubble ``(p-1)/(m·vpp + ...)``
— smaller, exactly as the reference's interleaved 1F1B
(fwd_bwd_pipelining_with_interleaving.py).

SPMD note: every stage runs the same program, so stage-special work
(embedding on the first stage, loss head on the last) is expressed with
``jnp.where`` on ``parallel_state.get_pipeline_model_parallel_rank()``
inside the user's ``stage_fn``.  Fill/drain steps compute on zero buffers
and are masked out of the loss — wasted FLOPs identical to the reference's
bubble, not extra.

All schedule functions must run **inside shard_map** binding the
"pipeline" axis (plus "tensor"/"data" if the stage uses them).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_recv_next,
)

StageFn = Callable[[Any, jnp.ndarray, Any], jnp.ndarray]
# loss_fn receives the stage-local params so the last stage can apply its head
LossFn = Callable[[Any, jnp.ndarray, Any], jnp.ndarray]


def _get_microbatch(microbatches, m):
    """Dynamic-index microbatch ``m`` (clipped) out of the stacked batch."""
    def idx(a):
        mm = jnp.clip(m, 0, a.shape[0] - 1)
        return jax.lax.dynamic_index_in_dim(a, mm, axis=0, keepdims=False)

    return jax.tree_util.tree_map(idx, microbatches)


def forward_backward_no_pipelining(
    forward_step_fn: Callable[[Any, Any], jnp.ndarray],
    loss_fn: Optional[LossFn] = None,
    params: Any = None,
    microbatches: Any = None,
    *,
    n_microbatches: int,
    tensor_shape: Optional[Sequence[int]] = None,
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    forward_only: bool = False,
    remat: bool = False,
):
    """Microbatched gradient accumulation, no pipelining
    (reference fwd_bwd_no_pipelining.py:29-91: grad-accum under
    ``model.no_sync`` then a final sync step).

    Two calling conventions, so :func:`get_forward_backward_func` is
    swappable across pipeline sizes exactly like the reference selector:

    * simple: ``forward_step_fn(params, microbatch) -> scalar loss`` with
      ``loss_fn=None`` (pass params/microbatches positionally or by name);
    * schedule-compatible: the pipelined ``(stage_fn, loss_fn, params,
      microbatches, ..., tensor_shape=...)`` signature — the stage runs as
      the single stage and ``loss_fn`` applies the head.

    Returns ``(mean_loss, grads)`` — grads averaged over microbatches — or
    ``(mean_loss,)`` if ``forward_only`` (same shape as the pipelined
    schedules).
    """
    del axis_name  # single-stage: no pipeline collective needed
    if loss_fn is not None:
        if tensor_shape is None:
            raise ValueError("tensor_shape is required with a loss_fn")
        buf0 = jnp.zeros(tuple(tensor_shape), dtype)

        def step(p, mb):
            return loss_fn(p, forward_step_fn(p, buf0, mb), mb)
    else:
        step = forward_step_fn
    if remat:
        step = jax.checkpoint(step)

    if forward_only:
        def body(_, m):
            return None, step(params, _get_microbatch(microbatches, m))

        _, losses = jax.lax.scan(body, None, jnp.arange(n_microbatches))
        return (jnp.mean(losses),)

    grad_fn = jax.value_and_grad(step)

    def body(acc, m):
        loss_acc, grad_acc = acc
        loss, g = grad_fn(params, _get_microbatch(microbatches, m))
        return (loss_acc + loss,
                jax.tree_util.tree_map(jnp.add, grad_acc, g)), None

    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.result_type(p)), params)
    (loss_sum, grad_sum), _ = jax.lax.scan(
        body, (jnp.zeros(()), zero_grads), jnp.arange(n_microbatches))
    inv = 1.0 / n_microbatches
    return loss_sum * inv, jax.tree_util.tree_map(
        lambda g: g * inv, grad_sum)


def _pipelined_loss(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = False,
):
    """Compiled fill-steady-drain pipeline forward; returns mean loss
    (replicated across stages via masked psum)."""
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    is_last = stage == n_stages - 1
    T = n_microbatches + n_stages - 1

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(carry, t):
        buf, loss_sum = carry
        m = t - stage  # microbatch index this stage handles at step t
        mb = _get_microbatch(microbatches, m)
        y = fn(params, buf, mb)
        valid = (m >= 0) & (m < n_microbatches)
        step_loss = jnp.where(valid & is_last,
                              loss_fn(params, y, mb).astype(jnp.float32), 0.0)
        # transfer to the next stage; stage 0's incoming slot carries
        # wrap-around garbage it never reads (its stage_fn embeds from mb)
        buf = send_recv_next(y, axis_name)
        return (buf, loss_sum + step_loss), None

    buf0 = jnp.zeros(tuple(tensor_shape), dtype)
    (_, loss_sum), _ = jax.lax.scan(
        body, (buf0, jnp.zeros((), jnp.float32)), jnp.arange(T))
    # Return the *local* mean loss (nonzero only on the last stage).  The
    # caller psums it for reporting.  Differentiating the local loss is what
    # makes grads correct when value_and_grad runs inside shard_map: every
    # device seeds cotangent 1.0, so a psum here would transpose into a
    # pp-fold overcount; with the local loss, only the last stage's
    # cotangent is live and the ppermute transposes route it backward
    # through the stages — the compiled backward pipeline.
    return loss_sum / n_microbatches


def forward_backward_pipelining_without_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    forward_only: bool = False,
    remat: bool = True,
):
    """Non-interleaved pipelining (reference
    fwd_bwd_pipelining_without_interleaving.py:22-170).

    ``stage_fn(params, hidden_in, microbatch) -> hidden_out`` — the user's
    per-stage block; it must select embedding/identity input by stage (see
    module docstring).  ``loss_fn(params, hidden_out, microbatch) ->
    scalar`` — evaluated on the last stage only (``params`` is that stage's
    local tree, carrying the head weights).  ``tensor_shape`` is the inter-stage
    activation shape, exactly the reference's ``tensor_shape`` argument
    (seq, microbatch, hidden) passed to its p2p layer.

    Returns ``(mean_loss, grads)``; ``forward_only=True`` returns
    ``(mean_loss,)`` (reference's losses_reduced).
    """
    run = functools.partial(
        _pipelined_loss, stage_fn, loss_fn,
        n_microbatches=n_microbatches, tensor_shape=tensor_shape,
        dtype=dtype, axis_name=axis_name, remat=remat)
    if forward_only:
        return (jax.lax.psum(run(params, microbatches), axis_name),)
    loss, grads = jax.value_and_grad(run)(params, microbatches)
    return jax.lax.psum(loss, axis_name), grads


def _interleaved_loss(
    chunk_fn: Callable[[Any, jnp.ndarray, Any, int], jnp.ndarray],
    loss_fn: LossFn,
    chunked_params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    num_model_chunks: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = False,
):
    """Ring pipeline over p·vpp virtual stages (interleaved schedule).

    Device ``d`` owns virtual stages ``d + p·k`` for local chunk
    ``k < vpp`` (the reference's model-chunk assignment,
    fwd_bwd_pipelining_with_interleaving.py).  Activations travel the ring
    0→1→…→p-1→0→…; crossing the wrap edge advances the chunk index.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    vpp = num_model_chunks
    total_virtual = n_stages * vpp
    is_last = stage == n_stages - 1
    T = n_microbatches + total_virtual - 1

    fn = jax.checkpoint(chunk_fn, static_argnums=(3,)) if remat else chunk_fn

    def body(carry, t):
        bufs, loss_sum = carry  # bufs: [vpp, *tensor_shape]
        ys = []
        for k in range(vpp):
            m = t - (stage + n_stages * k)  # µbatch at virtual stage d+p·k
            mb = _get_microbatch(microbatches, m)
            pk = jax.tree_util.tree_map(lambda a: a[k], chunked_params)
            y = fn(pk, bufs[k], mb, k)
            # last *virtual* stage: local chunk vpp-1 on last device
            valid = (m >= 0) & (m < n_microbatches)
            if k == vpp - 1:
                loss_sum = loss_sum + jnp.where(
                    valid & is_last, loss_fn(pk, y, mb).astype(jnp.float32), 0.0)
            ys.append(y)
        y_stack = jnp.stack(ys)
        r = send_recv_next(y_stack, axis_name)  # ring by device
        # crossing p-1 → 0 advances the chunk: device 0's chunk k input is
        # the wrapped output of chunk k-1; other devices keep chunk index
        r_shifted = jnp.concatenate([jnp.zeros_like(r[:1]), r[:-1]], axis=0)
        bufs = jnp.where(stage == 0, r_shifted, r)
        return (bufs, loss_sum), None

    bufs0 = jnp.zeros((vpp, *tensor_shape), dtype)
    (_, loss_sum), _ = jax.lax.scan(
        body, (bufs0, jnp.zeros((), jnp.float32)), jnp.arange(T))
    # local mean loss — see the matching note in _pipelined_loss
    return loss_sum / n_microbatches


def forward_backward_pipelining_with_interleaving(
    chunk_fn: Callable[[Any, jnp.ndarray, Any, int], jnp.ndarray],
    loss_fn: LossFn,
    chunked_params: Any,
    microbatches: Any,
    *,
    n_microbatches: int,
    num_model_chunks: int,
    tensor_shape: Sequence[int],
    dtype=jnp.float32,
    axis_name: str = PIPELINE_AXIS,
    forward_only: bool = False,
    remat: bool = True,
):
    """Interleaved (virtual-pipeline) schedule — reference
    fwd_bwd_pipelining_with_interleaving.py:1-308.

    ``chunk_fn(chunk_params, hidden_in, microbatch, local_chunk_idx) ->
    hidden_out``; ``chunked_params`` has a leading ``[vpp]`` axis per leaf
    (this device's model chunks).  The first virtual stage embeds, the last
    computes the head — chunk_fn selects by
    ``(get_pipeline_model_parallel_rank(), local_chunk_idx)``.
    """
    run = functools.partial(
        _interleaved_loss, chunk_fn, loss_fn,
        n_microbatches=n_microbatches, num_model_chunks=num_model_chunks,
        tensor_shape=tensor_shape, dtype=dtype, axis_name=axis_name,
        remat=remat)
    if forward_only:
        return (jax.lax.psum(run(chunked_params, microbatches), axis_name),)
    loss, grads = jax.value_and_grad(run)(chunked_params, microbatches)
    return jax.lax.psum(loss, axis_name), grads


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: int = 1,
):
    """Schedule selector (reference schedules/__init__.py:16-34)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
