"""Pipeline-parallel utilities.

TPU-native port of ``apex.transformer.pipeline_parallel.utils``
(reference pipeline_parallel/utils.py) — microbatch-calculator globals,
loss averaging, Megatron mask/position-id helpers, param-norm reporting.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    build_num_microbatches_calculator,
)

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """Reference utils.py:57-75 (asserts single init)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples,
                                               consistency_check)


def get_num_microbatches() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def get_micro_batch_size() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.micro_batch_size


def listify_model(model: Any) -> List[Any]:
    """Reference utils.py:104-107."""
    return model if isinstance(model, list) else [model]


def unwrap_model(model, module_instances=()):
    """Reference utils.py:110-128 unwraps DDP/FP16 wrappers; functional
    pytrees have no wrappers, so this is identity-or-unlist."""
    return_list = True
    if not isinstance(model, list):
        model = [model]
        return_list = False
    unwrapped = [getattr(m, "module", m) for m in model]
    return unwrapped if return_list else unwrapped[0]


def get_kth_microbatch(batch: Any, k: int, micro_batch_size: int) -> Any:
    """Reference utils.py:137-147: slice microbatch k out of a global batch
    along the leading dim."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(
            a, k * micro_batch_size, micro_batch_size, axis=0), batch)


def split_into_microbatches(batch: Any, n_microbatches: int) -> Any:
    """Reshape [B, ...] -> [n_micro, B/n_micro, ...] for the compiled
    schedules' stacked-microbatch input."""
    def split(a):
        return a.reshape(n_microbatches, a.shape[0] // n_microbatches,
                         *a.shape[1:])

    return jax.tree_util.tree_map(split, batch)


def average_losses_across_data_parallel_group(losses: Sequence[jnp.ndarray],
                                              axis_name: str = DATA_AXIS):
    """Reference utils.py:218-226: stack losses and pmean over the data
    axis.  Must run inside a region binding ``axis_name``."""
    return jax.lax.pmean(jnp.stack([jnp.asarray(l) for l in losses]),
                         axis_name)


def calc_params_l2_norm(params: Any) -> jnp.ndarray:
    """Reference utils.py:189-215 (without the TP-duplicate filtering —
    pass only this rank's unique shards)."""
    leaves = jax.tree_util.tree_leaves(params)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def print_params_min_max_norm(params: Any, *, iteration: int = 0) -> str:
    """Reference utils.py:241-259: per-leaf (min, max, l2 norm) dump for
    debugging parameter blowups; rank-0 style print, returns the text."""
    lines = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        a = jnp.asarray(leaf).astype(jnp.float32)
        name = jax.tree_util.keystr(path)
        lines.append(
            f"iteration {iteration}, {name}: min {float(a.min()):+.6e} "
            f"max {float(a.max()):+.6e} norm "
            f"{float(jnp.sqrt(jnp.sum(a * a))):.6e}")
    msg = "\n".join(lines)
    print(msg, flush=True)
    return msg


def get_autoresume():
    """Reference utils.py:131-133: hook for a cluster auto-resume service
    (ADLR internal).  No TPU-side service exists — returns None, and the
    caller's periodic check (reference :262-277) becomes a no-op; restarts
    are handled by checkpoint/resume (:mod:`apex_tpu.checkpoint`)."""
    return None


def check_adlr_autoresume_termination(iteration, state, args=None,
                                      save_fn=None):
    """Reference utils.py:262-277 parity: if an autoresume service is
    present and requests termination, save and signal exit.  Returns True
    when the caller should stop (always False without a service)."""
    svc = get_autoresume()
    if svc is None:
        return False
    if svc.termination_requested():  # pragma: no cover - no service here
        if save_fn is not None:
            save_fn(iteration, state)
        svc.request_resume()
        return True
    return False


def get_ltor_masks_and_position_ids(
    data: jnp.ndarray,
    eod_token: int,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Left-to-right (causal) masks and position ids
    (reference utils.py:279-333).

    Returns ``(attention_mask, loss_mask, position_ids)`` with the
    reference's conventions: attention_mask boolean with True = *masked
    out* (ready for :func:`apex_tpu.ops.scaled_masked_softmax`), loss_mask
    1.0 where the token contributes to the loss.

    The per-document reset options use a scan over the sequence instead of
    the reference's per-eod Python loop (jit-compatible, no host sync).
    """
    b, seq = data.shape
    causal = ~jnp.tril(jnp.ones((seq, seq), bool))  # True above diagonal
    attention_mask = jnp.broadcast_to(causal, (b, 1, seq, seq))

    loss_mask = jnp.ones((b, seq), jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(seq), (b, seq))

    if reset_position_ids or reset_attention_mask:
        is_eod = data == eod_token
        # document id of each position = number of eods strictly before it
        doc_id = jnp.cumsum(jnp.pad(is_eod[:, :-1], ((0, 0), (1, 0))), axis=1)
        if reset_attention_mask:
            same_doc = doc_id[:, None, :, None] == doc_id[:, None, None, :]
            attention_mask = attention_mask | ~same_doc
        if reset_position_ids:
            # position within document: index - index of document start
            idx = jnp.arange(seq)[None, :]
            doc_start = jnp.where(
                jnp.pad(is_eod[:, :-1], ((0, 0), (1, 0))), idx, 0)
            doc_start = jax.lax.cummax(doc_start, axis=1)
            position_ids = idx - doc_start

    return attention_mask, loss_mask, position_ids


def report_memory(name: str) -> str:
    """Reference utils.py:229-238 prints CUDA allocator stats; on TPU the
    equivalent signal is per-device memory stats from the runtime."""
    lines = [f"memory ({name})"]
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            lines.append(
                f"  {d}: in_use={stats.get('bytes_in_use', 0) / 2**20:.1f}MiB "
                f"limit={stats.get('bytes_limit', 0) / 2**20:.1f}MiB")
    msg = "\n".join(lines)
    print(msg, flush=True)
    return msg
