"""Generic pipeline model construction (reference
apex/transformer/pipeline_parallel/schedules/common.py:18-106).

The reference ``build_model(model_provider_func, wrap_with_ddp, ...)``
instantiates one module per virtual-pipeline chunk, calling the provider
with ``pre_process`` / ``post_process`` flags derived from the stage
position, then optionally wraps each chunk in torch DDP. Here the same
contract, functionally:

- ``model_provider_func(pre_process=..., post_process=...) -> model`` where
  a *model* is any object with ``init(key) -> params`` (or ``init_master``)
  and ``apply(params, hidden_or_batch, ...)``;
- :func:`build_model` returns the list of chunk models — one entry without
  virtual pipelining, ``vpp_size`` entries with it — with the virtual rank
  cursor set around each call exactly as the reference does
  (common.py:46-59);
- DDP wrapping has no object to wrap in JAX: data parallelism is a psum in
  the train step, so ``wrap_with_ddp`` instead attaches the data-parallel
  axis name the step should reduce over (the moral equivalent of
  common.py:95-105).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from apex_tpu.transformer import parallel_state


def build_model(
    model_provider_func: Callable[..., Any],
    wrap_with_ddp: bool = True,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    *args,
    **kwargs,
) -> List[Any]:
    """Reference common.py:18-106. Returns a list of chunk models.

    Provider calls receive ``pre_process`` (this chunk starts with the
    embedding / stem) and ``post_process`` (this chunk ends with the head /
    loss) computed from the pipeline + virtual ranks.
    """
    pp_size = parallel_state.get_pipeline_model_parallel_world_size()
    vpp = virtual_pipeline_model_parallel_size
    # SPMD divergence from the reference: there is no per-stage Python
    # process — ONE program spans every pipeline stage, so each chunk's
    # param structure must include both ends and the stage gating happens
    # inside the traced step (where-masked on the traced pipeline rank, the
    # make_gpt_stage_fns pattern). The flags are therefore True whenever
    # this chunk COULD sit at that end of the pipe; they go False only for
    # middle virtual chunks, which no stage placement ever maps to an end.
    if pp_size > 1 and vpp is not None:
        models = []
        for v in range(vpp):
            # the provider may consult the virtual cursor (common.py:49-52)
            parallel_state.set_virtual_pipeline_model_parallel_rank(v)
            models.append(
                model_provider_func(
                    *args,
                    pre_process=(v == 0),
                    post_process=(v == vpp - 1),
                    **kwargs,
                )
            )
        parallel_state.set_virtual_pipeline_model_parallel_rank(0)
    else:
        models = [
            model_provider_func(*args, pre_process=True, post_process=True,
                                **kwargs)
        ]
    if wrap_with_ddp:
        for m in models:
            # the step reduces grads over this axis (stands in for the
            # torchDDP wrap of common.py:95-105)
            setattr(m, "data_parallel_axis",
                    parallel_state.get_data_parallel_group())
    return models
