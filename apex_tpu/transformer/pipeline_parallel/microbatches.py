"""Microbatch calculators.

TPU-native port of ``apex.transformer.pipeline_parallel.microbatches``
(reference microbatches.py:21-172) — pure scheduling arithmetic, unchanged
semantics: global batch = micro_batch_size × num_micro_batches × dp_size,
with optional linear ramp-up of the global batch size over consumed samples.
"""

from __future__ import annotations

from typing import List, Optional


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """Reference microbatches.py:21-56."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "expected the following format: --rampup-batch-size <start batch "
            "size> <batch size increment> <ramp-up samples>")
    start, increment, samples = (int(v) for v in rampup_batch_size)
    if rank == 0:
        print(f"will use batch size rampup starting from global batch size "
              f"{start} to global batch size {global_batch_size} with batch "
              f"size increments {increment} over {samples} samples.", flush=True)
    return RampupBatchsizeNumMicroBatches(
        start, increment, samples, global_batch_size, micro_batch_size,
        data_parallel_size)


class NumMicroBatchesCalculator:
    """Reference microbatches.py:59-76."""

    def __init__(self):
        self.num_micro_batches = None
        self.current_global_batch_size = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        pass


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Reference microbatches.py:79-98."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        micro_batch_times_data_parallel = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_data_parallel != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel "
                f"size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // micro_batch_times_data_parallel
        if self.num_micro_batches < 1:
            raise ValueError("number of micro-batches should be at least 1")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear global-batch ramp over consumed samples
    (reference microbatches.py:101-172)."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        if batch_size_increment <= 0:
            raise ValueError("batch size increment must be positive")
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        diff = global_batch_size - start_batch_size
        if diff < 0:
            raise ValueError("global batch size must be >= start batch size")
        if diff % batch_size_increment != 0:
            raise ValueError(
                "expected global batch size interval to be divisible by the "
                "batch size increment")
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments > 0 else 0)
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if (consumed_samples > self.ramup_samples
                or self.rampup_samples_per_increment == 0):
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size)
        if consistency_check:
            if (self.current_global_batch_size
                    % self.micro_batch_times_data_parallel_size != 0):
                raise ValueError(
                    f"current global batch size "
                    f"({self.current_global_batch_size}) is not divisible by "
                    f"micro-batch-size ({self.micro_batch_size}) times data "
                    f"parallel size ({self.data_parallel_size})")
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size)
