"""Named timers.

TPU-native port of ``apex.transformer.pipeline_parallel._timers``
(reference _timers.py:1-83).  The reference cuda-synchronizes around
start/stop; here the device-sync is ``block_until_ready`` on a token the
caller passes (or nothing for host-side phases).
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class _Timer:
    """Reference _timers.py:9-39."""

    def __init__(self, name: str):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = time.time()

    def start(self, sync_on=None):
        if self.started_:
            raise RuntimeError("timer has already been started")
        if sync_on is not None:
            import jax
            jax.block_until_ready(sync_on)
        self.start_time = time.time()
        self.started_ = True

    def stop(self, sync_on=None):
        if not self.started_:
            raise RuntimeError("timer is not started")
        if sync_on is not None:
            import jax
            jax.block_until_ready(sync_on)
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    """Group of named timers (reference _timers.py:42-83).

    ``telemetry`` — optional :class:`apex_tpu.telemetry.TelemetryBus`;
    :meth:`log` then emits a structured ``timers`` event (name → ms
    map) through the bus's sinks instead of printing a bare string.
    The reference ``log`` API is preserved either way: same arguments,
    same formatted string returned."""

    def __init__(self, telemetry=None):
        self.timers: Dict[str, _Timer] = {}
        self.telemetry = telemetry

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names, writer, iteration: int, normalizer: float = 1.0,
              reset: bool = False):
        for name in names:
            value = self.timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(name + "-time", value, iteration)

    def log(self, names=None, normalizer: float = 1.0, reset: bool = True,
            step: Optional[int] = None) -> str:
        if normalizer <= 0.0:
            raise ValueError("normalizer must be positive")
        names = names if names is not None else list(self.timers)
        values = {
            name: self.timers[name].elapsed(reset=reset) * 1000.0
            / normalizer
            for name in names
        }
        string = "time (ms)"
        for name, t in values.items():
            string += f" | {name}: {t:.2f}"
        if self.telemetry is not None:
            self.telemetry.emit(
                "timers", step=step,
                timers_ms={k: round(v, 3) for k, v in values.items()},
                normalizer=normalizer)
        else:
            print(string, flush=True)
        return string


_Timers = Timers
