"""apex_tpu.transformer.pipeline_parallel — compiled pipeline schedules
over the mesh "pipeline" axis (reference apex/transformer/pipeline_parallel/).
"""

from apex_tpu.transformer.pipeline_parallel.microbatches import (  # noqa: F401
    ConstantNumMicroBatches,
    NumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
)
from apex_tpu.transformer.pipeline_parallel.utils import (  # noqa: F401
    average_losses_across_data_parallel_group,
    calc_params_l2_norm,
    destroy_microbatch_calculator,
    get_current_global_batch_size,
    get_kth_microbatch,
    get_ltor_masks_and_position_ids,
    get_num_microbatches,
    listify_model,
    report_memory,
    setup_microbatch_calculator,
    split_into_microbatches,
    unwrap_model,
    update_num_microbatches,
)
