"""Model-parallel state: the device mesh and its named axes.

TPU-native re-design of ``apex.transformer.parallel_state``
(reference apex/transformer/parallel_state.py:58-396).

The reference builds explicit ``torch.distributed`` process groups for the
TP × PP × DP 3-D decomposition (initialize_model_parallel :58-167) plus an
embedding group (first+last pipeline stage :143-167), and every layer asks
it for group handles and ranks.  On TPU there are no process groups: one
``jax.sharding.Mesh`` with axes ``("data", "pipeline", "tensor")`` carries
the whole decomposition, collectives take an axis *name*, and the "group"
for any collective is implied by the axes not mentioned.  The tensor axis is
innermost so TP collectives ride the fastest ICI links.

This module keeps the reference's global-registry ergonomics: call
:func:`initialize_model_parallel` once, then layers/schedules query axis
names and sizes from anywhere (including inside ``shard_map``-traced code,
where *rank* getters return traced ``axis_index`` values).

Virtual pipeline (interleaved 1F1B) carries over as a chunk count per stage
(reference virtual rank bookkeeping :100-107) — scheduling state, not mesh
state.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names (the reference's group names).
DATA_AXIS = "data"
PIPELINE_AXIS = "pipeline"
TENSOR_AXIS = "tensor"

#: Mesh-order axis tuple — the coordinate order of format-4 sharded
#: checkpoints and the linearized-world ZeRO layout.
MESH_AXES = (DATA_AXIS, PIPELINE_AXIS, TENSOR_AXIS)


@dataclasses.dataclass
class _ParallelState:
    mesh: Mesh
    tensor_model_parallel_size: int
    pipeline_model_parallel_size: int
    data_parallel_size: int
    virtual_pipeline_model_parallel_size: Optional[int] = None
    # mutable scheduling cursor used by the interleaved schedule, mirroring
    # get/set_virtual_pipeline_model_parallel_rank (reference :100-107)
    virtual_pipeline_model_parallel_rank: int = 0
    # host-side (tp, pp, dp) coordinates of this process's first mesh
    # device, precomputed once (get_rank_info is called per log record)
    rank_info: Tuple[int, int, int] = (0, 0, 0)


_STATE: Optional[_ParallelState] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build and register the global mesh (reference parallel_state.py:58).

    world = dp × pp × tp, with dp inferred from the device count exactly as
    the reference infers it from world size (:86-99).
    """
    global _STATE
    devs = list(devices if devices is not None else jax.devices())
    world = len(devs)
    tp, pp = tensor_model_parallel_size_, pipeline_model_parallel_size_
    if world % (tp * pp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tensor ({tp}) x "
            f"pipeline ({pp}) parallel sizes")
    dp = world // (tp * pp)
    if virtual_pipeline_model_parallel_size_ is not None and pp <= 2:
        raise RuntimeError(
            "pipeline-model-parallel size should be greater than 2 with "
            "interleaved schedule")
    mesh = Mesh(
        np.asarray(devs).reshape(dp, pp, tp),
        (DATA_AXIS, PIPELINE_AXIS, TENSOR_AXIS),
    )
    _STATE = _ParallelState(
        mesh=mesh,
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        data_parallel_size=dp,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size_,
        rank_info=_compute_rank_info(mesh),
    )
    return mesh


def model_parallel_is_initialized() -> bool:
    """Reference parallel_state.py:181-186."""
    return _STATE is not None


def _state() -> _ParallelState:
    if _STATE is None:
        raise RuntimeError("model parallel state is not initialized — call "
                           "initialize_model_parallel() first")
    return _STATE


def get_mesh() -> Mesh:
    return _state().mesh


# --- world sizes (static) ---------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return _state().tensor_model_parallel_size


def get_pipeline_model_parallel_world_size() -> int:
    return _state().pipeline_model_parallel_size


def get_data_parallel_world_size() -> int:
    return _state().data_parallel_size


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _state().virtual_pipeline_model_parallel_size


def mesh_axis_sizes() -> dict:
    """Ordered ``{axis name: size}`` of the registered mesh in
    :data:`MESH_AXES` order — the ``shard_axes`` mapping a format-4
    sharded save (:func:`apex_tpu.checkpoint.save_checkpoint`) and the
    telemetry mesh stamp want."""
    st = _state()
    return {DATA_AXIS: st.data_parallel_size,
            PIPELINE_AXIS: st.pipeline_model_parallel_size,
            TENSOR_AXIS: st.tensor_model_parallel_size}


# --- axis names (the "groups") ---------------------------------------------

def get_tensor_model_parallel_group() -> str:
    """The reference returns a ProcessGroup (:188); here the axis name is
    the group — pass it to any jax collective."""
    return TENSOR_AXIS


def get_pipeline_model_parallel_group() -> str:
    return PIPELINE_AXIS


def get_data_parallel_group() -> str:
    return DATA_AXIS


def get_model_parallel_groups() -> Tuple[str, str]:
    """Axes spanning the model-parallel block (TP × PP) — what the
    reference's amp GradScaler reduces found_inf over (grad_scaler.py:25-36)."""
    return (PIPELINE_AXIS, TENSOR_AXIS)


def get_embedding_axis() -> str:
    """The reference's embedding group ties word-embedding grads between the
    first and last pipeline stage (:143-167).  In SPMD the tie is a masked
    psum over the pipeline axis; this is that axis."""
    return PIPELINE_AXIS


# --- ranks (traced inside shard_map, 0 outside) -----------------------------

def _axis_rank(axis: str):
    try:
        return jax.lax.axis_index(axis)
    except NameError:
        return 0


def get_tensor_model_parallel_rank():
    """Inside shard_map-traced code: the traced TP coordinate of this device
    (reference :330).  Outside: 0."""
    return _axis_rank(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_AXIS)


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced predicate (reference :262-276). With virtual pipelining and
    ``ignore_virtual=False``, additionally requires virtual rank 0."""
    first = get_pipeline_model_parallel_rank() == 0
    st = _state()
    if (not ignore_virtual
            and st.virtual_pipeline_model_parallel_size is not None):
        first = first & (st.virtual_pipeline_model_parallel_rank == 0)
    return first


def is_pipeline_last_stage(ignore_virtual: bool = False):
    st = _state()
    last = get_pipeline_model_parallel_rank() == st.pipeline_model_parallel_size - 1
    if (not ignore_virtual
            and st.virtual_pipeline_model_parallel_size is not None):
        last = last & (st.virtual_pipeline_model_parallel_rank
                       == st.virtual_pipeline_model_parallel_size - 1)
    return last


def get_virtual_pipeline_model_parallel_rank() -> int:
    return _state().virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    _state().virtual_pipeline_model_parallel_rank = rank


def get_tensor_model_parallel_src_rank() -> int:
    """Reference :349-355 computes the global rank of tp-rank-0 in one's TP
    group for broadcast_data; with a mesh the source is simply tp index 0."""
    return 0


def get_rank_info() -> Tuple[int, int, int]:
    """(tp, pp, dp) rank triple for log records (reference :169-178).

    Host-side (outside traced code) a process owns a *block* of mesh
    coordinates, not a single rank; reports the coordinates of the first
    mesh device this process owns — in multi-host runs that is the
    process's real (tp, pp, dp) position, and on a single host it is
    (0, 0, 0) like the reference's rank-0 logs.  Precomputed at
    :func:`initialize_model_parallel` (called per log record)."""
    if _STATE is None:
        return (0, 0, 0)
    return _STATE.rank_info


def _compute_rank_info(mesh: Mesh) -> Tuple[int, int, int]:
    pid = jax.process_index()
    arr = np.asarray(mesh.devices)
    for idx in np.ndindex(arr.shape):
        if arr[idx].process_index == pid:
            dp_i, pp_i, tp_i = idx
            return (int(tp_i), int(pp_i), int(dp_i))
    return (0, 0, pid)


def tensor_parallel_mesh(tp: Optional[int] = None) -> Mesh:
    """One-axis ``Mesh`` over :data:`TENSOR_AXIS` — the tp-sharded
    serving engine's mesh (its ``shard_map`` bodies ``psum`` over this
    axis; r17, docs/serving.md "Tensor-parallel serving").

    With the global model-parallel state initialized, the serving mesh
    is the FIRST tensor group of the registered 3-D mesh: same devices,
    same axis name, so the serving engine and the training stack agree
    on what "tensor" means and the HLO contract vocabulary is shared.
    ``tp``, when given, must then match the registered tensor world
    size.  Uninitialized, ``tp`` is required and the mesh takes the
    first ``tp`` local devices.
    """
    if model_parallel_is_initialized():
        st = _state()
        world = st.tensor_model_parallel_size
        if tp is not None and tp != world:
            raise ValueError(
                f"tp={tp} does not match the initialized tensor-"
                f"parallel world size {world}")
        devs = np.asarray(st.mesh.devices).reshape(-1, world)[0]
        return Mesh(devs, (TENSOR_AXIS,))
    if tp is None:
        raise ValueError(
            "tp is required when model-parallel state is uninitialized")
    devs = jax.devices()
    if len(devs) < tp:
        raise ValueError(f"tp={tp} needs {tp} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:tp]), (TENSOR_AXIS,))


def destroy_model_parallel() -> None:
    """Reference :373-396."""
    global _STATE
    _STATE = None


@contextlib.contextmanager
def uninitialized_scope():
    """Temporarily hide the global model-parallel state.

    Inside the ``with`` block :func:`model_parallel_is_initialized` is
    False and :func:`tensor_parallel_mesh` builds from the first local
    devices; on exit the previous state (if any) is restored untouched.

    This exists for consumers that must construct a FIXED canonical
    geometry regardless of what a surrounding training process has
    registered — chiefly ``apex_tpu.analysis.registry``, whose HLO
    contracts pin the cpu-toy serving mesh and must lower identically
    whether invoked from a fresh CLI process or mid-suite after a test
    initialized an unrelated mesh.
    """
    global _STATE
    saved, _STATE = _STATE, None
    try:
        yield
    finally:
        _STATE = saved
