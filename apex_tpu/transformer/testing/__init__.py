"""apex_tpu.transformer.testing — reference Megatron models and helpers
(reference apex/transformer/testing/)."""

from apex_tpu.transformer.testing.standalone_bert import (  # noqa: F401
    BertConfig,
    BertModel,
    bert_model_provider,
)
from apex_tpu.transformer.testing.train_loop import (  # noqa: F401
    LoopResult,
    run_resilient_training,
)
from apex_tpu.transformer.testing.standalone_gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    ParallelAttention,
    ParallelMLP,
    ParallelTransformer,
    ParallelTransformerLayer,
    gpt_model_provider,
    make_gpt_stage_fns,
)
