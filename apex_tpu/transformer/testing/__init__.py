"""apex_tpu.transformer.testing — reference Megatron models and helpers
(reference apex/transformer/testing/)."""

from apex_tpu.transformer.testing.standalone_bert import (  # noqa: F401
    BertConfig,
    BertModel,
    bert_model_provider,
)
from apex_tpu.transformer.testing.train_loop import (  # noqa: F401
    LoopResult,
    run_resilient_training,
)
from apex_tpu.transformer.testing.flagship import (  # noqa: F401
    FIT_PLANS,
    FlagshipSetup,
    ZeroFitPlan,
    build_flagship_train_step,
    flagship_elastic_build,
    flagship_state_bytes,
    gpt1p3b_config,
    gpt_param_count,
)
from apex_tpu.transformer.testing.standalone_gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    ParallelAttention,
    ParallelMLP,
    ParallelTransformer,
    ParallelTransformerLayer,
    gpt_model_provider,
    make_gpt_stage_fns,
)
