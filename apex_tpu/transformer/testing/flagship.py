"""GPT-1.3B-class flagship: configuration + ZeRO-fit train step.

The benched flagship was pinned for five rounds to GPT-350M (h=1024,
16 heads → d=64), a shape whose head dim half-fills the MXU contraction
lanes and caps attention at the measured 54.9 TF dot floor (BASELINE.md
r5).  This module stands up the shape the hardware likes — **h=2048,
16 heads → d=128, seq 2048** (~1.32 B params with the 51200 vocab) —
as a first-class configuration, plus the memory-fit machinery a 1.3B
model needs on a 16 GB chip.

Following ZeRO (Rajbhandari et al., 2020), the train step wires
:class:`apex_tpu.contrib.optimizers.DistributedFusedAdam` — psum_scatter
→ sharded update → all_gather — over the mesh "data" axis, so fp32
moments live once per shard group instead of once per replica.  The
same step runs unchanged from 1 chip (world=1: the collectives are
identity and the *dtype plan* does the fitting) to a v5e-16 pod slice
(world=N: state is N-way sharded as well).  Since ISSUE 15 the
mesh_shape=(dp, tp, pp) step defaults to the **bucketed-overlap**
data path — per-bucket reduce-scatter/all-gather over partial grads,
``step_buckets`` + :func:`apex_tpu.multi_tensor.plan_buckets` — see
:func:`build_flagship_train_step`'s ``bucket_bytes`` notes and
docs/performance.md "Overlap-aware ZeRO".

Fit plans — why a 15.75-GiB (16.9e9-byte) chip needs one (1.32 B
params; bytes in GB, world=1):

=============  ======  =====  =========  ==================  ========
plan           params  grads  m / v      optimizer-phase     fits?
                                         peak (see note)
=============  ======  =====  =========  ==================  ========
fp32           5.3     5.3    5.3 / 5.3  26.4 GB             no
bf16_fp32m     2.6     2.6    5.3 / 5.3  18.5 GB             no
bf16_fit       2.6     2.6    2.6 / 5.3  15.8 GB             yes
=============  ======  =====  =========  ==================  ========

Peak note: the ZeRO step packs grads and params into flat superblocks,
so the optimizer-phase live set is m + v + flat grads + 2× flat params
(old tree and grad tree freed by donation — ``donate=True`` below is
load-bearing, not an optimization).  :func:`flagship_state_bytes`
computes both columns; BASELINE.md (gpt1p3b section) carries the full
table with the measured counterpart from the chip.

``bf16_fit`` keeps the variance (the adaptive step size) fp32 and
narrows params/grads/momentum to bf16; the update math itself always
runs fp32 inside the fused elementwise chain (see
``contrib/optimizers/distributed_fused.py``).  Parity vs the unsharded
fp32 FusedAdam is asserted on the emulated mesh in
``tests/L0/test_flagship.py`` (max|dw| ≤ 1e-3 — ISSUE 2 acceptance).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.multi_tensor.buckets import DEFAULT_BUCKET_BYTES, plan_buckets
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing.standalone_gpt import GPTConfig, GPTModel

__all__ = [
    "GPT1P3B_KW",
    "ZeroFitPlan",
    "FIT_PLANS",
    "gpt1p3b_config",
    "gpt_param_count",
    "flagship_state_bytes",
    "build_flagship_train_step",
    "flagship_elastic_build",
    "FlagshipSetup",
]


# The flagship shape (ISSUE 2): 16 heads at h=2048 give d=128 — full MXU
# contraction-lane fill, the regime where the flash kernels measure
# 0.67 of roof (BENCH_r05 flash_attention_s4096) vs 0.90-of-a-54.9-TF-
# floor at d=64.  Block 256: the packed-QKV kernels' whole-sequence
# working set at 3·128 lanes exceeds the VMEM budget at the 512 library
# default but fits at 256 (ops.attention._qkv_packed_block shrinks
# automatically; the config pins it so the routing is explicit).
GPT1P3B_KW = dict(
    num_layers=24,
    hidden_size=2048,
    num_attention_heads=16,
    vocab_size=51200,
    max_position_embeddings=2048,
    bf16=True,
    use_flash_attention=True,
    remat=True,
    remat_policy="attn_res",
    flash_block_q=256,
    flash_block_k=256,
)


def gpt1p3b_config(**overrides) -> GPTConfig:
    """The 1.3B flagship :class:`GPTConfig`; ``overrides`` for toy-depth
    test/trajectory variants (keep ``hidden_size / num_attention_heads
    = 128`` when shrinking, so the d=128 kernel routing stays the one
    under test)."""
    return GPTConfig(**{**GPT1P3B_KW, **overrides})


def gpt_param_count(cfg: GPTConfig) -> int:
    """Analytic parameter count of the standalone GPT (biases and
    layernorms included): per layer 12h² GEMM weights + 13h vectors,
    plus word/position embeddings and the final layernorm."""
    h, L = cfg.hidden_size, cfg.num_layers
    per_layer = 12 * h * h + 13 * h
    return (L * per_layer
            + (cfg.vocab_size + cfg.max_position_embeddings) * h
            + 2 * h)


@dataclasses.dataclass(frozen=True)
class ZeroFitPlan:
    """Storage dtypes for the ZeRO step (see module table)."""

    name: str
    param_dtype: Any
    exp_avg_dtype: Any
    scatter_dtype: Optional[Any]  # flat-grad / reduce-scatter transport
    gather_dtype: Optional[Any]   # updated-shard all_gather transport


FIT_PLANS = {
    # fp32 everything — the r5 350M construction; does NOT fit 1.3B on
    # one 16 GB chip (kept for parity tests and small models)
    "fp32": ZeroFitPlan("fp32", jnp.float32, jnp.float32, None, None),
    # bf16 params/transport, both moments fp32 — 15.8 GB of state+grads
    # at 1.3B: still over the single-chip budget, fits at world ≥ 2
    "bf16_fp32m": ZeroFitPlan("bf16_fp32m", jnp.bfloat16, jnp.float32,
                              jnp.bfloat16, jnp.bfloat16),
    # the single-chip 1.3B fit: bf16 momentum as well; variance stays
    # fp32 (it IS the adaptive step size — see distributed_fused.py)
    "bf16_fit": ZeroFitPlan("bf16_fit", jnp.bfloat16, jnp.bfloat16,
                            jnp.bfloat16, jnp.bfloat16),
}


def flagship_state_bytes(cfg: GPTConfig, plan: ZeroFitPlan,
                         n_shards: int = 1) -> dict:
    """Analytic persistent-state + grad bytes for the fitting table
    (BASELINE.md gpt1p3b section); activations/logits excluded."""
    n = gpt_param_count(cfg)
    it = lambda d: jnp.dtype(d).itemsize
    out = {
        "params": n * it(plan.param_dtype),
        "grads": n * it(plan.scatter_dtype or jnp.float32),
        "exp_avg": n * it(plan.exp_avg_dtype) // n_shards,
        "exp_avg_sq": n * 4 // n_shards,
    }
    out["total"] = sum(out.values())
    # optimizer-phase live set (module docstring "peak note"): with the
    # param and grad TREES donated/freed, the step holds moments + the
    # flat grad buffer + old and new flat param buffers at once
    flat_param = n * it(plan.gather_dtype or jnp.float32)
    out["step_peak"] = (out["exp_avg"] + out["exp_avg_sq"]
                        + out["grads"] + 2 * flat_param)
    return out


class FlagshipSetup(NamedTuple):
    """Everything the bench/tests need from one flagship construction."""

    step: Any          # jitted (params, opt_state, tokens, labels) -> …
    params: Any        # pytree in plan.param_dtype
    opt_state: Any     # per-rank ZeRO state, leading [n_shards] axis
    mesh: Any
    schema: Any
    opt: DistributedFusedAdam
    model: GPTModel
    plan: ZeroFitPlan
    # structure-prefix PartitionSpecs for the (params, opt_state) state
    # tuple: params replicated, every opt_state leaf led by the "data"
    # axis — exactly what save_checkpoint(shard_axis="data") needs to
    # write per-rank partition files (resilience/elastic.py).  On a 3-D
    # mesh the opt_state spec leads with all three axes and mesh_axes
    # carries the {"data": dp, "pipeline": pp, "tensor": tp} mapping a
    # format-4 save (shard_axes=) wants.
    shardings: Any = None
    mesh_axes: Any = None
    # the ISSUE 15 bucketed-overlap plan the 3-D step compiled with
    # (None on the single-axis path and the legacy serialized control)
    bucket_plan: Any = None


def build_flagship_train_step(
    cfg: GPTConfig,
    *,
    plan: str | ZeroFitPlan = "bf16_fit",
    lr: float = 1e-4,
    weight_decay: float = 0.0,
    devices: Optional[Sequence] = None,
    donate: bool = True,
    seed: int = 0,
    mesh_shape: Optional[Sequence[int]] = None,
    bucket_bytes: Any = "auto",
) -> FlagshipSetup:
    """One flagship construction: model + ZeRO-sharded FusedAdam over
    the "data" axis of a fresh ``parallel_state`` mesh spanning
    ``devices`` (default: all local devices — 1 on a single chip, 8 on
    the emulated CPU mesh).

    The returned ``step(params, opt_state, tokens, labels)`` expects the
    GLOBAL batch (sharded over "data" internally; batch must divide the
    data-parallel size) and returns ``(params, opt_state, loss)`` with
    params bitwise-replicated across ranks.  ``donate=True`` donates
    params and optimizer state — at 1.3B the old buffers ARE the fit
    margin.

    ``mesh_shape=(dp, tp, pp)`` — multi-axis form (ISSUE 6): the mesh
    carries all three ``parallel_state`` axes, tensor parallelism
    shards the *compute* (each device runs its tp-rank's slice of the
    replicated master params, taken with a traced ``dynamic_slice``
    inside the step), and ZeRO shards the optimizer state over the
    **linearized world** — every (d, p, t) coordinate owns one
    contiguous shard of the master flat buffer, so the opt_state leaves
    are ``[dp, pp, tp, shard]`` stacks with spec
    ``P("data", "pipeline", "tensor")``.  ``pp`` must be 1 for the
    *train step* (pipeline schedules stay in ``bench_gpt_3d``'s
    pipeline segment; the checkpoint / reshard machinery handles
    pp > 1 states).  ``mesh_shape=None`` keeps the historical
    single-axis layout byte-for-byte.

    ``bucket_bytes`` (3-D path only, ISSUE 15) selects the gradient
    data path:

    * ``"auto"`` (default) — the **bucketed-overlap ZeRO step**: the
      grad of the device-local mean loss is taken *inside* the
      shard_map region (per-device partial grads, no boundary
      all-reduces), and the flat buffer moves through one
      reduce-scatter + all-gather **per bucket**
      (:func:`apex_tpu.multi_tensor.plan_buckets` at
      :data:`~apex_tpu.multi_tensor.DEFAULT_BUCKET_BYTES`), so XLA's
      latency-hiding scheduler interleaves collectives with
      backward/optimizer compute instead of queueing one
      buffer-sized transfer per direction behind a wall of per-leaf
      grad all-reduces.  The mesh-sum of the partials is exactly
      ``world ×`` the data-mean grad — the same normalization the
      serialized path sees from ``world`` replicated copies — and
      the optimizer-state layout is canonical for every plan
      (buckets are per-rank shard spans; multi_tensor/buckets.py),
      so checkpoints reshard identically.  Parity vs the serialized
      control is pinned in tests/L0/test_bucketed_zero.py.
    * an ``int`` — same step at that bucket cap (a cap at or above
      the buffer size is the one-bucket edge: the serialized
      collective tail on the new data path).
    * ``None`` — the **legacy serialized control**: grads taken
      through the shard_map boundary (per-leaf all-reduces of the
      replicated master grad) feeding one monolithic mesh-wide
      ``psum_scatter``/``all_gather`` — kept as the contract-checker
      negative control and the pre-r15 construction.
    """
    if isinstance(plan, str):
        plan = FIT_PLANS[plan]
    devs = list(devices if devices is not None else jax.devices())
    if mesh_shape is not None:
        return _build_flagship_train_step_3d(
            cfg, plan=plan, lr=lr, weight_decay=weight_decay, devs=devs,
            donate=donate, seed=seed, mesh_shape=tuple(mesh_shape),
            bucket_bytes=bucket_bytes)
    if bucket_bytes != "auto":
        raise ValueError(
            "bucket_bytes applies to the mesh_shape=(dp, tp, pp) step; "
            "the single-axis path keeps the historical layout")
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(1, 1, devices=devs)
    n_shards = len(devs)

    model = GPTModel(cfg)
    params = model.shard_master(model.init_master(jax.random.PRNGKey(seed)),
                                0)
    params = jax.tree_util.tree_map(
        lambda a: a.astype(plan.param_dtype), params)

    opt = DistributedFusedAdam(
        lr=lr, weight_decay=weight_decay,
        scatter_dtype=plan.scatter_dtype,
        gather_dtype=plan.gather_dtype,
        exp_avg_dtype=plan.exp_avg_dtype)
    schema = opt.make_schema(params, n_shards)
    state0 = opt.init(params, schema, n_shards)
    # per-rank state with an explicit leading shard axis (every rank's
    # init is zeros, so a broadcast is exact)
    opt_state = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n_shards, *a.shape)), state0)

    def inner(p, state, tokens, labels):
        state = jax.tree_util.tree_map(lambda a: a[0], state)

        def lossf(p):
            return jnp.mean(model.apply(p, tokens, labels=labels))

        loss, grads = jax.value_and_grad(lossf)(p)
        new_p, new_state = opt.step(grads, state, p, schema)
        loss = jax.lax.pmean(loss, opt.axis_name)
        return (new_p,
                jax.tree_util.tree_map(lambda a: a[None], new_state),
                loss)

    sharded = shard_map(
        inner, mesh=mesh,
        in_specs=(P(), P("data"), P("data"), P("data")),
        out_specs=(P(), P("data"), P()),
        check_rep=False)
    step = jax.jit(sharded,
                   donate_argnums=(0, 1) if donate else ())
    return FlagshipSetup(step, params, opt_state, mesh, schema, opt,
                         model, plan, shardings=(P(), P("data")))


def _tp_slice_tables(master, local0):
    """Static per-leaf (dim, size) tables for the traced tp slice:
    compare master leaf shapes against tp-rank-0's ``shard_master``
    output — equal shape means replicated (sentinel dim -1); otherwise
    exactly one dim shrinks, and rank r's slice starts at ``r * size``
    along it (the contiguous-equal-chunk contract every
    ``tensor_parallel`` layer's ``shard_master`` follows)."""
    def _dim(m, l):
        if m.shape == l.shape:
            return -1
        if m.ndim != l.ndim:
            raise ValueError(
                f"shard_master changed rank: {m.shape} -> {l.shape}")
        diffs = [i for i, (a, b) in enumerate(zip(m.shape, l.shape))
                 if a != b]
        if len(diffs) != 1:
            raise ValueError(
                f"shard_master slices more than one dim: {m.shape} -> "
                f"{l.shape} — the traced tp slice cannot express this")
        return diffs[0]

    dims = jax.tree_util.tree_map(_dim, master, local0)
    sizes = jax.tree_util.tree_map(
        lambda l, d: int(l.shape[d]) if d >= 0 else 0, local0, dims)
    return dims, sizes


def _build_flagship_train_step_3d(cfg, *, plan, lr, weight_decay, devs,
                                  donate, seed, mesh_shape,
                                  bucket_bytes="auto"):
    """The mesh_shape=(dp, tp, pp) body of
    :func:`build_flagship_train_step` (see its docstring for the
    layout contract and the ``bucket_bytes`` data-path selector)."""
    dp, tp, pp = (int(x) for x in mesh_shape)
    if pp != 1:
        raise NotImplementedError(
            "the 3-D flagship train step supports pp=1 (pipeline "
            "schedules live in the dryrun legs); checkpoint/reshard "
            "machinery handles pp > 1 states")
    world = dp * tp * pp
    if world != len(devs):
        raise ValueError(
            f"mesh_shape {mesh_shape} needs {world} devices, got "
            f"{len(devs)}")
    if cfg.num_attention_heads % tp or cfg.hidden_size % tp \
            or cfg.vocab_size % tp:
        raise ValueError(
            f"tp={tp} must divide heads/hidden/vocab "
            f"({cfg.num_attention_heads}/{cfg.hidden_size}/"
            f"{cfg.vocab_size})")
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(tp, pp, devices=devs)

    cfg_tp = dataclasses.replace(cfg, tp_size=tp)
    model = GPTModel(cfg_tp)
    master = jax.tree_util.tree_map(
        lambda a: a.astype(plan.param_dtype),
        model.init_master(jax.random.PRNGKey(seed)))
    local0 = model.shard_master(master, 0)
    slice_dims, slice_sizes = _tp_slice_tables(master, local0)

    def _slice_tp(mp, t_idx):
        return jax.tree_util.tree_map(
            lambda m, d, n: m if d < 0 else jax.lax.dynamic_slice_in_dim(
                m, t_idx * n, n, axis=d),
            mp, slice_dims, slice_sizes)

    opt = DistributedFusedAdam(
        lr=lr, weight_decay=weight_decay,
        scatter_dtype=plan.scatter_dtype,
        gather_dtype=plan.gather_dtype,
        exp_avg_dtype=plan.exp_avg_dtype,
        axis_name=tuple(parallel_state.MESH_AXES))
    schema = opt.make_schema(master, world)
    state0 = opt.init(master, schema, world)
    opt_state = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None, None, None],
                                   (dp, pp, tp, *a.shape)), state0)
    spec3 = P(*parallel_state.MESH_AXES)
    mesh_axes = {parallel_state.DATA_AXIS: dp,
                 parallel_state.PIPELINE_AXIS: pp,
                 parallel_state.TENSOR_AXIS: tp}

    if bucket_bytes is not None:
        # -- the bucketed-overlap ZeRO step (ISSUE 15, the default) ----
        # The whole step is ONE shard_map region.  The grad of the
        # device-local mean loss is taken INSIDE it: under the
        # unreplicated-cotangent convention (check_rep=False transposes
        # ``psum`` to ``psum``) the per-device partial grads carry a
        # uniform ×tp from the model's tensor-parallel activation
        # reductions, so their mesh-sum is tp·dp = world × the
        # data-mean grad — exactly the normalization the serialized
        # path sees from ``world`` replicated copies, and
        # ``grad_average`` divides the same ``world`` back out.  What
        # this buys: the per-leaf boundary all-reduces of a replicated
        # master grad never exist (8.2× less all-reduce traffic at the
        # toy contracts geometry), and the grad sum happens in the
        # per-bucket reduce-scatters the latency-hiding scheduler can
        # interleave with backward/optimizer compute.  Collective
        # inventory + end-to-end donation are machine-checked against
        # hlo_contracts.json (`python -m apex_tpu.analysis hlo`).
        bb = DEFAULT_BUCKET_BYTES if bucket_bytes == "auto" \
            else int(bucket_bytes)
        bplan = plan_buckets(
            schema, world, bucket_bytes=bb,
            itemsize=jnp.dtype(plan.scatter_dtype or jnp.float32).itemsize)

        def _bucketed_zero_inner(mp, state, tokens, labels):
            state = jax.tree_util.tree_map(lambda a: a[0, 0, 0], state)
            t_idx = jax.lax.axis_index(parallel_state.TENSOR_AXIS)

            def local_loss(mp):
                return jnp.mean(model.apply(_slice_tp(mp, t_idx), tokens,
                                            labels=labels))

            loss, grads = jax.value_and_grad(local_loss)(mp)
            loss = jax.lax.pmean(loss, parallel_state.DATA_AXIS)
            new_p, new_state = opt.step_buckets(grads, state, mp, schema,
                                                bplan)
            return (new_p,
                    jax.tree_util.tree_map(
                        lambda a: a[None, None, None], new_state),
                    loss)

        sharded = shard_map(
            _bucketed_zero_inner, mesh=mesh,
            in_specs=(P(), spec3, P("data"), P("data")),
            out_specs=(P(), spec3, P()),
            check_rep=False)
        step = jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
        return FlagshipSetup(
            step, master, opt_state, mesh, schema, opt, model, plan,
            shardings=(P(), spec3), mesh_axes=mesh_axes,
            bucket_plan=bplan)

    # -- the legacy serialized control (bucket_bytes=None) -------------
    # The grad is taken OUTSIDE the shard_map.  Inside a
    # check_rep=False region jax transposes ``psum`` to ``psum``
    # (the unreplicated-cotangent convention), so differentiating
    # through the model's tensor-parallel reductions *inside* the
    # region scales cotangents by the axis size — loss comes out right
    # and every grad is ×tp (measured, exactly; the bucketed step
    # above RELIES on that uniform factor).  Differentiating through
    # the shard_map boundary instead uses its true adjoints end-to-end
    # — the convention tensor_parallel/mappings.py documents and
    # tests/L0/test_tensor_parallel.py's col→row grad-parity case
    # pins.  The outer grads arrive replicated (the global master
    # grad), so the opt step needs no data-average: the mesh-wide
    # psum_scatter sums world identical copies and grad_average
    # divides them back out (exact for power-of-two worlds).  The
    # price — per-leaf boundary all-reduces, then one monolithic
    # scatter/gather pair strictly after the whole backward — is the
    # serialized inventory the ratcheted hlo contract now REJECTS
    # (tests/L0/test_hlo_contracts.py keeps this path as the negative
    # control).
    def inner_fwd(mp, tokens, labels):
        t_idx = jax.lax.axis_index(parallel_state.TENSOR_AXIS)
        loss = jnp.mean(model.apply(_slice_tp(mp, t_idx), tokens,
                                    labels=labels))
        return jax.lax.pmean(loss, parallel_state.DATA_AXIS)

    loss_fn = shard_map(
        inner_fwd, mesh=mesh,
        in_specs=(P(), P("data"), P("data")), out_specs=P(),
        check_rep=False)

    def inner_opt(grads, state, mp):
        state = jax.tree_util.tree_map(lambda a: a[0, 0, 0], state)
        new_p, new_state = opt.step(grads, state, mp, schema)
        return new_p, jax.tree_util.tree_map(
            lambda a: a[None, None, None], new_state)

    opt_sharded = shard_map(
        inner_opt, mesh=mesh,
        in_specs=(P(), spec3, P()), out_specs=(P(), spec3),
        check_rep=False)

    def train_step(mp, state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(mp, tokens, labels)
        new_p, new_state = opt_sharded(grads, state, mp)
        return new_p, new_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1) if donate else ())
    return FlagshipSetup(
        step, master, opt_state, mesh, schema, opt, model, plan,
        shardings=(P(), spec3), mesh_axes=mesh_axes)


def flagship_elastic_build(cfg: GPTConfig, *, plan: str | ZeroFitPlan
                           = "bf16_fit", lr: float = 1e-4,
                           seed: int = 0, donate: bool = False,
                           on_loss=None, bucket_bytes="auto"):
    """``build(devices)`` factory for
    :func:`apex_tpu.resilience.run_elastic_training`: each call stands up
    the ZeRO flagship step on exactly ``devices`` (a fresh mesh whose
    "data" axis spans them) and adapts it to the resilient-loop contract
    — ``state`` is the ``(params, opt_state)`` tuple (leading
    ``[len(devices)]`` shard axis on every opt leaf, so it doubles as
    the cross-topology restore target) and ``step_fn(state, (tokens,
    labels))`` returns ``(state, None)``.  ``on_loss(step_loss)`` taps
    the per-step loss for trajectory assertions.

    ``build(devices, mesh_shape=(dp, tp, pp))`` — the multi-axis form
    the 3-D elastic harness calls: the step builds over the full
    dp×tp×pp ``parallel_state`` mesh and the opt leaves carry
    ``[dp, pp, tp, shard]`` stacks (see
    :func:`build_flagship_train_step`'s ``mesh_shape`` notes)."""

    def build(devices, mesh_shape=None):
        fs = build_flagship_train_step(
            cfg, plan=plan, lr=lr, devices=list(devices), seed=seed,
            donate=donate, mesh_shape=mesh_shape,
            bucket_bytes=bucket_bytes if mesh_shape is not None
            else "auto")

        def step_fn(state, batch):
            p, s = state
            tokens, labels = batch
            p, s, loss = fs.step(p, s, tokens, labels)
            if on_loss is not None:
                on_loss(float(loss))
            return (p, s), None

        return step_fn, (fs.params, fs.opt_state), fs.shardings

    return build
