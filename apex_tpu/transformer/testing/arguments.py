"""Megatron-style argument parsing (reference
apex/transformer/testing/arguments.py:23-806), adapted to the TPU runtime.

Same structure: grouped ``_add_*_args`` builders, ``parse_args`` with
cross-argument consistency checks and world-size-derived defaults. TPU
deltas, each deliberate:

- world size comes from ``jax.device_count()`` (or --world-size for
  emulated meshes), not RANK/WORLD_SIZE env (reference arguments.py:56-58);
- ``--bf16`` is the native half type; ``--fp16`` keeps the reference
  loss-scaling semantics for parity runs;
- ``params_dtype`` is a jnp dtype; bf16 forces fp32 grad accumulation
  exactly as the reference does (arguments.py:149-158);
- DDP_impl/contiguous-buffer knobs are accepted but meaningless under XLA
  (flagged in help) — kept so reference scripts parse unchanged.

All of the reference's argument groups are present — including the
autoresume, biencoder (ICT/retriever), and ViT groups (reference
arguments.py:725-806), added in r7 so "reference scripts parse
unchanged" holds for the full flag surface, not just the transformer
subset.  The autoresume flags are parse-surface only: ADLR's SLURM
autoresume daemon has no TPU analog (the resilience layer's
GracePeriodHandler + async checkpointing covers preemption instead,
apex_tpu/resilience/), and the biencoder/ViT flags configure models the
testing tier does not instantiate — they exist so reference launch
scripts run unmodified, and each help string says so.
"""

from __future__ import annotations

import argparse
import os
from typing import Callable, Optional

import jax.numpy as jnp


def parse_args(extra_args_provider: Optional[Callable] = None, defaults: dict = {},
               ignore_unknown_args: bool = False, args=None):
    """Parse all arguments (reference arguments.py:23-280)."""
    parser = argparse.ArgumentParser(description="apex_tpu Megatron Arguments",
                                     allow_abbrev=False)
    parser = _add_network_size_args(parser)
    parser = _add_regularization_args(parser)
    parser = _add_training_args(parser)
    parser = _add_initialization_args(parser)
    parser = _add_learning_rate_args(parser)
    parser = _add_checkpointing_args(parser)
    parser = _add_mixed_precision_args(parser)
    parser = _add_distributed_args(parser)
    parser = _add_validation_args(parser)
    parser = _add_data_args(parser)
    parser = _add_autoresume_args(parser)
    parser = _add_biencoder_args(parser)
    parser = _add_vit_args(parser)
    parser = _add_logging_args(parser)
    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)
    return _validate_and_derive(parsed, defaults)


def _validate_and_derive(args, defaults):
    """The consistency-check block (reference arguments.py:55-280)."""
    # world size: explicit flag (emulated mesh) > device count
    if args.world_size is None:
        try:
            import jax

            args.world_size = jax.device_count()
        except Exception:
            args.world_size = 1
    args.rank = int(os.getenv("RANK", "0"))

    assert args.tensor_model_parallel_size >= 1, (
        f"tensor model parallel size "
        f"({args.tensor_model_parallel_size}) must be >= 1")
    args.tensor_model_parallel_size = min(
        args.tensor_model_parallel_size, args.world_size)
    assert args.world_size % args.tensor_model_parallel_size == 0, (
        f"world size ({args.world_size}) is not divisible by tensor model "
        f"parallel size ({args.tensor_model_parallel_size})")
    args.pipeline_model_parallel_size = min(
        args.pipeline_model_parallel_size,
        args.world_size // args.tensor_model_parallel_size)
    model_parallel_size = (
        args.pipeline_model_parallel_size * args.tensor_model_parallel_size)
    assert args.world_size % model_parallel_size == 0, (
        f"world size ({args.world_size}) is not divisible by tensor parallel "
        f"size ({args.tensor_model_parallel_size}) times pipeline parallel "
        f"size ({args.pipeline_model_parallel_size})")
    args.data_parallel_size = args.world_size // model_parallel_size

    # user-supplied defaults only fill unset (None) args — reference :108-120
    for key, val in defaults.items():
        if getattr(args, key, None) is None:
            setattr(args, key, val)

    # batch sizes — reference :122-130
    assert args.micro_batch_size is not None and args.micro_batch_size > 0
    if args.global_batch_size is None:
        args.global_batch_size = args.micro_batch_size * args.data_parallel_size
    assert args.global_batch_size > 0
    assert args.global_batch_size % (
        args.micro_batch_size * args.data_parallel_size) == 0

    # virtual pipeline — reference :131-141
    if args.num_layers_per_virtual_pipeline_stage is not None:
        assert args.pipeline_model_parallel_size > 2, (
            "pipeline-model-parallel size should be greater than 2 with "
            "interleaved schedule")
        assert args.num_layers % args.num_layers_per_virtual_pipeline_stage == 0
        args.virtual_pipeline_model_parallel_size = (
            args.num_layers // args.pipeline_model_parallel_size
        ) // args.num_layers_per_virtual_pipeline_stage
    else:
        args.virtual_pipeline_model_parallel_size = None

    # params dtype — reference :145-163; TPU-native half is bf16
    assert not (args.fp16 and args.bf16)
    args.params_dtype = jnp.float32
    if args.fp16:
        args.params_dtype = jnp.float16
    if args.bf16:
        args.params_dtype = jnp.bfloat16
        # bf16 grads accumulate/all-reduce in fp32 (reference :152-158)
        args.accumulate_allreduce_grads_in_fp32 = True

    if args.lr is not None and args.min_lr is not None:
        assert args.min_lr <= args.lr
    if args.lr_warmup_fraction is not None:
        assert args.lr_warmup_iters == 0, (
            "can only specify one of lr-warmup-fraction and lr-warmup-iters")
    if args.save_interval is not None:
        assert args.save is not None, "--save-interval needs --save"
    for req in ("hidden_size", "num_attention_heads"):
        assert getattr(args, req) is not None, f"--{req.replace('_', '-')} is required"
    assert args.hidden_size % args.num_attention_heads == 0
    # derived network sizes (reference arguments.py network-size defaults)
    if args.ffn_hidden_size is None:
        args.ffn_hidden_size = 4 * args.hidden_size
    if args.kv_channels is None:
        args.kv_channels = args.hidden_size // args.num_attention_heads
    if args.seq_length is not None and args.max_position_embeddings is not None:
        assert args.max_position_embeddings >= args.seq_length
    if args.fp32_residual_connection:
        assert args.fp16 or args.bf16

    args.consumed_train_samples = 0
    args.consumed_valid_samples = 0
    return args


def _add_network_size_args(parser):
    group = parser.add_argument_group(title="network size")
    group.add_argument("--num-layers", type=int, default=None)
    group.add_argument("--hidden-size", type=int, default=None)
    group.add_argument("--ffn-hidden-size", type=int, default=None,
                       help="defaults to 4*hidden-size")
    group.add_argument("--num-attention-heads", type=int, default=None)
    group.add_argument("--kv-channels", type=int, default=None)
    group.add_argument("--max-position-embeddings", type=int, default=None)
    group.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    group.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    group.add_argument("--apply-residual-connection-post-layernorm",
                       action="store_true")
    group.add_argument("--openai-gelu", action="store_true")
    group.add_argument("--onnx-safe", type=bool, default=None)
    return parser


def _add_regularization_args(parser):
    group = parser.add_argument_group(title="regularization")
    group.add_argument("--attention-dropout", type=float, default=0.1)
    group.add_argument("--hidden-dropout", type=float, default=0.1)
    group.add_argument("--weight-decay", type=float, default=0.01)
    group.add_argument("--clip-grad", type=float, default=1.0)
    group.add_argument("--adam-beta1", type=float, default=0.9)
    group.add_argument("--adam-beta2", type=float, default=0.999)
    group.add_argument("--adam-eps", type=float, default=1e-8)
    group.add_argument("--sgd-momentum", type=float, default=0.9)
    return parser


def _add_training_args(parser):
    group = parser.add_argument_group(title="training")
    group.add_argument("--micro-batch-size", type=int, default=None)
    group.add_argument("--global-batch-size", type=int, default=None)
    group.add_argument("--rampup-batch-size", nargs="*", default=None,
                       help="<start batch size> <increment> <ramp-up samples>")
    group.add_argument("--train-iters", type=int, default=None)
    group.add_argument("--train-samples", type=int, default=None)
    group.add_argument("--log-interval", type=int, default=100)
    group.add_argument("--exit-interval", type=int, default=None)
    group.add_argument("--tensorboard-dir", type=str, default=None)
    group.add_argument("--activations-checkpoint-method", type=str,
                       choices=["uniform", "block"], default=None)
    group.add_argument("--activations-checkpoint-num-layers", type=int, default=1)
    group.add_argument("--distribute-checkpointed-activations",
                       action="store_true")
    group.add_argument("--optimizer", type=str, default="adam",
                       choices=["adam", "sgd", "lamb", "novograd", "adagrad"])
    group.add_argument("--dataloader-type", type=str, default="single",
                       choices=["single", "cyclic"])
    return parser


def _add_initialization_args(parser):
    group = parser.add_argument_group(title="initialization")
    group.add_argument("--seed", type=int, default=1234)
    group.add_argument("--init-method-std", type=float, default=0.02)
    group.add_argument("--init-method-xavier-uniform", action="store_true")
    return parser


def _add_learning_rate_args(parser):
    group = parser.add_argument_group(title="learning rate")
    group.add_argument("--lr", type=float, default=None)
    group.add_argument("--lr-decay-style", type=str, default="linear",
                       choices=["constant", "linear", "cosine"])
    group.add_argument("--lr-decay-iters", type=int, default=None)
    group.add_argument("--lr-decay-samples", type=int, default=None)
    group.add_argument("--lr-warmup-fraction", type=float, default=None)
    group.add_argument("--lr-warmup-iters", type=int, default=0)
    group.add_argument("--lr-warmup-samples", type=int, default=0)
    group.add_argument("--min-lr", type=float, default=0.0)
    group.add_argument("--override-lr-scheduler", action="store_true")
    group.add_argument("--use-checkpoint-lr-scheduler", action="store_true")
    return parser


def _add_checkpointing_args(parser):
    group = parser.add_argument_group(title="checkpointing")
    group.add_argument("--save", type=str, default=None)
    group.add_argument("--save-interval", type=int, default=None)
    group.add_argument("--no-save-optim", action="store_true", default=None)
    group.add_argument("--no-save-rng", action="store_true", default=None)
    group.add_argument("--load", type=str, default=None)
    group.add_argument("--no-load-optim", action="store_true", default=None)
    group.add_argument("--no-load-rng", action="store_true", default=None)
    group.add_argument("--finetune", action="store_true")
    return parser


def _add_mixed_precision_args(parser):
    group = parser.add_argument_group(title="mixed precision")
    group.add_argument("--fp16", action="store_true",
                       help="fp16 + loss scaling (reference parity mode)")
    group.add_argument("--bf16", action="store_true",
                       help="bfloat16 — the TPU-native half type")
    group.add_argument("--loss-scale", type=float, default=None,
                       help="static loss scale; None = dynamic")
    group.add_argument("--initial-loss-scale", type=float, default=2 ** 16)
    group.add_argument("--min-loss-scale", type=float, default=1.0)
    group.add_argument("--loss-scale-window", type=float, default=2000)
    group.add_argument("--hysteresis", type=int, default=2)
    group.add_argument("--fp32-residual-connection", action="store_true")
    group.add_argument("--accumulate-allreduce-grads-in-fp32",
                       action="store_true")
    group.add_argument("--attention-softmax-in-fp32", action="store_true")
    return parser


def _add_distributed_args(parser):
    group = parser.add_argument_group(title="distributed")
    group.add_argument("--tensor-model-parallel-size", type=int, default=1)
    group.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    group.add_argument("--pipeline-model-parallel-split-rank", type=int,
                       default=None)
    group.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                       default=None)
    group.add_argument("--world-size", type=int, default=None,
                       help="override device count (emulated meshes)")
    group.add_argument("--distributed-backend", default="xla",
                       choices=["xla", "nccl", "gloo"],
                       help="accepted for script parity; the mesh always "
                            "rides XLA collectives")
    group.add_argument("--DDP-impl", default="local",
                       choices=["local", "torch"],
                       help="no-op under XLA (GSPMD owns bucketing)")
    group.add_argument("--use-contiguous-buffers-in-local-ddp",
                       action="store_true", help="no-op under XLA")
    group.add_argument("--local_rank", type=int, default=None)
    return parser


def _add_validation_args(parser):
    group = parser.add_argument_group(title="validation")
    group.add_argument("--eval-iters", type=int, default=100)
    group.add_argument("--eval-interval", type=int, default=1000)
    return parser


def _add_data_args(parser):
    group = parser.add_argument_group(title="data and dataloader")
    group.add_argument("--data-path", nargs="*", default=None)
    group.add_argument("--split", type=str, default="969, 30, 1")
    group.add_argument("--vocab-file", type=str, default=None)
    group.add_argument("--merge-file", type=str, default=None)
    group.add_argument("--seq-length", type=int, default=None)
    group.add_argument("--encoder-seq-length", type=int, default=None)
    group.add_argument("--decoder-seq-length", type=int, default=None)
    group.add_argument("--num-workers", type=int, default=2)
    group.add_argument("--reset-position-ids", action="store_true")
    group.add_argument("--reset-attention-mask", action="store_true")
    group.add_argument("--eod-mask-loss", action="store_true")
    return parser


def _add_autoresume_args(parser):
    """Reference arguments.py:725-733.  Parse-surface parity: ADLR's
    SLURM autoresume daemon has no TPU analog — preemption is handled by
    the resilience layer (GracePeriodHandler SIGTERM grace + async
    checkpointing) instead of a cluster-side resubmit hook."""
    group = parser.add_argument_group(title="autoresume")
    group.add_argument("--adlr-autoresume", action="store_true",
                       help="accepted for script parity; preemption is "
                            "handled by apex_tpu.resilience instead of "
                            "the ADLR autoresume daemon")
    group.add_argument("--adlr-autoresume-interval", type=int, default=1000,
                       help="intervals over which check for autoresume "
                            "termination signal (parity no-op)")
    return parser


def _add_biencoder_args(parser):
    """Reference arguments.py:736-775 — the ICT/REALM biencoder +
    retriever flag set.  The testing tier does not instantiate these
    models; the flags exist so reference launch scripts parse
    unchanged."""
    group = parser.add_argument_group(title="biencoder")

    # network size
    group.add_argument("--ict-head-size", type=int, default=None,
                       help="size of block embeddings to be used in "
                            "ICT and REALM")
    group.add_argument("--biencoder-projection-dim", type=int, default=0,
                       help="dimension of projection head used in "
                            "biencoder")
    group.add_argument("--biencoder-shared-query-context-model",
                       action="store_true",
                       help="whether to share the parameters of the "
                            "query and context models")

    # checkpointing
    group.add_argument("--ict-load", type=str, default=None,
                       help="directory containing an ICTBertModel "
                            "checkpoint")
    group.add_argument("--bert-load", type=str, default=None,
                       help="directory containing an BertModel "
                            "checkpoint (needed to start ICT and REALM)")

    # data
    group.add_argument("--titles-data-path", type=str, default=None,
                       help="path to titles dataset used for ICT")
    group.add_argument("--query-in-block-prob", type=float, default=0.1,
                       help="probability of keeping query in block for "
                            "ICT dataset")
    group.add_argument("--use-one-sent-docs", action="store_true",
                       help="whether to use one sentence documents in ICT")
    group.add_argument("--evidence-data-path", type=str, default=None,
                       help="path to Wikipedia evidence from DPR paper")

    # training
    group.add_argument("--retriever-report-topk-accuracies", nargs="+",
                       type=int, default=[],
                       help="which top-k accuracies to report (e.g. "
                            "'1 5 20')")
    group.add_argument("--retriever-score-scaling", action="store_true",
                       help="whether to scale retriever scores by "
                            "inverse square root of hidden size")

    # faiss index
    group.add_argument("--block-data-path", type=str, default=None,
                       help="where to save/load BlockData to/from")
    group.add_argument("--embedding-path", type=str, default=None,
                       help="where to save/load Open-Retrieval "
                            "Embedding data to/from")

    # indexer
    group.add_argument("--indexer-batch-size", type=int, default=128,
                       help="how large of batches to use when doing "
                            "indexing jobs")
    group.add_argument("--indexer-log-interval", type=int, default=1000,
                       help="after how many batches should the indexer "
                            "report progress")
    return parser


def _add_vit_args(parser):
    """Reference arguments.py:778-806 — the vision-transformer flag
    group (parse-surface parity; the testing tier's models are GPT and
    BERT)."""
    group = parser.add_argument_group(title="vit")
    group.add_argument("--num-classes", type=int, default=1000,
                       help="num of classes in vision classification task")
    group.add_argument("--img-dim", type=int, default=224,
                       help="image size for vision classification task")
    group.add_argument("--num-channels", type=int, default=3,
                       help="number of image channels")
    group.add_argument("--patch-dim", type=int, default=16,
                       help="patch dimension used in vit")
    return parser


def _add_logging_args(parser):
    group = parser.add_argument_group(title="logging")
    group.add_argument("--log-params-norm", action="store_true")
    group.add_argument("--log-num-zeros-in-grad", action="store_true")
    group.add_argument("--timing-log-level", type=int, default=0,
                       choices=range(0, 3))
    group.add_argument("--log-timers-to-tensorboard", action="store_true")
    group.add_argument("--log-memory-to-tensorboard", action="store_true")
    return parser
