"""Resilient training-loop harness (testing tier).

A minimal but complete train loop wiring together every piece of
:mod:`apex_tpu.resilience`: periodic async checkpointing, preemption
polling with a final blocking save, and divergence guarding.  The chaos
tier drives this loop under simulated preemption / storage faults to prove
the full survive-and-resume story on CPU; it is also the reference wiring
for real entrypoints (``examples/gpt/pretrain_gpt.py`` follows the same
shape).

Contract: ``step_fn(state, batch) -> (state, finite_or_None)`` where
``finite`` is the all-finite scalar of the step's grads (or None when the
loop should not do skip accounting).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Optional

from apex_tpu import checkpoint as ckpt
from apex_tpu.resilience import wait_for_save
from apex_tpu.resilience.guards import StepGuard
from apex_tpu.resilience.preemption import GracePeriodHandler


@dataclasses.dataclass
class LoopResult:
    state: Any
    steps_run: int  # steps executed by THIS loop invocation
    step: int  # global step reached (start_step + steps_run)
    preempted: bool
    stop_reason: Optional[str]
    last_saved_step: Optional[int]
    skipped_steps: int


def run_resilient_training(
    step_fn: Callable[[Any, Any], tuple],
    state: Any,
    batches: Iterable[Any],
    *,
    ckpt_dir: Optional[str] = None,
    save_every: int = 0,
    keep: Optional[int] = None,
    async_saves: bool = True,
    shardings: Any = None,
    shard_axis: Optional[str] = None,
    handler: Optional[GracePeriodHandler] = None,
    guard: Optional[StepGuard] = None,
    watchdog: Any = None,
    start_step: int = 0,
    on_step: Optional[Callable[[int], None]] = None,
    log_every: int = 0,
    log_fn: Optional[Callable[[str], None]] = None,
) -> LoopResult:
    """Run ``step_fn`` over ``batches`` with the full resilience wiring.

    - every ``save_every`` steps: checkpoint (async by default — the loop
      keeps stepping while the write is in flight; the next save fences);
      ``shard_axis`` makes every save *sharded* (per-rank partition files
      for leaves whose spec leads with that axis — the ZeRO layout);
    - after every step: poll ``handler.should_stop``; on preemption write a
      final BLOCKING checkpoint (itself fencing any in-flight async write)
      and return with ``preempted=True`` — the caller restarts later via
      :func:`apex_tpu.resilience.restore_resilient` and passes the
      remaining batches with ``start_step`` set;
    - ``guard`` counts skipped steps from the ``finite`` flag ``step_fn``
      returns and raises after too many consecutive skips;
    - ``watchdog`` (:class:`apex_tpu.resilience.Watchdog`) arms its
      deadline around each ``step_fn`` call — the collective-bearing
      region; a hang escalates to ``handler``'s save-and-exit path;
    - ``log_every``/``log_fn`` emit a status line every N steps that
      surfaces divergence-skip accounting — the guard's total/consecutive
      skip counters and, when the state carries a
      ``LossScaleState.skipped`` device counter (``state.scaler_state``),
      that too — so skip events are visible without reading the pytree;
    - ``on_step(step)`` runs at each step boundary *before* the preemption
      poll (the chaos harness's ``SimulatedPreemption.poll`` and
      ``DeviceLoss.poll`` hook here);
    - before returning (any path) the loop fences on outstanding async
      writes, so a completed run's checkpoints are durable.
    """
    step = start_step
    steps_run = 0
    last_saved: Optional[int] = None
    preempted = False

    def _save(blocking: bool) -> None:
        nonlocal last_saved
        if ckpt_dir is None:
            return
        ckpt.save_checkpoint(ckpt_dir, state, step=step, keep=keep,
                             shardings=shardings, shard_axis=shard_axis,
                             blocking=blocking or not async_saves)
        last_saved = step

    def _log() -> None:
        emit = log_fn or print
        parts = [f"[resilient] step {step}"]
        if guard is not None:
            parts.append(f"skipped {guard.total_skipped}/"
                         f"{guard.total_steps} (consecutive "
                         f"{guard.consecutive})")
        scaler_state = getattr(state, "scaler_state", None)
        skipped = getattr(scaler_state, "skipped", None)
        if skipped is not None:
            import jax as _jax

            parts.append(f"scaler_skipped {int(_jax.device_get(skipped))}")
        if last_saved is not None:
            parts.append(f"last_saved {last_saved}")
        emit(" ".join(parts))

    try:
        for batch in batches:
            if watchdog is not None:
                with watchdog.step(step):
                    state, finite = step_fn(state, batch)
            else:
                state, finite = step_fn(state, batch)
            step += 1
            steps_run += 1
            if guard is not None and finite is not None:
                guard.update(finite)
            if log_every and step % log_every == 0:
                _log()
            if on_step is not None:
                on_step(step)
            if handler is not None and handler.should_stop:
                # grace period: current step finished; make the work durable
                # and hand control back for a clean exit
                preempted = True
                _save(blocking=True)
                break
            if save_every and step % save_every == 0:
                _save(blocking=False)
    except BaseException:
        # still fence, but never let a parked async-save error mask the
        # primary exception (e.g. a DivergenceError diagnostic)
        try:
            wait_for_save()
        except Exception:
            pass
        raise
    wait_for_save()

    return LoopResult(
        state=state,
        steps_run=steps_run,
        step=step,
        preempted=preempted,
        stop_reason=handler.reason if handler is not None else None,
        last_saved_step=last_saved,
        skipped_steps=guard.total_skipped if guard is not None else 0,
    )
