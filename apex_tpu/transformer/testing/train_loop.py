"""Resilient training-loop harness (testing tier).

A minimal but complete train loop wiring together every piece of
:mod:`apex_tpu.resilience`: periodic async checkpointing, preemption
polling with a final blocking save, and divergence guarding.  The chaos
tier drives this loop under simulated preemption / storage faults to prove
the full survive-and-resume story on CPU; it is also the reference wiring
for real entrypoints (``examples/gpt/pretrain_gpt.py`` follows the same
shape).

With a :class:`~apex_tpu.telemetry.TelemetryBus` attached the loop is
also the reference *observability* wiring (ISSUE 4): per-step ``step``
events with the data-wait / step / checkpoint-fence wall split,
``ckpt_save`` events, ``skip`` events from the guard, ``watchdog``
events from the deadline monitor, and a flight-recorder postmortem
flushed on every abnormal exit (grace-period stop, watchdog escalation,
device loss, divergence).

Contract: ``step_fn(state, batch) -> (state, finite_or_None)`` where
``finite`` is the all-finite scalar of the step's grads (or None when the
loop should not do skip accounting).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, Optional

from apex_tpu import checkpoint as ckpt
from apex_tpu.resilience import wait_for_save
from apex_tpu.resilience.guards import StepGuard
from apex_tpu.resilience.preemption import GracePeriodHandler


@dataclasses.dataclass
class LoopResult:
    state: Any
    steps_run: int  # steps executed by THIS loop invocation
    step: int  # global step reached (start_step + steps_run)
    preempted: bool
    stop_reason: Optional[str]
    last_saved_step: Optional[int]
    skipped_steps: int


def _default_scalars(state: Any, finite: Any) -> Dict[str, Any]:
    """Device-scalar refs the loop can surface without knowing the
    state's shape: the amp scaler's loss scale and monotonic skip
    counter (when the state carries them) plus the step's finite flag.
    These are REFERENCES — the accountant batches the fetch, one
    device_get per logging window."""
    out: Dict[str, Any] = {}
    scaler_state = getattr(state, "scaler_state", None)
    if scaler_state is not None:
        if getattr(scaler_state, "loss_scale", None) is not None:
            out["loss_scale"] = scaler_state.loss_scale
        if getattr(scaler_state, "skipped", None) is not None:
            out["scaler_skipped"] = scaler_state.skipped
    if finite is not None:
        out["finite"] = finite
    return out


def run_resilient_training(
    step_fn: Callable[[Any, Any], tuple],
    state: Any,
    batches: Optional[Iterable[Any]] = None,
    *,
    data_iter: Any = None,
    ckpt_dir: Optional[str] = None,
    save_every: int = 0,
    keep: Optional[int] = None,
    async_saves: bool = True,
    shardings: Any = None,
    shard_axis: Optional[str] = None,
    shard_axes: Optional[Any] = None,
    handler: Optional[GracePeriodHandler] = None,
    guard: Optional[StepGuard] = None,
    watchdog: Any = None,
    start_step: int = 0,
    on_step: Optional[Callable[[int], None]] = None,
    log_every: int = 0,
    log_fn: Optional[Callable[[str], None]] = None,
    telemetry: Any = None,
    telemetry_scalars: Optional[Callable[[Any], Dict[str, Any]]] = None,
    profile_sampler: Any = None,
) -> LoopResult:
    """Run ``step_fn`` over ``batches`` with the full resilience wiring.

    - every ``save_every`` steps: checkpoint (async by default — the loop
      keeps stepping while the write is in flight; the next save fences);
      ``shard_axis`` makes every save *sharded* (per-rank partition files
      for leaves whose spec leads with that axis — the ZeRO layout);
      ``shard_axes`` (an ordered {mesh axis: size} mapping) makes them
      *multi-axis* sharded — format 4, shard files keyed by (d, p, t)
      mesh coordinates (the 3-D elastic harness's save path);
    - after every step: poll ``handler.should_stop``; on preemption write a
      final BLOCKING checkpoint (itself fencing any in-flight async write)
      and return with ``preempted=True`` — the caller restarts later via
      :func:`apex_tpu.resilience.restore_resilient` and passes the
      remaining batches with ``start_step`` set;
    - ``guard`` counts skipped steps from the ``finite`` flag ``step_fn``
      returns and raises after too many consecutive skips;
    - ``watchdog`` (:class:`apex_tpu.resilience.Watchdog`) arms its
      deadline around each ``step_fn`` call — the collective-bearing
      region; a hang escalates to ``handler``'s save-and-exit path;
    - ``log_every``/``log_fn`` emit a status line every N steps carrying
      throughput (steps/s over the window), divergence-skip accounting
      (the guard's counters and, when the state carries a
      ``LossScaleState.skipped`` device counter, that too), and — with a
      watchdog attached — the max heartbeat age, so a stalling mesh is
      visible *before* the deadline escalates;
    - ``telemetry`` (:class:`apex_tpu.telemetry.TelemetryBus`): the loop
      emits ``run_start``/``step``/``ckpt_save``/``run_end`` events,
      books the wall split (data-wait / step / ckpt-fence, for goodput),
      shares its bus with ``guard``/``watchdog`` (skip and watchdog
      events), and flushes a flight-recorder postmortem on the
      grace-period exit and on any exception leaving the loop.
      ``telemetry_scalars(state) -> {name: device_ref}`` adds run-
      specific scalars (e.g. the loss) to the windowed batched fetch;
    - ``profile_sampler``
      (:class:`apex_tpu.telemetry.ProfileSampler`, ISSUE 9): gets
      :meth:`~apex_tpu.telemetry.ProfileSampler.on_step` at every step
      boundary, so the run periodically captures a short profiler
      window and emits ``profile``/``memory`` attribution events
      (per-phase device ms, exposed-collective ms, live/peak HBM)
      through the bus; its capture overhead books to the accountant's
      ``profile`` bucket.  The sampler never raises into the loop;
    - ``on_step(step)`` runs at each step boundary *before* the preemption
      poll (the chaos harness's ``SimulatedPreemption.poll`` and
      ``DeviceLoss.poll`` hook here);
    - ``data_iter`` (instead of ``batches``): an input-pipeline iterator
      conforming to the checkpointable-iterator protocol
      (``state_dict()``/``load_state_dict()``, e.g.
      :class:`apex_tpu.data.ShardedRecordIterator` — optionally behind
      :class:`~apex_tpu.data.AsyncPrefetcher`).  Every checkpoint then
      also records the iterator's position (the manifest ``data_state``
      key) so a resumed run replays *exactly* the samples an
      uninterrupted run would have seen — no duplicates, no drops
      (docs/data.md).  With checkpointing enabled, a plain
      generator/iterator without the protocol is REJECTED up front:
      restoring model state while silently rewinding (or fast-
      forwarding) the data stream is the bug this parameter exists to
      make impossible;
    - before returning (any path) the loop fences on outstanding async
      writes, so a completed run's checkpoints are durable.
    """
    if data_iter is not None:
        if batches is not None:
            raise ValueError("pass batches OR data_iter, not both")
        if ckpt_dir is not None and not (
                hasattr(data_iter, "state_dict")
                and hasattr(data_iter, "load_state_dict")):
            raise TypeError(
                f"data_iter {type(data_iter).__name__} is not "
                "checkpointable (no state_dict/load_state_dict) but "
                "checkpointing is enabled — a restored run would "
                "silently replay or skip training data.  Use "
                "apex_tpu.data.ShardedRecordIterator (or wrap it in "
                "AsyncPrefetcher), or pass a Sequence via batches= and "
                "manage the position yourself.")
        if ckpt_dir is not None:
            # probe eagerly: a wrapper (AsyncPrefetcher) around a
            # non-checkpointable source defines state_dict but raises
            # inside it — fail NOW, not at the first checkpoint save
            # hundreds of steps in
            data_iter.state_dict()
        batches = data_iter
    elif batches is None:
        raise ValueError("run_resilient_training needs batches or "
                         "data_iter")
    step = start_step
    steps_run = 0
    last_saved: Optional[int] = None
    preempted = False

    acct = None
    compile_acc = {"s": 0.0}  # XLA compile wall since the last step
    uninstall_recompile = lambda: None  # noqa: E731
    if telemetry is not None:
        from apex_tpu.telemetry import install_recompile_listener

        acct = telemetry.accountant(window=log_every or 10)
        uninstall_recompile = install_recompile_listener(
            telemetry,
            on_duration=lambda s: compile_acc.__setitem__(
                "s", compile_acc["s"] + s))
        if guard is not None and guard.telemetry is None:
            guard.telemetry = telemetry
        if watchdog is not None:
            telemetry.attach_watchdog(watchdog)
        if profile_sampler is not None:
            profile_sampler.attach_accountant(acct)
        telemetry.emit(
            "run_start", step=start_step,
            save_every=save_every, async_saves=bool(async_saves),
            sharded=shard_axis is not None or shard_axes is not None,
            watchdog=watchdog is not None, guarded=guard is not None)

    def _save(blocking: bool) -> None:
        nonlocal last_saved
        if ckpt_dir is None:
            return
        t0 = time.monotonic()
        # the iterator position rides the SAME manifest as the model
        # state (atomic commit), so a restore can never pair step N's
        # weights with step M's data cursor
        data_state = (data_iter.state_dict()
                      if data_iter is not None
                      and hasattr(data_iter, "state_dict") else None)
        ckpt.save_checkpoint(ckpt_dir, state, step=step, keep=keep,
                             shardings=shardings, shard_axis=shard_axis,
                             shard_axes=shard_axes,
                             data_state=data_state,
                             blocking=blocking or not async_saves)
        dt = time.monotonic() - t0
        last_saved = step
        if telemetry is not None:
            # the host-visible cost: a blocking save IS a fence+write;
            # an async save call only stalls when it fences a previous
            # in-flight write — either way `dt` is checkpoint stall
            acct.pause(dt, "ckpt_fence")
            telemetry.emit("ckpt_save", step=step,
                           blocking=bool(blocking or not async_saves),
                           wall_ms=round(dt * 1e3, 3))

    t_last_log = time.monotonic()
    step_last_log = start_step

    def _log() -> None:
        nonlocal t_last_log, step_last_log
        emit = log_fn or print
        parts = [f"[resilient] step {step}"]
        now = time.monotonic()
        if now > t_last_log and step > step_last_log:
            parts.append(
                f"{(step - step_last_log) / (now - t_last_log):.2f} steps/s")
        t_last_log, step_last_log = now, step
        if guard is not None:
            parts.append(f"skipped {guard.total_skipped}/"
                         f"{guard.total_steps} (consecutive "
                         f"{guard.consecutive})")
        scaler_state = getattr(state, "scaler_state", None)
        skipped = getattr(scaler_state, "skipped", None)
        if skipped is not None:
            import jax as _jax

            parts.append(f"scaler_skipped {int(_jax.device_get(skipped))}")
        if watchdog is not None:
            age = watchdog.max_heartbeat_age()
            if age is not None:
                # the stall early-warning: this climbs for the whole
                # hang, the deadline only fires at its end
                parts.append(f"max_hb_age {age:.1f}s")
        if last_saved is not None:
            parts.append(f"last_saved {last_saved}")
        emit(" ".join(parts))

    def _flush_postmortem(reason: str) -> None:
        if telemetry is None:
            return
        try:
            telemetry.flush_postmortem(reason, step=step, watchdog=watchdog)
        except Exception:  # never mask the primary failure
            pass

    def _finish(reason: str) -> None:
        if acct is not None:
            try:
                acct.finish(step=step, reason=reason)
            except Exception:
                pass

    try:
        it = iter(batches)
        while True:
            t0 = time.monotonic()
            try:
                batch = next(it)
            except StopIteration:
                break
            t1 = time.monotonic()
            if watchdog is not None:
                with watchdog.step(step):
                    state, finite = step_fn(state, batch)
            else:
                state, finite = step_fn(state, batch)
            step += 1
            steps_run += 1
            skipped = False
            synced = guard is not None and finite is not None
            if synced:
                scaler_state = getattr(state, "scaler_state", None)
                # bool(finite) inside update is a device sync — the one
                # per-step sync a guarded loop already pays
                skipped = guard.update(
                    finite, step=step,
                    loss_scale=getattr(scaler_state, "loss_scale", None))
            # measure step wall AFTER the guard's finite sync, so on an
            # asynchronous backend step_ms covers the device step, not
            # just host dispatch; an unguarded loop has no sync point
            # and its step events are tagged timing="dispatch" — the
            # stream must say which clock it is on
            t2 = time.monotonic()
            if acct is not None:
                scalars = _default_scalars(state, finite)
                if telemetry_scalars is not None:
                    scalars.update(telemetry_scalars(state) or {})
                # compile wall observed inside this step (first step,
                # mid-run reshape) goes to the compile bucket, not to
                # productive goodput
                compile_s, compile_acc["s"] = compile_acc["s"], 0.0
                acct.step_done(step, step_s=t2 - t1, data_wait_s=t1 - t0,
                               skipped=skipped, scalars=scalars,
                               compile_s=compile_s,
                               timing="synced" if synced else "dispatch")
            if profile_sampler is not None:
                # never raises: a broken profiler backend degrades to
                # "no profile events", not a crashed run
                profile_sampler.on_step(step)
            if log_every and step % log_every == 0:
                _log()
            if on_step is not None:
                on_step(step)
            if handler is not None and handler.should_stop:
                # grace period: current step finished; make the work durable
                # and hand control back for a clean exit
                preempted = True
                _save(blocking=True)
                break
            if save_every and step % save_every == 0:
                _save(blocking=False)
    except BaseException as e:
        # the crash path: dump the flight recorder FIRST (the postmortem
        # is the whole point of the recorder), then fence — and never
        # let a parked async-save error mask the primary exception
        # (e.g. a DivergenceError diagnostic)
        _flush_postmortem(type(e).__name__)
        _finish(type(e).__name__)
        try:
            wait_for_save()
        except Exception:
            pass
        raise
    finally:
        uninstall_recompile()
    t0 = time.monotonic()
    wait_for_save()
    if acct is not None:
        acct.pause(time.monotonic() - t0, "ckpt_fence")

    stop_reason = handler.reason if handler is not None else None
    if preempted:
        # grace-period exit (SIGTERM / watchdog escalation /
        # request_stop): leave the machine-readable record of the last
        # ring-buffer window next to the stream
        _flush_postmortem(stop_reason or "preempted")
    _finish(stop_reason or "completed")

    return LoopResult(
        state=state,
        steps_run=steps_run,
        step=step,
        preempted=preempted,
        stop_reason=stop_reason,
        last_saved_step=last_saved,
        skipped_steps=guard.total_skipped if guard is not None else 0,
    )
