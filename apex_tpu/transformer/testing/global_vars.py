"""Global args/timers/microbatch-calculator singletons (reference
apex/transformer/testing/global_vars.py:34-270).

Same contract: ``set_global_variables`` parses args exactly once and builds
the microbatch calculator + timers; getters assert initialization. The
tensorboard writer hook keeps the reference's graceful degradation (None
when the package or --tensorboard-dir is absent).
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.transformer.pipeline_parallel._timers import Timers
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.testing import arguments

_GLOBAL_ARGS = None
_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_TIMERS = None


def _ensure_initialized(var, name):
    assert var is not None, f"{name} is not initialized."


def _ensure_not_initialized(var, name):
    assert var is None, f"{name} is already initialized."


def get_args():
    """Reference global_vars.py:34-37."""
    _ensure_initialized(_GLOBAL_ARGS, "args")
    return _GLOBAL_ARGS


def get_num_microbatches() -> int:
    _ensure_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                        "num microbatches calculator")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    _ensure_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                        "num microbatches calculator")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    """Reference global_vars.py:46-58 (no-op unless rampup configured)."""
    _ensure_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                        "num microbatches calculator")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples, consistency_check)


def get_tensorboard_writer():
    """May be None (reference global_vars.py:66-69)."""
    return _GLOBAL_TENSORBOARD_WRITER


def get_timers() -> Timers:
    _ensure_initialized(_GLOBAL_TIMERS, "timers")
    return _GLOBAL_TIMERS


def set_global_variables(extra_args_provider=None, args_defaults={},
                         ignore_unknown_args=False, args=None):
    """Reference global_vars.py:87-101."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    global _GLOBAL_TENSORBOARD_WRITER, _GLOBAL_TIMERS
    _ensure_not_initialized(_GLOBAL_ARGS, "args")
    _GLOBAL_ARGS = arguments.parse_args(
        extra_args_provider=extra_args_provider, defaults=args_defaults,
        ignore_unknown_args=ignore_unknown_args, args=args)

    _ensure_not_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                            "num microbatches calculator")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank=_GLOBAL_ARGS.rank,
        rampup_batch_size=_GLOBAL_ARGS.rampup_batch_size,
        global_batch_size=_GLOBAL_ARGS.global_batch_size,
        micro_batch_size=_GLOBAL_ARGS.micro_batch_size,
        data_parallel_size=_GLOBAL_ARGS.data_parallel_size,
    )

    if (_GLOBAL_TENSORBOARD_WRITER is None
            and getattr(_GLOBAL_ARGS, "tensorboard_dir", None)):
        try:
            from torch.utils.tensorboard import SummaryWriter

            _GLOBAL_TENSORBOARD_WRITER = SummaryWriter(
                log_dir=_GLOBAL_ARGS.tensorboard_dir)
        except Exception:
            _GLOBAL_TENSORBOARD_WRITER = None

    _ensure_not_initialized(_GLOBAL_TIMERS, "timers")
    _GLOBAL_TIMERS = Timers()
    return _GLOBAL_ARGS


def destroy_global_vars():
    """Test helper: reset all singletons (the reference leaks them between
    unittest runs; explicit teardown is cleaner)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    global _GLOBAL_TENSORBOARD_WRITER, _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_TENSORBOARD_WRITER = None
    _GLOBAL_TIMERS = None
