"""Standalone Megatron GPT — the reference testing model, TPU-native.

Re-design of ``apex.transformer.testing.standalone_gpt``
(reference standalone_gpt.py: GPTModel :1426, gpt_model_provider :1502,
ParallelMLP :234, ParallelAttention :283, ParallelTransformerLayer :575,
ParallelTransformer :711).

Structure parity (pre-LN GPT-2 architecture, untied pieces noted):

* vocab-parallel word embedding + learned position embedding,
* N × ParallelTransformerLayer:
    LN → ParallelAttention (ColumnParallel QKV → causal fused softmax →
    RowParallel proj) → residual → LN → ParallelMLP (ColumnParallel h→4h →
    GELU → RowParallel 4h→h) → residual,
* final LN, logits through the (vocab-parallel) word-embedding transpose,
* loss = vocab-parallel cross entropy.

TPU-native choices: layers are stacked and applied with ``lax.scan``
(constant compile time in depth); attention softmax is the fused
:class:`apex_tpu.ops.FusedScaleMaskSoftmax` causal kernel; all TP
communication comes from the plain-collective mappings, so the backward
all-reduces are derived by AD.  ``apply`` must run inside a region binding
the "tensor" axis.  Dropout is deterministic-off by default so pipeline /
TP parity tests are exact (reference tests run in eval-determinism too).

For pipeline parallelism, :func:`gpt_stage_fn` / :func:`gpt_loss_fn` adapt
the model to the compiled schedules: stage 0 embeds, the last stage applies
the head — selected with ``jnp.where`` on the stage index (SPMD-uniform).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops import (
    AttnMaskType,
    FusedScaleMaskSoftmax,
    layer_norm,
)
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.random import (
    dropout as _dropout,
    model_parallel_dropout_key,
)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Network-size args (reference testing/arguments.py network-size group)."""

    num_layers: int = 2
    hidden_size: int = 64
    num_attention_heads: int = 4
    vocab_size: int = 128
    max_position_embeddings: int = 64
    ffn_hidden_size: Optional[int] = None
    layernorm_epsilon: float = 1e-5
    init_method_std: float = 0.02
    fp16: bool = False
    bf16: bool = False
    tp_size: int = 1
    # dropout (reference ParallelAttention :283 / ParallelMLP-consumer
    # bias_dropout_add :575 / Embedding dropout): active only when a
    # ``dropout_key`` is passed to ``apply`` (training mode); parity and
    # eval runs simply pass no key
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    # TPU-first extensions beyond the reference's arguments set:
    # use the Pallas flash kernel for causal self-attention (no S×S
    # probs materialised) and rematerialise each layer in backward
    use_flash_attention: bool = False
    remat: bool = False
    # what the per-layer checkpoint saves: "full" recomputes the whole
    # layer (max memory savings, ~33% extra flops); "dots" saves matmul
    # outputs and recomputes only the cheap pointwise ops
    # (jax.checkpoint_policies.dots_saveable) — near-zero recompute
    # flops at ~4× the activation footprint of "full"
    remat_policy: str = "full"
    # Mixture-of-Experts: num_experts > 0 replaces every layer's MLP
    # with a Switch-routed expert MLP (apex_tpu.transformer.moe) —
    # experts replicated across TP; shard them over an expert mesh axis
    # by using SwitchMLP directly.  aux loss (load balancing) is folded
    # into the returned per-token losses so mean(losses) includes it.
    num_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coeff: float = 1e-2
    # flash kernel tile sizes (512² measured best for fwd+bwd at the
    # GPT-350M shape bh=128 s=1024 d=64; the 512/1024 library defaults
    # favor long sequences)
    flash_block_q: int = 512
    flash_block_k: int = 512

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def compute_dtype(self):
        if self.bf16:
            return jnp.bfloat16
        if self.fp16:
            return jnp.float16
        return jnp.float32

    @property
    def kv_channels(self) -> int:
        return self.hidden_size // self.num_attention_heads


def _normal_init(std):
    def init(key, shape):
        return jax.random.normal(key, shape) * std

    return init


class ParallelAttention:
    """Causal self-attention (reference standalone_gpt.py:283-546)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.qkv = ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, gather_output=False,
            init_method=_normal_init(cfg.init_method_std), tp_size=cfg.tp_size)
        self.proj = RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, input_is_parallel=True,
            init_method=_normal_init(cfg.init_method_std), tp_size=cfg.tp_size)
        self.softmax = FusedScaleMaskSoftmax(
            input_in_fp16=cfg.fp16, input_in_bf16=cfg.bf16,
            attn_mask_type=AttnMaskType.causal,
            scaled_masked_softmax_fusion=True, softmax_in_fp32=True,
            scale=None)
        self.np_local = cfg.num_attention_heads // cfg.tp_size

    def init_master(self, key):
        k1, k2 = jax.random.split(key)
        return {"qkv": self.qkv.init_master(k1), "proj": self.proj.init_master(k2)}

    def shard_master(self, master, rank):
        return {"qkv": self.qkv.shard_master(master["qkv"], rank),
                "proj": self.proj.shard_master(master["proj"], rank)}

    def apply(self, params, h, attention_mask=None, dropout_key=None,
              segment_ids=None):
        # h: [b, s, hidden]; segment_ids: int [b, s] varlen-packing ids
        # (pad tokens in their own bucket) — masks cross-segment scores
        cfg = self.cfg
        do_dropout = dropout_key is not None and cfg.attention_dropout > 0.0
        b, s, _ = h.shape
        qkv = self.qkv.apply(params["qkv"], h)  # [b, s, 3*hidden/tp]
        # flash-path dropout runs IN-KERNEL (counter-hash masks, FMHA
        # parity) — the seed derives from the per-TP-rank stream so
        # head-sharded probs drop independently per rank
        flash_drop = {}
        if cfg.use_flash_attention and do_dropout:
            seed = jax.random.bits(
                model_parallel_dropout_key(dropout_key), (),
                jnp.uint32).astype(jnp.int32)
            flash_drop = dict(dropout_rate=cfg.attention_dropout,
                              dropout_seed=seed)
        # the module's mask type, not the mask's presence, decides
        # causality (GPT: causal even WITH an extra padding mask)
        is_causal = self.softmax.attn_mask_type == AttnMaskType.causal
        is_key_padding = (attention_mask is not None
                          and attention_mask.ndim == 4
                          and attention_mask.shape[1] == 1
                          and attention_mask.shape[2] == 1)
        if cfg.use_flash_attention and (
                attention_mask is None or is_key_padding):
            # Packed flash kernel: consumes the QKV projection output
            # directly in its interleaved per-head layout and emits
            # dqkv the same way — no head transposes in forward,
            # recompute, or backward (r5; ~10 ms/step of layout copies
            # at the 350M bench shape).  Varlen shapes STAY on it (r7):
            # explicit packing ids, and KEY-PADDING masks ([b, 1, 1, s],
            # True = masked key — the BERT form) as segment ids with
            # all-ones query ids, reproducing key-side-only masking
            # exactly (pad QUERY rows still attend real keys, like the
            # reference's additive mask; the reference FMHA existed for
            # precisely this BERT varlen case, fmha.py:33-75).  The
            # segment predicate is fused in-kernel and fully-masked
            # k-blocks are skipped via the block-skip index; composes
            # with the causal flag for causal-model + padding callers.
            from apex_tpu.ops.attention import flash_attention_qkv

            seg = None
            if segment_ids is not None:
                seg = segment_ids
                if is_key_padding:
                    # fold padding into the packing ids: pad keys get a
                    # bucket no real segment uses (ids are >= 0), so no
                    # query row — any packing id — attends a pad key
                    pad = attention_mask[:, 0, 0, :].astype(bool)
                    seg = (seg, jnp.where(pad, -1, seg))
            elif is_key_padding:
                keep = (~attention_mask[:, 0, 0, :].astype(bool)).astype(
                    jnp.int32)  # [b, s], 1 = real token
                seg = (jnp.ones_like(keep), keep)
            ctx = flash_attention_qkv(
                qkv, self.np_local, causal=is_causal, segment_ids=seg,
                block=cfg.flash_block_q, block_k=cfg.flash_block_k,
                **flash_drop).astype(h.dtype)
            return self.proj.apply(params["proj"], ctx)
        qkv = qkv.reshape(b, s, self.np_local, 3 * cfg.kv_channels)
        q, k, v = jnp.split(qkv, 3, axis=-1)  # each [b, s, np, hn]
        # scores [b, np, s, s]; scale 1/sqrt(hn) matches norm_factor (:389)
        scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.kv_channels, jnp.float32))
        scores = jnp.einsum("bqnh,bknh->bnqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = (scores * scale).astype(h.dtype)
        if segment_ids is not None:
            # reference path for the packed form: cross-segment scores
            # masked through the same boolean-mask softmax (True =
            # masked) the padding variant uses — the parity anchor for
            # the flash packed path
            seg_mask = (segment_ids[:, None, :, None]
                        != segment_ids[:, None, None, :])
            if attention_mask is not None:
                seg_mask = seg_mask | attention_mask.astype(bool)
            attention_mask = seg_mask
        probs = self.softmax(scores, attention_mask)
        if do_dropout:
            # probs are head-sharded over TP: per-rank stream (reference
            # wraps this dropout in get_cuda_rng_tracker().fork(), :283)
            probs = _dropout(probs, cfg.attention_dropout,
                             model_parallel_dropout_key(dropout_key))
        ctx = jnp.einsum("bnqk,bknh->bqnh", probs, v,
                         preferred_element_type=jnp.float32).astype(h.dtype)
        ctx = ctx.reshape(b, s, self.np_local * cfg.kv_channels)
        return self.proj.apply(params["proj"], ctx)


class ParallelMLP:
    """h → 4h → h with fused GELU (reference standalone_gpt.py:234-281)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.dense_h_to_4h = ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn, gather_output=False,
            init_method=_normal_init(cfg.init_method_std), tp_size=cfg.tp_size)
        self.dense_4h_to_h = RowParallelLinear(
            cfg.ffn, cfg.hidden_size, input_is_parallel=True,
            init_method=_normal_init(cfg.init_method_std), tp_size=cfg.tp_size)

    def init_master(self, key):
        k1, k2 = jax.random.split(key)
        return {"dense_h_to_4h": self.dense_h_to_4h.init_master(k1),
                "dense_4h_to_h": self.dense_4h_to_h.init_master(k2)}

    def shard_master(self, master, rank):
        return {
            "dense_h_to_4h": self.dense_h_to_4h.shard_master(
                master["dense_h_to_4h"], rank),
            "dense_4h_to_h": self.dense_4h_to_h.shard_master(
                master["dense_4h_to_h"], rank),
        }

    def apply(self, params, h):
        from jax.ad_checkpoint import checkpoint_name

        inter = self.dense_h_to_4h.apply(params["dense_h_to_4h"], h)
        # named for remat_policy="attn_res_mlp": the PRE-gelu h→4h output
        # is the one tensor whose save removes the layer's biggest GEMM
        # (4h² of the 12h² per-layer GEMM flops) from the remat
        # recompute — gelu's backward needs this value, gelu/4h→h-wgrad
        # inputs rebuild from it elementwise, and the 4h→h forward
        # output is dead in the recompute graph (nothing in the backward
        # reads it)
        inter = checkpoint_name(inter, "mlp_4h")
        inter = jax.nn.gelu(inter, approximate=True)  # bias_gelu fusion (:250)
        return self.dense_4h_to_h.apply(params["dense_4h_to_h"], inter)


def embedding_dropout(h, cfg, dropout_key):
    """Dropout on the embedding output (reference Embedding.forward
    applies hidden_dropout before the first layer).  Replicated stream;
    one shared derivation so GPT and BERT keep identical RNG
    conventions."""
    if dropout_key is None or cfg.hidden_dropout <= 0.0:
        return h
    return _dropout(h, cfg.hidden_dropout,
                    jax.random.fold_in(dropout_key, 0x0E0B))


def _hidden_dropout(x, cfg, key):
    """Post-RowParallel hidden dropout: the activation is TP-replicated, so
    the *base* (replicated) key is correct — every rank must drop the same
    elements or the replicas diverge (reference bias_dropout_add :575 runs
    on the default RNG stream)."""
    if key is None or cfg.hidden_dropout <= 0.0:
        return x
    return _dropout(x, cfg.hidden_dropout, key)


class ParallelTransformerLayer:
    """Pre-LN block (reference standalone_gpt.py:575-709); with
    ``cfg.num_experts > 0`` the MLP is a Switch-routed expert MLP."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.attention = ParallelAttention(cfg)
        if cfg.num_experts > 0:
            from apex_tpu.transformer.moe import MoEConfig, SwitchMLP

            self.mlp = SwitchMLP(MoEConfig(
                hidden_size=cfg.hidden_size, ffn_hidden_size=cfg.ffn,
                num_experts=cfg.num_experts,
                capacity_factor=cfg.moe_capacity_factor,
                init_method_std=cfg.init_method_std))
        else:
            self.mlp = ParallelMLP(cfg)

    def init_master(self, key):
        k1, k2 = jax.random.split(key)
        h = self.cfg.hidden_size
        return {
            "input_layernorm": {"weight": jnp.ones((h,)), "bias": jnp.zeros((h,))},
            "attention": self.attention.init_master(k1),
            "post_attention_layernorm": {"weight": jnp.ones((h,)),
                                         "bias": jnp.zeros((h,))},
            "mlp": self.mlp.init_master(k2),
        }

    def shard_master(self, master, rank):
        if self.cfg.num_experts > 0:
            # experts are replicated across TP (shard them over an
            # expert axis with SwitchMLP.shard_master directly)
            mlp = master["mlp"]
        else:
            mlp = self.mlp.shard_master(master["mlp"], rank)
        return {
            "input_layernorm": master["input_layernorm"],
            "attention": self.attention.shard_master(master["attention"], rank),
            "post_attention_layernorm": master["post_attention_layernorm"],
            "mlp": mlp,
        }

    def apply(self, params, h, attention_mask=None, dropout_key=None,
              segment_ids=None):
        """Returns ``(h, aux)`` — ``aux`` is the MoE load-balancing loss
        (0.0 for the dense MLP)."""
        cfg = self.cfg
        eps = cfg.layernorm_epsilon
        k_attn = k_h1 = k_h2 = None
        if dropout_key is not None:
            k_attn, k_h1, k_h2 = (jax.random.fold_in(dropout_key, i)
                                  for i in range(3))
        ln1 = layer_norm(h, params["input_layernorm"]["weight"],
                         params["input_layernorm"]["bias"], eps=eps)
        attn = self.attention.apply(params["attention"], ln1, attention_mask,
                                    dropout_key=k_attn,
                                    segment_ids=segment_ids)
        # named for remat_policy="attn_out": saving just this [b,s,h]
        # tensor per layer (16 MB at the 350M bench shape) removes the
        # whole attention region from the remat recompute
        from jax.ad_checkpoint import checkpoint_name

        attn = checkpoint_name(attn, "attn_out")
        h = h + _hidden_dropout(attn, cfg, k_h1)
        ln2 = layer_norm(h, params["post_attention_layernorm"]["weight"],
                         params["post_attention_layernorm"]["bias"], eps=eps)
        if cfg.num_experts > 0:
            b, s, hid = ln2.shape
            out, aux = self.mlp.apply(params["mlp"], ln2.reshape(b * s, hid))
            out = out.reshape(b, s, hid).astype(h.dtype)
        else:
            out, aux = self.mlp.apply(params["mlp"], ln2), jnp.zeros((),
                                                                    jnp.float32)
        return h + _hidden_dropout(out, cfg, k_h2), aux


class ParallelTransformer:
    """Stack of layers applied with lax.scan (reference :711-1040 keeps a
    ModuleList; scanning is the compile-time-friendly TPU equivalent)."""

    def __init__(self, cfg: GPTConfig, num_layers: Optional[int] = None):
        self.cfg = cfg
        self.num_layers = num_layers if num_layers is not None else cfg.num_layers
        self.layer = ParallelTransformerLayer(cfg)

    def init_master(self, key):
        keys = jax.random.split(key, self.num_layers)
        layers = [self.layer.init_master(k) for k in keys]
        return {"layers": jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *layers)}

    def shard_master(self, master, rank):
        # shard each stacked leaf layer-wise
        def shard(stacked):
            return jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs),
                *[self.layer.shard_master(
                    jax.tree_util.tree_map(lambda a: a[i], stacked), rank)
                  for i in range(self.num_layers)])

        return {"layers": shard(master["layers"])}

    def apply(self, params, h, attention_mask=None, dropout_key=None,
              segment_ids=None):
        """Returns ``(h, aux)``; ``aux`` sums the layers' MoE
        load-balancing losses (0.0 for dense MLPs)."""
        def body(carry, xs):
            hidden, aux_sum = carry
            layer_params, idx = xs
            k = (None if dropout_key is None
                 else jax.random.fold_in(dropout_key, idx))
            hidden, aux = self.layer.apply(layer_params, hidden,
                                           attention_mask, dropout_key=k,
                                           segment_ids=segment_ids)
            return (hidden, aux_sum + aux), None

        if self.cfg.remat:
            # save only layer boundaries; recompute inside each layer on
            # backward (reference activation checkpointing, random.py TPU
            # mapping) — activation memory O(L·B·S·H) → O(B·S·H).  RNG
            # replay on recompute is free: keys are values (fold_in of the
            # same inputs), the property the reference's CheckpointFunction
            # restores CUDA RNG state for.  remat_policy="dots" keeps the
            # memory ceiling but skips recomputing the matmuls (the flops).
            if self.cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_saveable
            elif self.cfg.remat_policy == "attn_res":
                # save the flash kernel's RESIDUALS (o, lse — named in
                # ops/attention._flash_fwd_rule): the backward then
                # consumes them directly instead of re-running the
                # attention forward inside the remat region (saving the
                # module OUTPUT alone cannot do this — the custom_vjp
                # backward needs o and lse, so remat reruns the kernel
                # to rebuild them)
                policy = jax.checkpoint_policies.save_only_these_names(
                    "flash_attn_out", "flash_attn_lse")
            elif self.cfg.remat_policy == "attn_res_mlp":
                # attn_res plus the pre-gelu h→4h output (named in
                # ParallelMLP.apply): removes the h→4h GEMM (the
                # largest single recompute GEMM, 4h² of the 12h² body)
                # and gelu from the recompute.  The qkv and proj GEMMs
                # STILL recompute — the flash custom_vjp saves only
                # (o, lse), and its backward consumes q/k/v, which must
                # be rebuilt (bench.py's gpt_analytic_flops keeps their
                # 4h² in the recompute term accordingly).  Costs
                # +b·s·4h·2B per layer over attn_res (64 MB at the
                # 350M bench shape); measured LOSING to attn_res at
                # B=8/16 (BASELINE.md r5 sweep)
                policy = jax.checkpoint_policies.save_only_these_names(
                    "flash_attn_out", "flash_attn_lse", "mlp_4h")
            elif self.cfg.remat_policy == "attn_out":
                # keep the flash-attention output per layer (named above):
                # +16 MB/layer at the 350M shape.  This only removes
                # recompute of ops DOWNSTREAM of the saved output — the
                # flash custom_vjp backward still needs its (o, lse)
                # residuals, so remat re-runs the kernel to rebuild them
                # (only attn_res skips the kernel re-run; bench.py's
                # hw-flops accounting sets remat_attn=True here).
                # Measured ~7% off the step at B=8 (BASELINE.md r4 sweep)
                policy = jax.checkpoint_policies.save_only_these_names(
                    "attn_out")
            elif self.cfg.remat_policy == "full":
                policy = None
            else:
                # a misspelled policy must not silently degrade to full
                # recompute (review finding)
                raise ValueError(
                    f"unknown remat_policy {self.cfg.remat_policy!r}; "
                    "expected full|dots|attn_res|attn_res_mlp|attn_out")
            body = jax.checkpoint(body, policy=policy)
        (h, aux), _ = jax.lax.scan(
            body, (h, jnp.zeros((), jnp.float32)),
            (params["layers"], jnp.arange(self.num_layers)))
        return h, aux


class GPTModel:
    """Reference GPTModel (standalone_gpt.py:1426-1500): embeddings +
    transformer + tied LM head."""

    def __init__(self, cfg: GPTConfig, num_layers: Optional[int] = None,
                 pre_process: bool = True, post_process: bool = True):
        self.cfg = cfg
        self.pre_process = pre_process
        self.post_process = post_process
        self.embedding = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            init_method=_normal_init(cfg.init_method_std), tp_size=cfg.tp_size)
        self.transformer = ParallelTransformer(cfg, num_layers)

    def init_master(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        p = {"transformer": self.transformer.init_master(k3)}
        if self.pre_process:
            p["embedding"] = self.embedding.init_master(k1)
            p["position_embeddings"] = {
                "weight": jax.random.normal(
                    k2, (self.cfg.max_position_embeddings,
                         self.cfg.hidden_size)) * self.cfg.init_method_std}
        if self.post_process:
            h = self.cfg.hidden_size
            p["final_layernorm"] = {"weight": jnp.ones((h,)),
                                    "bias": jnp.zeros((h,))}
            if not self.pre_process:
                # untied stage: own copy of the word embedding for the head
                p["embedding"] = self.embedding.init_master(k1)
        return p

    def shard_master(self, master, rank):
        p = {"transformer": self.transformer.shard_master(
            master["transformer"], rank)}
        if "embedding" in master:
            p["embedding"] = self.embedding.shard_master(master["embedding"], rank)
        if "position_embeddings" in master:
            p["position_embeddings"] = master["position_embeddings"]
        if "final_layernorm" in master:
            p["final_layernorm"] = master["final_layernorm"]
        return p

    def embed(self, params, tokens):
        h = self.embedding.apply(params["embedding"], tokens)
        pos = params["position_embeddings"]["weight"][:tokens.shape[1]]
        return (h + pos[None]).astype(self.cfg.compute_dtype)

    def _final_norm(self, params, h):
        return layer_norm(h, params["final_layernorm"]["weight"],
                          params["final_layernorm"]["bias"],
                          eps=self.cfg.layernorm_epsilon)

    def head_logits_local(self, params, h):
        """Sharded logits [b, s, vocab/tp] through the tied embedding
        (reference post_language_model_processing / parallel_lm_logits)."""
        h = self._final_norm(params, h)
        # cast the tied fp32 master weight to the compute dtype (O2
        # semantics, and what the fused tp=1 head does): a mixed
        # bf16xfp32 dot would silently promote to an fp32 matmul
        w = params["embedding"]["weight"].astype(
            self.cfg.compute_dtype)  # [vocab/tp, hidden]
        return jax.lax.dot_general(
            h, w, (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    def apply(self, params, tokens, labels=None, attention_mask=None,
              dropout_key=None):
        """Full forward.  With ``labels`` returns per-token losses
        (reference GPTModel.forward returning CE loss); otherwise sharded
        logits.  ``dropout_key`` switches the config's
        attention/hidden-dropout rates on (training mode); the key must be
        TP-replicated — per-rank streams are derived inside (reference RNG
        tracker discipline, random.py:193-221)."""
        h = self.embed(params, tokens)
        h = embedding_dropout(h, self.cfg, dropout_key)
        h, aux = self.transformer.apply(params["transformer"], h,
                                        attention_mask,
                                        dropout_key=dropout_key)
        if labels is None:
            return self.head_logits_local(params, h)
        if self.cfg.tp_size == 1 and self.cfg.compute_dtype != jnp.float32:
            # single-shard half-precision head: fuse projection + CE so
            # only bf16 logits + fp32 lse round-trip HBM
            # (ops/fused_linear_xent.py).  fp32 configs keep the full-
            # precision unfused head (the fused op narrows operands);
            # TP-sharded heads keep the collective vocab-parallel CE.
            from apex_tpu.ops import fused_linear_cross_entropy

            hn = self._final_norm(params, h)
            b, s, hid = hn.shape
            losses = fused_linear_cross_entropy(
                hn.reshape(b * s, hid), params["embedding"]["weight"],
                labels.reshape(b * s)).reshape(b, s)
        else:
            logits_local = self.head_logits_local(params, h)
            losses = vocab_parallel_cross_entropy(logits_local, labels)
        if self.cfg.num_experts > 0:
            # fold the MoE load-balancing term in per-token so that
            # mean(losses) == CE_mean + coeff * aux (the Megatron
            # convention of adding aux to the scalar loss)
            losses = losses + (self.cfg.moe_aux_loss_coeff * aux
                               ).astype(losses.dtype)
        return losses

    __call__ = apply


def gpt_model_provider(cfg: GPTConfig, pre_process: bool = True,
                       post_process: bool = True) -> GPTModel:
    """Reference gpt_model_provider (standalone_gpt.py:1502)."""
    return GPTModel(cfg, pre_process=pre_process, post_process=post_process)


# --- pipeline adaptation ----------------------------------------------------


def make_gpt_stage_fns(cfg: GPTConfig, n_stages: int
                       ) -> Tuple[Any, Any]:
    """Split a GPT into ``n_stages`` pipeline stages for the compiled
    schedules (reference build_model pre/post_process flags per stage,
    schedules/common.py:18-106).

    Every stage holds the same param structure — embedding, L/p layers, and
    head — but only the first uses the embedding and only the last the head
    (where-masked).  Returns ``(stage_fn, loss_fn)`` for
    ``forward_backward_pipelining_without_interleaving``; microbatches are
    dicts with "tokens" and "labels".
    """
    if cfg.num_layers % n_stages != 0:
        raise ValueError("num_layers must divide evenly into stages")
    if getattr(cfg, "num_experts", 0):
        import warnings

        warnings.warn(
            "MoE under pipeline parallelism drops the load-balancing aux "
            "loss (stage outputs are a single hidden tensor) — routing "
            "can silently collapse. Use MoE with TP/DP, or thread a "
            "custom stage contract that carries the aux loss.",
            stacklevel=2)
    model = GPTModel(cfg, num_layers=cfg.num_layers // n_stages)

    def stage_fn(params, h_in, mb):
        s = parallel_state.get_pipeline_model_parallel_rank()
        embedded = model.embed(params, mb["tokens"])
        h = jnp.where(s == 0, embedded, h_in.astype(embedded.dtype))
        # MoE aux is dropped under pipelining (stage outputs are a single
        # hidden tensor); use MoE with TP/DP, not PP, or thread a custom
        # stage contract
        h, _aux = model.transformer.apply(params["transformer"], h)
        return h

    def loss_fn(params, h_out, mb):
        logits_local = model.head_logits_local(params, h_out)
        return jnp.mean(vocab_parallel_cross_entropy(logits_local, mb["labels"]))

    return stage_fn, loss_fn
