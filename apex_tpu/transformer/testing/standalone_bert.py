"""Standalone Megatron BERT — the reference testing model, TPU-native.

Re-design of ``apex.transformer.testing.standalone_bert``
(reference standalone_bert.py: BertModel :101, bert_model_provider :215).

Shares the parallel transformer body with
:mod:`apex_tpu.transformer.testing.standalone_gpt` (as the reference shares
ParallelTransformer), with BERT's differences: token-type embeddings,
*padding* (bidirectional) attention-mask semantics, a tanh pooler over the
first token, the tied MLM head with its own layernorm, and the binary
(NSP) head.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import AttnMaskType, layer_norm
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.testing.standalone_gpt import (
    GPTConfig,
    ParallelTransformer,
    _normal_init,
    embedding_dropout,
)


@dataclasses.dataclass(frozen=True)
class BertConfig(GPTConfig):
    """BERT reuses the network-size config plus token types / NSP head."""

    num_tokentypes: int = 2
    add_binary_head: bool = True


class BertModel:
    """Reference BertModel (standalone_bert.py:101-213)."""

    def __init__(self, cfg: BertConfig, num_layers: Optional[int] = None,
                 pre_process: bool = True, post_process: bool = True):
        self.cfg = cfg
        self.pre_process = pre_process
        self.post_process = post_process
        self.embedding = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            init_method=_normal_init(cfg.init_method_std), tp_size=cfg.tp_size)
        # BERT attention is bidirectional: overwrite the body's mask type
        self.transformer = ParallelTransformer(cfg, num_layers)
        for_softmax = self.transformer.layer.attention.softmax
        for_softmax.attn_mask_type = AttnMaskType.padding

    def init_master(self, key):
        k1, k2, k3, k4, k5, k6 = jax.random.split(key, 6)
        h = self.cfg.hidden_size
        std = self.cfg.init_method_std
        p: dict = {"transformer": self.transformer.init_master(k3)}
        if self.pre_process:
            p["embedding"] = self.embedding.init_master(k1)
            p["position_embeddings"] = {
                "weight": jax.random.normal(
                    k2, (self.cfg.max_position_embeddings, h)) * std}
            if self.cfg.num_tokentypes > 0:
                p["tokentype_embeddings"] = {
                    "weight": jax.random.normal(
                        k4, (self.cfg.num_tokentypes, h)) * std}
        if self.post_process:
            if not self.pre_process:
                p["embedding"] = self.embedding.init_master(k1)
            # lm head: dense + LN over hidden before the tied projection
            # (reference BertLmHead standalone_bert.py:40-72)
            p["lm_head"] = {
                "dense": {"weight": jax.random.normal(k5, (h, h)) * std,
                          "bias": jnp.zeros((h,))},
                "layernorm": {"weight": jnp.ones((h,)), "bias": jnp.zeros((h,))},
                "bias": jnp.zeros((self.embedding.num_embeddings_per_partition,)),
            }
            if self.cfg.add_binary_head:
                p["pooler"] = {"weight": jax.random.normal(k6, (h, h)) * std,
                               "bias": jnp.zeros((h,))}
                p["binary_head"] = {"weight": jnp.zeros((2, h)),
                                    "bias": jnp.zeros((2,))}
        return p

    def shard_master(self, master, rank):
        p = dict(master)
        if "embedding" in master:
            p["embedding"] = self.embedding.shard_master(master["embedding"], rank)
        if "lm_head" in master:
            lm = dict(master["lm_head"])
            n = self.embedding.num_embeddings_per_partition
            # the lm bias is vocab-parallel like the tied embedding; a master
            # built at tp=1 carries the full vocab-length bias — shard it
            full = master["lm_head"]["bias"]
            lm["bias"] = (full[rank * n:(rank + 1) * n]
                          if full.shape[0] != n else full)
            p["lm_head"] = lm
        p["transformer"] = self.transformer.shard_master(master["transformer"],
                                                         rank)
        return p

    def embed(self, params, tokens, tokentype_ids=None, position_ids=None):
        h = self.embedding.apply(params["embedding"], tokens)
        if position_ids is None:
            pos = params["position_embeddings"]["weight"][:tokens.shape[1]]
            h = h + pos[None]
        else:
            # explicit per-token positions (varlen packing: positions
            # restart at each segment boundary, the reference packing
            # convention — each packed sequence sees the same position
            # embeddings it would see padded)
            h = h + params["position_embeddings"]["weight"][position_ids]
        if tokentype_ids is not None and "tokentype_embeddings" in params:
            h = h + params["tokentype_embeddings"]["weight"][tokentype_ids]
        return h.astype(self.cfg.compute_dtype)

    def lm_logits_local(self, params, h):
        """Sharded MLM logits via the tied embedding + head transform."""
        lm = params["lm_head"]
        h = h @ lm["dense"]["weight"].T + lm["dense"]["bias"]
        h = jax.nn.gelu(h, approximate=True)
        h = layer_norm(h, lm["layernorm"]["weight"], lm["layernorm"]["bias"],
                       eps=self.cfg.layernorm_epsilon)
        w = params["embedding"]["weight"]
        logits = jax.lax.dot_general(
            h, w, (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return logits + lm["bias"]

    def apply(self, params, tokens, attention_mask=None, tokentype_ids=None,
              lm_labels=None, dropout_key=None, segment_ids=None,
              position_ids=None):
        """Returns ``(lm_losses_or_logits, binary_logits)``.

        ``dropout_key`` enables the config's attention/hidden dropout
        (training mode), with the same TP-replicated/per-rank stream
        discipline as the GPT (see standalone_gpt.GPTModel.apply).

        ``segment_ids`` (r7): int [b, s] varlen-*packing* ids — several
        real sequences share one row of ``tokens``, delimited by id
        changes (the reference FMHA's cu_seqlens packing, fmha.py:33-75;
        give trailing pad tokens their own id bucket).  Attention is
        masked across segments; with ``use_flash_attention`` the packed
        rows ride the transpose-free varlen fast path with block-skip.
        Pass ``position_ids`` restarting at each segment so every packed
        sequence sees the same position embeddings it would see padded."""
        h = self.embed(params, tokens, tokentype_ids, position_ids)
        h = embedding_dropout(h, self.cfg, dropout_key)
        # padding mask [b, 1, 1, s] -> broadcast [b, 1, s, s], True = masked
        am = None
        if attention_mask is not None:
            am = ~attention_mask[:, None, None, :].astype(bool)
        h, _aux = self.transformer.apply(params["transformer"], h, am,
                                         dropout_key=dropout_key,
                                         segment_ids=segment_ids)

        binary_logits = None
        if self.cfg.add_binary_head and "binary_head" in params:
            pooled = jnp.tanh(
                h[:, 0] @ params["pooler"]["weight"].T + params["pooler"]["bias"])
            binary_logits = (pooled @ params["binary_head"]["weight"].T
                             + params["binary_head"]["bias"])

        logits_local = self.lm_logits_local(params, h)
        if lm_labels is None:
            return logits_local, binary_logits
        losses = vocab_parallel_cross_entropy(logits_local, lm_labels)
        return losses, binary_logits

    __call__ = apply


def bert_model_provider(cfg: BertConfig, pre_process: bool = True,
                        post_process: bool = True) -> BertModel:
    """Reference bert_model_provider (standalone_bert.py:215)."""
    return BertModel(cfg, pre_process=pre_process, post_process=post_process)
