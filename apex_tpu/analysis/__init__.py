"""apex_tpu.analysis — project-invariant linter + hot-path sanitizer.

Ten PRs of hard-won invariants — the closed telemetry event set with
bool-not-int discipline (PR 4), buffer donation on pool-sized jit
calls (PR 8), seeded-only randomness in every bitwise-contract module,
one-device-fetch-per-window in hot loops — enforced as build-time
checks instead of reviewer memory (ISSUE 11; the reference encodes the
same kind of discipline as setup.py build-time feature gates,
SURVEY §L0).

Two halves:

- **static** — an AST-based linter with a project-specific rule
  catalog (:mod:`~apex_tpu.analysis.rules`: HS001 host-sync-in-hot-
  path, ND001 unseeded nondeterminism, DN001 missing donation, TL001
  telemetry schema drift, TH001 lock discipline, EX001 exception
  swallowing), inline ``# lint: disable=RULE`` suppression, and a
  committed baseline of documented exceptions.  CLI::

      python -m apex_tpu.analysis lint apex_tpu/ [--baseline FILE]
                                       [--json] [--no-baseline]
      python -m apex_tpu.analysis rules

  Exit 0 = clean against the baseline (the tier-1 CI gate), 1 =
  findings.  The linter never imports the modules it checks — it is
  AST-only and runs in seconds.

- **runtime** — :func:`hot_path_guard`, a context manager composing
  ``jax.transfer_guard`` with the PR 4 recompile listener (plus a
  CPU-effective host-fetch tripwire) to fail a test on any unexpected
  host transfer or recompile inside a guarded region.  It is what
  *enforces by construction* the serving engine's compiled-shapes
  contract and the flagship step's steady-state no-sync property.

- **compiled artifacts** (ISSUE 13) — :mod:`~apex_tpu.analysis.hlo`
  parses each registered executable's optimized HLO into an
  :class:`~apex_tpu.analysis.hlo.ExecutableReport` (verified
  input→output donation, per-opcode collective inventory with bytes,
  host-interaction ops, temp/arg/output bytes) and diffs it against
  the committed ``hlo_contracts.json``::

      python -m apex_tpu.analysis hlo [--update] [--only NAME] [--json]

  Exit 0 = clean, 1 = violations or stale contract entries, 2 =
  missing/unparseable contract or unbuildable artifact.  The
  executable registry is :mod:`~apex_tpu.analysis.registry` (imported
  lazily — it pulls in jax + the serving/flagship stacks).

See docs/analysis.md for the rule catalog (with the incident each
rule encodes), suppression/baseline syntax, the contract schema, and
CI wiring.
"""

from apex_tpu.analysis.framework import (  # noqa: F401
    Baseline,
    Finding,
    LintResult,
    Rule,
    default_rules,
    lint_paths,
    lint_source,
    normalize_path,
)
from apex_tpu.analysis.hlo import (  # noqa: F401
    CheckResult,
    ContractFileError,
    ExecutableReport,
    check_contract,
    check_reports,
    collective_inventory,
    executable_report,
    host_interaction_ops,
    load_contracts,
    parse_aliases,
)
from apex_tpu.analysis.rules import RULES  # noqa: F401
from apex_tpu.analysis.runtime import (  # noqa: F401
    GuardReport,
    HotPathViolation,
    hot_path_guard,
)

__all__ = [
    "Baseline",
    "CheckResult",
    "ContractFileError",
    "ExecutableReport",
    "Finding",
    "GuardReport",
    "HotPathViolation",
    "LintResult",
    "RULES",
    "Rule",
    "check_contract",
    "check_reports",
    "collective_inventory",
    "default_rules",
    "executable_report",
    "hot_path_guard",
    "host_interaction_ops",
    "lint_paths",
    "lint_source",
    "load_contracts",
    "normalize_path",
    "parse_aliases",
]
