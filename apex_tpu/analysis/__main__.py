"""CLI: ``python -m apex_tpu.analysis lint|hlo …``.

Two exit-code CI gates:

- ``lint [paths] [--baseline FILE]`` — the PR 11 AST linter.  0 =
  clean against the baseline, 1 = non-baselined findings (or stale
  baseline entries under ``--strict-baseline``), 2 = usage error.
- ``hlo [--contracts FILE] [--update] [--only NAME] [--json]`` — the
  ISSUE 13 compiled-artifact contract checker: compiles every
  registered executable at cpu-toy geometry and diffs its report
  against ``hlo_contracts.json``.  0 = clean, 1 = contract violations
  or stale contract entries (an entry for a deleted executable fails
  loudly), 2 = missing-or-unparseable contract / unbuildable artifact
  (the r4 ``parsed:null`` lesson: an unreadable gate must not pass
  green).  ``--update`` rewrites the contracts from the current
  artifacts — review the diff before committing.

``--json`` emits a machine-readable report for tooling.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from apex_tpu.analysis.framework import (Baseline, default_rules,
                                         lint_paths)

#: The committed ledgers' conventional home: the repo root (the
#: directory holding the ``apex_tpu`` package).
DEFAULT_BASELINE = "analysis_baseline.json"
DEFAULT_CONTRACTS = "hlo_contracts.json"


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _find_default_file(fname: str) -> Optional[str]:
    for root in (os.getcwd(), os.path.dirname(_package_root())):
        p = os.path.join(root, fname)
        if os.path.isfile(p):
            return p
    return None


def _find_default_baseline() -> Optional[str]:
    return _find_default_file(DEFAULT_BASELINE)


def _cmd_hlo(args) -> int:
    """The ``hlo`` subcommand body (exit codes in the module
    docstring).  Registry/jax imports are deferred so ``lint`` stays
    AST-speed."""
    from apex_tpu.analysis import hlo as H
    from apex_tpu.analysis import registry as R

    try:
        R.ensure_cpu_toy_platform()
    except RuntimeError as e:
        print(f"hlo: {e}", file=sys.stderr)
        return 2
    names = R.registered_executables()
    only = args.only or None
    if only:
        unknown = sorted(set(only) - set(names))
        if unknown:
            print(f"hlo: unknown executable(s) {', '.join(unknown)}; "
                  f"registered: {', '.join(names)}", file=sys.stderr)
            return 2
    reports, errors = R.build_all_reports(only=only)
    if errors:
        for name, err in sorted(errors.items()):
            print(f"hlo: building {name} failed: {err}", file=sys.stderr)
        print("hlo: an unbuildable artifact cannot gate green (exit 2)",
              file=sys.stderr)
        return 2

    cpath = args.contracts or _find_default_file(DEFAULT_CONTRACTS)
    if args.update:
        if cpath is None:
            cpath = os.path.join(os.path.dirname(_package_root()),
                                 DEFAULT_CONTRACTS)
        previous = None
        if only and os.path.isfile(cpath):
            try:
                previous = H.load_contracts(cpath)
            except H.ContractFileError:
                previous = None   # rewriting an unreadable file is fine
        H.save_contracts(cpath, reports, previous=previous)
        print(f"hlo: wrote {len(reports)} contract(s) to {cpath}")
        return 0

    if cpath is None:
        print(f"hlo: no {DEFAULT_CONTRACTS} found (generate one with "
              "--update)", file=sys.stderr)
        return 2
    try:
        doc = H.load_contracts(cpath)
    except H.ContractFileError as e:
        print(f"hlo: {e}", file=sys.stderr)
        return 2

    result = H.check_reports(reports, doc, registry_names=names)
    if args.as_json:
        print(json.dumps({
            "contracts": cpath,
            "geometry": doc.get("geometry"),
            "reports": {n: r.to_json() for n, r in sorted(reports.items())},
            **result.to_json(),
        }, indent=2))
    else:
        n_viol = 0
        for name in sorted(reports):
            for v in result.violations.get(name, []):
                print(f"{name}: {v}")
                n_viol += 1
        for name in result.missing:
            print(f"{name}: registered executable has no contract entry "
                  f"in {cpath} (run --update)")
        for name in result.stale:
            print(f"{name}: stale contract entry — no such registered "
                  "executable (delete it, or restore the executable)")
        print(f"{n_viol} violation(s) over {len(reports)} executable(s) "
              f"({len(result.missing)} missing contract(s), "
              f"{len(result.stale)} stale entr"
              f"{'y' if len(result.stale) == 1 else 'ies'}) "
              f"[geometry: {doc.get('geometry')}]")
    return result.exit_code


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="Project-invariant linter (ISSUE 11). "
                    "See docs/analysis.md for the rule catalog.")
    sub = parser.add_subparsers(dest="cmd")

    lint = sub.add_parser("lint", help="lint files/dirs; exit 1 on "
                                       "non-baselined findings")
    lint.add_argument("paths", nargs="*",
                      help="files or directories (default: the "
                           "apex_tpu package)")
    lint.add_argument("--baseline", default=None,
                      help=f"baseline JSON (default: {DEFAULT_BASELINE}"
                           " in cwd or next to the package)")
    lint.add_argument("--no-baseline", action="store_true",
                      help="ignore any baseline (show everything)")
    lint.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable report on stdout")
    lint.add_argument("--strict-baseline", action="store_true",
                      help="stale baseline entries also fail the gate")

    sub.add_parser("rules", help="print the rule catalog")

    hlo = sub.add_parser(
        "hlo", help="compiled-artifact contract checker; exit 1 on "
                    "violations/stale entries, 2 on a missing or "
                    "unreadable contract")
    hlo.add_argument("--contracts", default=None,
                     help=f"contracts JSON (default: {DEFAULT_CONTRACTS} "
                          "in cwd or next to the package)")
    hlo.add_argument("--update", action="store_true",
                     help="rewrite the contracts from the current "
                          "artifacts instead of checking")
    hlo.add_argument("--only", action="append", default=None,
                     metavar="NAME",
                     help="check only the named executable(s); "
                          "repeatable")
    hlo.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable report on stdout")

    args = parser.parse_args(argv)
    if args.cmd == "hlo":
        return _cmd_hlo(args)
    if args.cmd == "rules":
        for rule in default_rules():
            print(f"{rule.id}  {rule.title}")
            print(f"       {rule.rationale}")
        return 0
    if args.cmd != "lint":
        parser.print_help()
        return 2

    paths = args.paths or [_package_root()]
    baseline = None
    if not args.no_baseline:
        bpath = args.baseline or _find_default_baseline()
        if args.baseline and not os.path.isfile(args.baseline):
            print(f"baseline not found: {args.baseline}",
                  file=sys.stderr)
            return 2
        if bpath:
            baseline = Baseline.load(bpath)

    try:
        result = lint_paths(paths, baseline=baseline)
    except FileNotFoundError as e:
        print(f"no such path: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({
            "files": result.files,
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
            "stale_baseline": result.stale_baseline,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.format())
        for e in result.stale_baseline:
            print(f"stale baseline entry (matched nothing): "
                  f"{e['rule']} {e['path']} match={e['match']!r}")
        print(f"{len(result.findings)} finding(s) over {result.files} "
              f"file(s) ({len(result.baselined)} baselined, "
              f"{len(result.stale_baseline)} stale baseline entr"
              f"{'y' if len(result.stale_baseline) == 1 else 'ies'})")
    if result.findings:
        return 1
    if args.strict_baseline and result.stale_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
