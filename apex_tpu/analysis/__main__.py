"""CLI: ``python -m apex_tpu.analysis lint [paths] [--baseline FILE]``.

The exit code IS the CI gate: 0 = clean against the baseline, 1 =
non-baselined findings (or stale baseline entries under ``--strict-
baseline``), 2 = usage error.  ``--json`` emits a machine-readable
report for tooling.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from apex_tpu.analysis.framework import (Baseline, default_rules,
                                         lint_paths)

#: The committed baseline's conventional home: the repo root (the
#: directory holding the ``apex_tpu`` package).
DEFAULT_BASELINE = "analysis_baseline.json"


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _find_default_baseline() -> Optional[str]:
    for root in (os.getcwd(), os.path.dirname(_package_root())):
        p = os.path.join(root, DEFAULT_BASELINE)
        if os.path.isfile(p):
            return p
    return None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="Project-invariant linter (ISSUE 11). "
                    "See docs/analysis.md for the rule catalog.")
    sub = parser.add_subparsers(dest="cmd")

    lint = sub.add_parser("lint", help="lint files/dirs; exit 1 on "
                                       "non-baselined findings")
    lint.add_argument("paths", nargs="*",
                      help="files or directories (default: the "
                           "apex_tpu package)")
    lint.add_argument("--baseline", default=None,
                      help=f"baseline JSON (default: {DEFAULT_BASELINE}"
                           " in cwd or next to the package)")
    lint.add_argument("--no-baseline", action="store_true",
                      help="ignore any baseline (show everything)")
    lint.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable report on stdout")
    lint.add_argument("--strict-baseline", action="store_true",
                      help="stale baseline entries also fail the gate")

    sub.add_parser("rules", help="print the rule catalog")

    args = parser.parse_args(argv)
    if args.cmd == "rules":
        for rule in default_rules():
            print(f"{rule.id}  {rule.title}")
            print(f"       {rule.rationale}")
        return 0
    if args.cmd != "lint":
        parser.print_help()
        return 2

    paths = args.paths or [_package_root()]
    baseline = None
    if not args.no_baseline:
        bpath = args.baseline or _find_default_baseline()
        if args.baseline and not os.path.isfile(args.baseline):
            print(f"baseline not found: {args.baseline}",
                  file=sys.stderr)
            return 2
        if bpath:
            baseline = Baseline.load(bpath)

    try:
        result = lint_paths(paths, baseline=baseline)
    except FileNotFoundError as e:
        print(f"no such path: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({
            "files": result.files,
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
            "stale_baseline": result.stale_baseline,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.format())
        for e in result.stale_baseline:
            print(f"stale baseline entry (matched nothing): "
                  f"{e['rule']} {e['path']} match={e['match']!r}")
        print(f"{len(result.findings)} finding(s) over {result.files} "
              f"file(s) ({len(result.baselined)} baselined, "
              f"{len(result.stale_baseline)} stale baseline entr"
              f"{'y' if len(result.stale_baseline) == 1 else 'ies'})")
    if result.findings:
        return 1
    if args.strict_baseline and result.stale_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
