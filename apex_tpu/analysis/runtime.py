"""Runtime sanitizer: ``hot_path_guard`` — fail the test, not the SLO.

The static rules (``rules.py``) catch what an AST can see; this is the
other half, for the invariants only a live region can prove:

- **no recompiles** — the serving engine's contract is exactly TWO
  compiled shapes for its lifetime (PR 8), and the flagship train
  step's steady state is zero compiles after the first step.  The
  guard counts XLA backend compiles via the PR 4
  :func:`~apex_tpu.telemetry.install_recompile_listener` (callback-
  only mode, no bus needed) and raises :class:`HotPathViolation` on
  exit when the region compiled more than ``max_recompiles`` times;
- **no host syncs** — composes two mechanisms, because they cover
  different backends:

  1. ``jax.transfer_guard(transfers)`` — the runtime's own guard.  On
     device backends it makes any implicit transfer raise at the
     offending call.  On the CPU backend transfers are zero-copy and
     the runtime does NOT guard them — which is exactly where CI runs;
  2. a Python-level **host-fetch tripwire**: for the guarded region,
     ``jax.device_get``, ``jax.block_until_ready``, and the jax array
     ``.item()``/``.block_until_ready()`` methods raise
     :class:`HotPathViolation` immediately.  This works on every
     backend, so the CPU test tier can pin (and seed-violate) the
     no-sync property deterministically.

  Known limit: a ``np.asarray(device_value)`` goes through numpy's C
  buffer path and only the real transfer guard sees it — the CPU tier
  catches it statically instead (HS001).

Usage (the contracts ISSUE 11 pins in ``tests/L0/test_analysis.py``)::

    engine.warmup()                    # both shapes compile here
    with hot_path_guard("serving lifetime", transfers=None):
        engine.serve(trace)            # any further compile raises

    step(state, batch)                 # first call compiles
    with hot_path_guard("steady state") as guard:
        for b in batches:
            state, loss = step(state, b)   # no sync, no recompile
    assert guard.recompiles == 0

The tripwire patches process-global attributes for the duration of the
region — guard one region at a time from the main thread (tests), not
concurrent production threads; production enforcement on device
backends is ``jax.transfer_guard`` alone (``tripwire=False``).
"""

from __future__ import annotations

import contextlib
from typing import List, Optional

__all__ = ["HotPathViolation", "GuardReport", "hot_path_guard"]


class HotPathViolation(AssertionError):
    """A guarded hot region host-synced or recompiled unexpectedly."""


class GuardReport:
    """What the guarded region did: compile walls (seconds) and the
    first host-sync description (when ``raise_on_sync=False``)."""

    def __init__(self, label: str):
        self.label = label
        self.compile_s: List[float] = []
        self.syncs: List[str] = []

    @property
    def recompiles(self) -> int:
        return len(self.compile_s)


def _patch_host_fetch(report: GuardReport, raise_on_sync: bool):
    """Install the host-fetch tripwire; returns an undo callable."""
    import jax

    def trip(what: str):
        report.syncs.append(what)
        if raise_on_sync:
            raise HotPathViolation(
                f"host sync `{what}` inside guarded hot path "
                f"'{report.label}' — fetch outside the region or once "
                "per logging window (HS001's runtime twin)")

    orig_get = jax.device_get
    orig_block = jax.block_until_ready

    def guarded_get(*a, **k):
        trip("jax.device_get")
        return orig_get(*a, **k)

    def guarded_block(*a, **k):
        trip("jax.block_until_ready")
        return orig_block(*a, **k)

    jax.device_get = guarded_get
    jax.block_until_ready = guarded_block

    undo_methods = []
    try:
        import jaxlib.xla_extension as _xe

        cls = _xe.ArrayImpl
        for meth in ("item", "block_until_ready"):
            orig = getattr(cls, meth, None)
            if orig is None:
                continue

            def make(meth=meth, orig=orig):
                def guarded(self, *a, **k):
                    trip(f"Array.{meth}")
                    return orig(self, *a, **k)
                return guarded

            setattr(cls, meth, make())
            undo_methods.append((cls, meth, orig))
    except Exception:  # pragma: no cover — jaxlib layout moved; the
        pass           # function-level wraps above still apply

    def undo():
        jax.device_get = orig_get
        jax.block_until_ready = orig_block
        # restore uses the same setattr that installed the wrapper, so
        # it cannot fail where installation succeeded
        for cls, meth, orig in undo_methods:
            setattr(cls, meth, orig)

    return undo


@contextlib.contextmanager
def hot_path_guard(label: str = "hot path", *,
                   max_recompiles: int = 0,
                   transfers: Optional[str] = "disallow",
                   tripwire: bool = True,
                   raise_on_sync: bool = True,
                   telemetry=None):
    """Guard a region against unexpected recompiles and host syncs.

    ``max_recompiles`` — XLA backend compiles tolerated inside the
    region (0 = the steady-state contract); exceeding it raises
    :class:`HotPathViolation` on exit, with the compile walls in the
    message.  ``transfers`` — a ``jax.transfer_guard`` level
    (``"disallow"``, ``"log"``, …) or None to leave transfers
    unguarded (the serving engine legitimately moves one token batch
    per step).  ``tripwire`` — install the Python-level host-fetch
    tripwire (CPU-effective; see module doc); ``raise_on_sync=False``
    records syncs on the report instead of raising.  ``telemetry`` —
    optional bus; compiles inside the region additionally emit
    ``recompile`` events.

    Yields a :class:`GuardReport` (``recompiles``, ``compile_s``,
    ``syncs``)."""
    import jax

    from apex_tpu.telemetry.bus import install_recompile_listener

    report = GuardReport(label)
    uninstall = install_recompile_listener(
        telemetry, on_duration=report.compile_s.append)
    undo_tripwire = (_patch_host_fetch(report, raise_on_sync)
                     if tripwire else lambda: None)
    try:
        if transfers is None:
            yield report
        else:
            with jax.transfer_guard(transfers):
                yield report
    finally:
        undo_tripwire()
        uninstall()
    if report.recompiles > max_recompiles:
        walls = ", ".join(f"{s * 1e3:.1f}ms" for s in report.compile_s)
        raise HotPathViolation(
            f"{report.recompiles} XLA compile(s) inside guarded hot "
            f"path '{label}' (allowed {max_recompiles}) — compile "
            f"walls: [{walls}].  A steady-state region must reuse its "
            "compiled executables; a new shape mid-region is the "
            "silent step-time cliff the recompile listener exists for")
