"""Registered hot executables for the compiled-artifact contract
checker (ISSUE 13).

Each entry is a zero-argument builder returning a ``jax.stages.
Lowered`` for one production executable at **cpu-toy geometry** —
small enough to compile on the CPU backend in seconds, shaped exactly
like the production artifact (same program structure, same donation
spec, same collective pattern; only the dimension sizes shrink).  The
``hlo`` CLI subcommand and the tier-1 gate compile every entry and
diff its :class:`~apex_tpu.analysis.hlo.ExecutableReport` against the
committed ``hlo_contracts.json``.

The registry (12 entries):

- the serving engine's five compiled shapes (prefill row, decode,
  admission scatter, speculative verify, chunked prefill) — derived
  from :data:`apex_tpu.serving.engine.SERVING_EXECUTABLES`, lowered by
  ``ServingEngine.analysis_executables()`` with the TPU pool donation
  forced on;
- the r17 tp-sharded serving hot path (``serving_tp_decode`` /
  ``serving_tp_verify`` / ``serving_tp_chunk``): the same engine at
  ``tp=2`` over the :data:`~apex_tpu.transformer.parallel_state.
  TENSOR_AXIS` with the int8 KV pool, so the contract pins BOTH r17
  artifacts at once — the collective inventory of the sharded decode
  step (per-block residual ``psum`` all-reduces and nothing else: an
  unexpected all-gather on the decode hot path is a contract
  violation) and the quantized pool operands (int8 code planes + f32
  scale planes as loop carries, donation end-to-end across all four);
- the dp×tp flagship train step (mesh ``(2, 2, 1)``) — since ISSUE 15
  this is the **bucketed-overlap** ZeRO step at the toy bucket cap
  :data:`FLAGSHIP_BUCKET_BYTES`: the contract pins the ratcheted
  inventory (tp activation all-reduces + one reduce-scatter/all-gather
  pair per bucket; the per-leaf boundary grad all-reduces of the
  serialized construction are GONE, and the old step's 30-all-reduce
  inventory now FAILS this entry — the control in
  tests/L0/test_hlo_contracts.py proves it);
- the ZeRO flat optimizer update (``FlatFusedAdam.jit_step`` — the
  ``input_output_aliases={1:0, 3:1, 4:2}`` donation story verified at
  the entry boundary) plus its bucketed twin
  (``zero_flat_adam_update_bucketed``: one kernel launch per plan
  span, donation still end-to-end);
- ``reshard_stack`` (the device twin ``reshard_stack_device``) — pure
  data movement: zero collectives, zero host interaction.

Builders are deliberately lazy (imports inside) so ``python -m
apex_tpu.analysis lint`` never pays for serving/flagship imports.
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Callable, Dict, Optional, Sequence, Tuple

from apex_tpu.analysis.hlo import ExecutableReport, executable_report

__all__ = [
    "FLAGSHIP_MESH",
    "FLAGSHIP_TOY",
    "SERVING_TOY",
    "build_all_reports",
    "build_report",
    "ensure_cpu_toy_platform",
    "register",
    "registered_executables",
]

# -- the cpu-toy geometry (the contracts file's provenance stamp) ---------

#: Serving model + engine knobs — the test_serving toy config with the
#: full ISSUE 12 draft–verify subsystem enabled so all five compiled
#: shapes exist.
SERVING_TOY = dict(vocab_size=64, hidden_size=32, num_heads=4,
                   num_layers=2, max_position=96)
SERVING_ENGINE_TOY = dict(num_pages=24, page_size=16, max_batch=4,
                          prefill_budget=32)
SERVING_SPEC_K = 2
SERVING_CHUNK = 16

#: r17 tp-sharded serving geometry: tensor world 2 (the smallest mesh
#: where the boundary psums appear in the artifact) + the int8 KV
#: pool, so one extra toy engine covers both new serving modes.
SERVING_TP = 2
SERVING_KV_QUANT = "int8"
#: The tp entries are the HOT PATH only: prefill/admission run once
#: per request and their tp variants add compile time to every gate
#: run without pinning anything the decode-path entries don't.
SERVING_TP_EXECUTABLES = ("decode", "verify", "chunk")

#: Flagship: the test_flagship toy GPT on a dp=2 × tp=2 mesh — the
#: smallest geometry where the ZeRO scatter/gather AND the tp
#: all-reduces both appear in the artifact.
FLAGSHIP_TOY = dict(num_layers=2, hidden_size=256, num_attention_heads=2,
                    vocab_size=256, max_position_embeddings=64)
FLAGSHIP_MESH = (2, 2, 1)
FLAGSHIP_BATCH = 4

#: Toy bucket cap for the flagship entry (ISSUE 15): small enough that
#: the ~1.7M-param toy buffer splits into several buckets, so the
#: contract really pins the per-bucket reduce-scatter/all-gather
#: structure (the production default, DEFAULT_BUCKET_BYTES, would be a
#: single bucket at this geometry).
FLAGSHIP_BUCKET_BYTES = 1 << 20

#: Flat-Adam superblock length (must be a multiple of 8·128).
FLAT_ADAM_N = 8 * 1024

#: Span plan for the bucketed flat-Adam entry: three sublane-aligned
#: spans over the FLAT_ADAM_N buffer (a single leaf cannot be split by
#: the DDP leaf-cap planner — that IS reference semantics — so the
#: registry pins a hand-built plan the way a sharded caller would).
FLAT_ADAM_SPANS = ((0, 2048), (2048, 4096), (4096, FLAT_ADAM_N))

#: reshard_stack geometry: a (dp=4, tp=2) stack merging into (8,) —
#: the constant-world-size C-order merge of the PR 6 contract.
RESHARD_FROM = (4, 2, 1024)
RESHARD_TO = (8, 1024)


_REGISTRY: Dict[str, Callable[[], object]] = {}


def register(name: str):
    """Decorator: register a zero-arg ``() -> jax.stages.Lowered``
    builder under ``name``."""
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def registered_executables() -> Tuple[str, ...]:
    """Registry names in registration order — the set the contracts
    file must cover, and the set its entries are judged stale
    against."""
    return tuple(_REGISTRY)


def ensure_cpu_toy_platform(min_devices: int = 4) -> None:
    """Force the cpu-toy platform the contracts are stamped with: CPU
    backend, >= ``min_devices`` emulated host devices (the flagship
    entry needs a (2, 2, 1) mesh).  Must run before jax's first
    backend touch; a no-op under the tier-1 conftest, which sets up
    the same thing.  Raises RuntimeError when the backend already
    initialized some other way — the checker must not silently
    compile contracts at a geometry the committed file wasn't stamped
    with."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.default_backend() != "cpu" or jax.local_device_count() < min_devices:
        raise RuntimeError(
            f"cpu-toy platform unavailable: backend="
            f"{jax.default_backend()!r} with {jax.local_device_count()} "
            f"device(s), need cpu with >= {min_devices} (run in a fresh "
            "process, or set XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8 before jax initializes)")


# -- builders -------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _toy_engine():
    from apex_tpu.serving.engine import ServingEngine
    from apex_tpu.serving.model import ServingModelConfig
    from apex_tpu.serving.spec import SpecConfig

    cfg = ServingModelConfig(**SERVING_TOY)
    return ServingEngine(
        cfg, **SERVING_ENGINE_TOY,
        spec=SpecConfig(k=SERVING_SPEC_K, chunk_size=SERVING_CHUNK))


@functools.lru_cache(maxsize=1)
def _serving_lowered():
    # one analysis_executables() sweep serves all five serving
    # builders — per-builder calls would re-trace the whole model
    # five times per gate run
    return _toy_engine().analysis_executables()


def _serving_builder(exec_name: str):
    def build():
        return _serving_lowered()[exec_name]
    build.__name__ = f"serving_{exec_name}"
    return build


def _register_serving() -> None:
    # table order from the engine's own contract tuple — the registry
    # cannot drift from the compiled-shapes contract
    from apex_tpu.serving.engine import SERVING_EXECUTABLES

    for exec_name in SERVING_EXECUTABLES:
        _REGISTRY[f"serving_{exec_name}"] = _serving_builder(exec_name)


_register_serving()


@functools.lru_cache(maxsize=1)
def _toy_engine_tp():
    from apex_tpu.serving.engine import ServingEngine
    from apex_tpu.serving.model import ServingModelConfig
    from apex_tpu.serving.spec import SpecConfig
    from apex_tpu.transformer.parallel_state import uninitialized_scope

    cfg = ServingModelConfig(**SERVING_TOY)
    # the contract geometry is pinned at tp=2 over the first two local
    # devices; an ambient training mesh (e.g. left registered by an
    # earlier test or a surrounding training process) must not leak
    # into the lowering, so the engine is built under a hidden state
    with uninitialized_scope():
        return ServingEngine(
            cfg, **SERVING_ENGINE_TOY,
            spec=SpecConfig(k=SERVING_SPEC_K, chunk_size=SERVING_CHUNK),
            tp=SERVING_TP, kv_quant=SERVING_KV_QUANT)


@functools.lru_cache(maxsize=1)
def _serving_tp_lowered():
    # same one-sweep economy as _serving_lowered: three builders, one
    # engine trace
    return _toy_engine_tp().analysis_executables()


def _serving_tp_builder(exec_name: str):
    def build():
        return _serving_tp_lowered()[exec_name]
    build.__name__ = f"serving_tp_{exec_name}"
    return build


def _register_serving_tp() -> None:
    for exec_name in SERVING_TP_EXECUTABLES:
        _REGISTRY[f"serving_tp_{exec_name}"] = _serving_tp_builder(exec_name)


_register_serving_tp()


def _flagship_lowered(bucket_bytes):
    import jax
    import jax.numpy as jnp
    from apex_tpu.transformer.testing.flagship import (
        build_flagship_train_step, gpt1p3b_config)

    n_dev = 1
    for d in FLAGSHIP_MESH:
        n_dev *= d
    cfg = gpt1p3b_config(**FLAGSHIP_TOY)
    fs = build_flagship_train_step(
        cfg, plan="bf16_fit", lr=1e-3, devices=jax.devices()[:n_dev],
        donate=True, mesh_shape=FLAGSHIP_MESH, bucket_bytes=bucket_bytes)
    tokens = jnp.zeros(
        (FLAGSHIP_BATCH, cfg.max_position_embeddings), jnp.int32)
    return fs.step.lower(fs.params, fs.opt_state, tokens, tokens)


@register("flagship_dp_tp_step")
def _flagship_dp_tp_step():
    return _flagship_lowered(FLAGSHIP_BUCKET_BYTES)


def flagship_serialized_lowered():
    """The PRE-ISSUE-15 serialized construction (bucket_bytes=None):
    per-leaf boundary grad all-reduces + one monolithic scatter/gather.
    Deliberately NOT registered — it has no contract to pass; the
    tests/L0/test_hlo_contracts.py control compiles it and proves it
    FAILS the ratcheted ``flagship_dp_tp_step`` entry."""
    return _flagship_lowered(None)


@register("zero_flat_adam_update")
def _zero_flat_adam_update():
    import jax
    import jax.numpy as jnp
    from apex_tpu.optimizers.flat import FlatAdamState, FlatFusedAdam

    opt = FlatFusedAdam()
    buf = jax.ShapeDtypeStruct((FLAT_ADAM_N,), jnp.float32)
    state = FlatAdamState(step=jax.ShapeDtypeStruct((), jnp.int32),
                          exp_avg=buf, exp_avg_sq=buf)
    return opt.jit_step().lower(buf, state, buf)


@register("zero_flat_adam_update_bucketed")
def _zero_flat_adam_update_bucketed():
    import jax
    import jax.numpy as jnp
    from apex_tpu.multi_tensor.buckets import BucketPlan
    from apex_tpu.optimizers.flat import FlatAdamState, FlatFusedAdam

    opt = FlatFusedAdam()
    plan = BucketPlan(spans=FLAT_ADAM_SPANS, shard=FLAT_ADAM_N, world=1,
                      bucket_bytes=None)
    buf = jax.ShapeDtypeStruct((FLAT_ADAM_N,), jnp.float32)
    state = FlatAdamState(step=jax.ShapeDtypeStruct((), jnp.int32),
                          exp_avg=buf, exp_avg_sq=buf)
    return opt.jit_step(plan=plan).lower(buf, state, buf)


@register("reshard_stack")
def _reshard_stack():
    import jax
    import jax.numpy as jnp
    from apex_tpu.multi_tensor.flat import reshard_stack_device

    # no donate_argnums: jax pairs a donated input only with a
    # same-shape output, and a reshard changes shape by definition —
    # requesting donation here would just be a warning, and aliasing
    # is deliberately NOT part of this entry's contract (see
    # reshard_stack_device's docstring)
    fn = jax.jit(lambda v: reshard_stack_device(v, RESHARD_TO))
    return fn.lower(jax.ShapeDtypeStruct(RESHARD_FROM, jnp.float32))


# -- report construction --------------------------------------------------


@functools.lru_cache(maxsize=None)
def build_report(name: str) -> ExecutableReport:
    """Lower + compile one registered executable and parse its report.
    Donation is forced on for analysis, so the CPU backend warns it
    cannot honor it — exactly the situation the checker exists to see
    through (the lowering still records the alias pairs); that one
    warning is silenced, nothing else."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown executable {name!r}; registered: "
                       f"{', '.join(_REGISTRY)}")
    lowered = _REGISTRY[name]()
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        compiled = lowered.compile()
    return executable_report(name, compiled)


def build_all_reports(only: Optional[Sequence[str]] = None
                      ) -> Tuple[Dict[str, ExecutableReport],
                                 Dict[str, str]]:
    """Build every (or the ``only``-selected) registered report.
    Returns ``(reports, errors)`` — a builder failure lands in
    ``errors`` instead of aborting the sweep, and the CLI maps any
    error to exit 2: an artifact the checker cannot build/read must
    never gate green."""
    reports: Dict[str, ExecutableReport] = {}
    errors: Dict[str, str] = {}
    for name in registered_executables():
        if only is not None and name not in only:
            continue
        try:
            reports[name] = build_report(name)
        except Exception as e:  # noqa: BLE001 — mapped to exit 2, never pass
            errors[name] = f"{type(e).__name__}: {e}"
    return reports, errors
