"""Rule framework for the project-invariant linter (ISSUE 11).

The moving parts, each deliberately small:

- :class:`Finding` — one violation: rule id, span, message, and the
  offending source line (the ``snippet`` is also the baseline-matching
  anchor, so baselines survive line-number drift);
- :class:`Rule` — the fixture-testable interface: ``check(tree,
  source, path) -> Iterable[Finding]``.  Rules are pure AST walkers:
  the linter NEVER imports the modules it checks (that is what keeps
  the tier-1 lint gate an AST-speed step, and what lets it lint a
  module whose imports would need a TPU);
- inline suppression — ``# lint: disable=RULE[,RULE…]`` on the
  offending line (or on a comment-only line immediately above it)
  waives named rules for that line.  Use it for one-off local
  exceptions; use the baseline for repo-level documented ones;
- :class:`Baseline` — the committed ledger of documented exceptions
  (``analysis_baseline.json``).  Each entry names the rule, the file,
  a ``match`` substring of the offending line, and a one-line
  ``justification``; entries that stop matching anything are reported
  as STALE so the baseline cannot silently outlive its exceptions.

``lint_source`` / ``lint_paths`` are the runners; the CLI in
``__main__`` turns them into an exit-code CI gate.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: ``# lint: disable=HS001`` / ``# lint: disable=HS001,ND001``
DISABLE_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source span."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


class Rule:
    """Base class: subclasses set ``id``/``title``/``rationale`` and
    implement :meth:`check`.  ``rationale`` names the incident the rule
    encodes — a rule nobody can justify is a rule nobody will keep
    green (docs/analysis.md carries the catalog)."""

    id: str = "XX000"
    title: str = ""
    rationale: str = ""

    def check(self, tree: ast.AST, source: str,
              path: str) -> Iterable[Finding]:
        raise NotImplementedError

    # -- helper ----------------------------------------------------------

    def finding(self, path: str, node: ast.AST, message: str,
                source: str = "") -> Finding:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        snippet = ""
        if source and line:
            lines = source.splitlines()
            if 0 < line <= len(lines):
                snippet = lines[line - 1].strip()
        return Finding(self.id, path, line, col, message, snippet)


def suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """line number -> rule ids waived there.  A ``# lint: disable=``
    on a comment-only line also covers the next line (the black-
    friendly form when the offending line has no room)."""
    out: Dict[int, Set[str]] = {}
    for i, ln in enumerate(source.splitlines(), 1):
        m = DISABLE_RE.search(ln)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if ln.strip().startswith("#"):
            out.setdefault(i + 1, set()).update(rules)
    return out


def normalize_path(path: str) -> str:
    """Repo-relative posix path: everything from the last ``apex_tpu/``
    component on (baseline entries and findings agree on this form no
    matter what cwd/absolute prefix the linter was invoked with)."""
    p = str(path).replace(os.sep, "/")
    i = p.rfind("apex_tpu/")
    return p[i:] if i >= 0 else p


class Baseline:
    """The committed documented-exception ledger.

    JSON shape::

        {"format": 1,
         "entries": [{"rule": "HS001",
                      "path": "apex_tpu/serving/engine.py",
                      "match": "np.asarray(next_tok)",
                      "justification": "the one per-step token fetch"}]}

    An entry suppresses findings with the same rule id and path whose
    source line contains ``match``.  Matching is content-anchored, not
    line-anchored, so ordinary edits elsewhere in the file do not
    invalidate the baseline — but deleting the offending line makes
    the entry STALE (reported, so baselines stay honest)."""

    def __init__(self, entries: Sequence[Dict]):
        self.entries: List[Dict] = list(entries)
        self._hits = [0] * len(self.entries)
        for i, e in enumerate(self.entries):
            for key in ("rule", "path", "match", "justification"):
                if not isinstance(e.get(key), str) or not e[key]:
                    raise ValueError(
                        f"baseline entry {i} missing/empty {key!r}: {e}")

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            doc = json.load(f)
        if doc.get("format") != 1:
            raise ValueError(
                f"unknown baseline format {doc.get('format')!r} in {path}")
        return cls(doc.get("entries", []))

    def matches(self, finding: Finding) -> bool:
        # match against the source line OR the message — rules whose
        # offending line is generic (an `except Exception:` handler)
        # anchor on the message, which names the enclosing function
        for i, e in enumerate(self.entries):
            if (e["rule"] == finding.rule
                    and e["path"] == normalize_path(finding.path)
                    and (e["match"] in finding.snippet
                         or e["match"] in finding.message)):
                self._hits[i] += 1
                return True
        return False

    def stale_entries(self) -> List[Dict]:
        """Entries that matched nothing in the last run — the exception
        they documented no longer exists; delete them."""
        return [e for e, n in zip(self.entries, self._hits) if n == 0]


@dataclasses.dataclass
class LintResult:
    """Everything a caller (CLI, CI test) needs to judge a run."""

    findings: List[Finding]            # NOT baselined — these gate
    baselined: List[Finding]           # matched a baseline entry
    stale_baseline: List[Dict]         # baseline entries matching nothing
    files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


def default_rules() -> List[Rule]:
    from apex_tpu.analysis.rules import RULES

    return [cls() for cls in RULES]


def lint_source(source: str, path: str,
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint one source string (the fixture-test entry point).  Inline
    suppressions are applied; baseline matching is the caller's job."""
    rules = list(rules) if rules is not None else default_rules()
    tree = ast.parse(source, filename=path)
    norm = normalize_path(path)
    sup = suppressed_lines(source)
    out: List[Finding] = []
    for rule in rules:
        for f in rule.check(tree, source, norm):
            if rule.id in sup.get(f.line, ()):
                continue
            out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule))


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            raise FileNotFoundError(p)


def lint_paths(paths: Sequence[str], *,
               rules: Optional[Sequence[Rule]] = None,
               baseline: Optional[Baseline] = None) -> LintResult:
    """Lint files/directories; split findings against the baseline."""
    rules = list(rules) if rules is not None else default_rules()
    gating: List[Finding] = []
    waived: List[Finding] = []
    files = 0
    for path in iter_py_files(paths):
        files += 1
        with open(path, encoding="utf-8") as f:
            source = f.read()
        for finding in lint_source(source, path, rules):
            if baseline is not None and baseline.matches(finding):
                waived.append(finding)
            else:
                gating.append(finding)
    stale = baseline.stale_entries() if baseline is not None else []
    return LintResult(findings=gating, baselined=waived,
                      stale_baseline=stale, files=files)


# -- shared AST helpers (used by rules.py and by rule authors) -----------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None when the chain roots
    in anything else (a call result, a subscript…)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def call_attr(node: ast.Call) -> Optional[str]:
    """The trailing attribute of a method-style call (``x.item()`` ->
    ``item``) regardless of what the receiver expression is."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def walk_functions(tree: ast.AST) -> Iterable[Tuple[ast.AST, List[str]]]:
    """Yield every (Async)FunctionDef with its enclosing name stack
    (outermost first), lambdas excluded."""

    def rec(node: ast.AST, stack: List[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, stack
                yield from rec(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, stack + [child.name])
            else:
                yield from rec(child, stack)

    yield from rec(tree, [])
