"""Compiled-artifact contract checker (ISSUE 13).

The PR 11 linter checks what the *source* promises (a ``jit`` call
names ``donate_argnums``, a hot function avoids host syncs).  This
module checks what the *compiler* delivered: it parses an executable's
optimized HLO (``compiled.as_text()``), buffer assignment
(``memory_analysis()``) and cost model (``cost_analysis()``) into a
structured :class:`ExecutableReport` —

- **verified donation** — the ``input_output_alias`` pairs the module
  header actually carries.  A ``donate_argnums`` that XLA dropped
  (shape-changing output, layout mismatch, a refactor that reordered
  arguments) leaves no alias pair, and at flagship scale the old
  buffers ARE the fit margin (the PR 8 768 MB lesson);
- **collective inventory** — per-opcode counts and result-shape bytes,
  under the trace_report anchored-opcode discipline (``all-gather-
  start.3`` counts, a compiler-pass-named row like ``reduce-scatter-
  decomposer`` does not; ``-start``/``-done`` async pairs count ONCE,
  at the start row).  This is the measured communication-per-step
  baseline ROADMAP item 3's overlap work gates against;
- **host interaction** — infeed/outfeed/send/recv and host custom
  calls (``xla_python_cpu_callback`` and friends): the ops that turn
  "zero host syncs after warmup" from prose into a checkable property;
- plus the optimized-HLO opcode histogram (shared with
  :func:`apex_tpu.profiling.opcode_histogram_from_text`) and
  argument/output/temp byte totals.

Reports are diffed against a committed ``hlo_contracts.json`` (per
executable: required aliasing pairs, max collectives per opcode,
allowed host ops, a temp-byte ceiling) by ``python -m
apex_tpu.analysis hlo`` — exit 0 clean, 1 violations (stale contract
entries included: a contract for a deleted executable fails loudly,
PR 11 baseline discipline), 2 missing-or-unparseable contract (the r4
``parsed:null`` lesson: an unreadable gate must not pass green).  The
registry of executables lives in :mod:`apex_tpu.analysis.registry`;
docs/analysis.md "Compiled-artifact contracts" documents the schema
and the ``--update`` workflow.

Counting caveat (same as the HLO flops parser): an instruction inside
a ``while`` body appears once in the HLO text, so a collective inside
a loop counts ONCE regardless of trip count — the inventory is
per-program structure, not per-execution.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "AliasPair",
    "CheckResult",
    "ContractFileError",
    "ExecutableReport",
    "HostOp",
    "check_contract",
    "check_reports",
    "collective_inventory",
    "contract_from_report",
    "executable_report",
    "host_interaction_ops",
    "load_contracts",
    "parse_aliases",
    "parse_instructions",
    "save_contracts",
]

CONTRACTS_FORMAT = 1

#: Provenance stamp written into every contracts file (the BENCH_r10/
#: r12 ``geometry: "cpu-toy"`` discipline): contract byte/count
#: numbers come from CPU-lowerable toy geometry and must not be read
#: as flagship-scale truth.
DEFAULT_GEOMETRY = "cpu-toy"


class ContractFileError(Exception):
    """The contracts file is missing, unparseable, or wrong-format —
    the CLI maps this to exit code 2: an unreadable gate must not
    pass green (the r4 ``parsed:null`` incident)."""


# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------

#: One instruction line: ``[ROOT] %name = <shape> opcode(...)``.  The
#: non-greedy shape group stops at the first identifier followed by an
#: open paren, which is the opcode token (operand shapes live INSIDE
#: the parens).  Computation definitions (``%comp (p: f32[]) -> …``)
#: have no ``=`` and are skipped.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*?)\s*([a-z][a-z0-9\-]*)\(")

#: ``{out,idx}: (param, {param,idx}[, kind])`` entries of the module
#: header's ``input_output_alias={ … }`` block.  The ``: (`` makes the
#: pattern specific to alias entries — layout braces (``{1,0}``) and
#: ``buffer_donor={ {2} }`` entries never match.
_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{([\d,\s]*)\}\s*"
    r"(?:,\s*(may-alias|must-alias))?\)")

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([\d,]*)\]")

#: Anchored collective-opcode matcher — the trace_report discipline
#: transplanted from trace rows to HLO opcode tokens: the opcode, an
#: optional ``-start``/``-done``, then NOTHING.  ``all-reduce`` and
#: ``all-gather-start`` match; ``all-reduce-promotion`` (a compiler
#: PASS name) does not.
COLLECTIVE_OPCODES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast", "ragged-all-to-all")
_COLLECTIVE_RE = re.compile(
    r"^(%s)(-start|-done)?$"
    % "|".join(re.escape(o) for o in COLLECTIVE_OPCODES))

#: Host-interaction opcodes.  send/recv are counted unconditionally:
#: in this project's programs they only appear as host transfers
#: (device-to-device send/recv would come from pipelining machinery
#: the repo does not emit) — being conservative here means a false
#: POSITIVE surfaces for a human to look at, never a silent pass.
_HOST_OPCODES = frozenset(
    ("infeed", "outfeed", "send", "recv", "send-done", "recv-done"))

#: ``custom_call_target`` substrings that mark a custom call as host
#: interaction: python callbacks (``xla_python_cpu_callback``,
#: ``xla_ffi_python_cpu_callback`` — jax.pure_callback/io_callback/
#: debug.print all lower to these) and host-memory offload moves.
#: Pallas (``tpu_custom_call``/``__gpu$…``) matches neither.
_HOST_TARGET_HINTS = ("callback", "host")

_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')


def _shape_bytes(shape: str) -> int:
    """Total bytes of an HLO shape string — ``f32[8,128]{1,0}`` or a
    tuple ``(f32[256]{0}, s32[])``; elements of unknown dtype (token,
    opaque) contribute 0."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape):
        nbytes = _DTYPE_BYTES.get(dtype)
        if nbytes is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * nbytes
    return total


def parse_instructions(hlo_text: str) -> Iterable[Tuple[str, str, str]]:
    """Yield ``(instruction_name, shape_str, opcode)`` for every
    instruction line of an HLO module dump."""
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m:
            yield m.group(1), m.group(2), m.group(3)


@dataclasses.dataclass(frozen=True)
class AliasPair:
    """One verified input→output buffer alias from the module header:
    entry parameter ``param_number`` (sub-index ``param_index`` when
    the parameter is a tuple, usually empty) aliases output tuple
    index ``output_index``."""

    output_index: str          # "0" or "1,0" — tuple index path
    param_number: int
    param_index: str = ""
    kind: str = "may-alias"

    def to_json(self) -> Dict[str, Any]:
        d = {"param": self.param_number, "output": self.output_index}
        if self.param_index:
            d["param_index"] = self.param_index
        return d


def parse_aliases(hlo_text: str) -> List[AliasPair]:
    """``input_output_alias`` pairs of the module header — the
    donation that actually SURVIVED compilation.  An empty list on a
    supposedly-donating executable is exactly the failure this checker
    exists to catch."""
    header = ""
    for line in hlo_text.splitlines():
        if line.startswith("HloModule"):
            header = line
            break
    if "input_output_alias" not in header:
        return []
    out = []
    for m in _ALIAS_ENTRY_RE.finditer(header):
        norm = lambda s: ",".join(t.strip() for t in s.split(",") if t.strip())  # noqa: E731
        out.append(AliasPair(
            output_index=norm(m.group(1)),
            param_number=int(m.group(2)),
            param_index=norm(m.group(3)),
            kind=m.group(4) or "may-alias"))
    return out


def collective_inventory(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Per-opcode collective counts + result-shape bytes.

    Anchored instruction opcodes only (``all-gather-start.3`` counts;
    a pass-named row like ``reduce-scatter-decomposer`` does not);
    async ``-start``/``-done`` pairs count ONCE, at the start row,
    under the base opcode; a collective inside a ``while`` body counts
    once regardless of trip count (module docstring caveat).  Bytes
    are the counted row's result-shape bytes — for an async start
    whose shape is an (operand, result) tuple this over-counts by the
    operand copy, which is the conservative direction."""
    inv: Dict[str, Dict[str, int]] = {}
    for _name, shape, opcode in parse_instructions(hlo_text):
        m = _COLLECTIVE_RE.match(opcode)
        if m is None or m.group(2) == "-done":
            continue
        slot = inv.setdefault(m.group(1), {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += _shape_bytes(shape)
    return inv


@dataclasses.dataclass(frozen=True)
class HostOp:
    """One host-interaction op: infeed/outfeed/send/recv, or a custom
    call whose target is a host callback."""

    opcode: str
    name: str
    target: str = ""

    def to_json(self) -> Dict[str, Any]:
        d = {"opcode": self.opcode, "name": self.name}
        if self.target:
            d["target"] = self.target
        return d


def host_interaction_ops(hlo_text: str) -> List[HostOp]:
    """Every host-interaction op in the program.  ``-done`` halves of
    send/recv pairs are skipped (the pair counts once, like the
    collective inventory's async rule)."""
    out: List[HostOp] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        name, _shape, opcode = m.group(1), m.group(2), m.group(3)
        if opcode in _HOST_OPCODES:
            if opcode.endswith("-done"):
                continue
            out.append(HostOp(opcode=opcode, name=name))
        elif opcode == "custom-call":
            t = _TARGET_RE.search(line)
            target = t.group(1) if t else ""
            if any(h in target.lower() for h in _HOST_TARGET_HINTS):
                out.append(HostOp(opcode=opcode, name=name, target=target))
    return out


# ---------------------------------------------------------------------------
# The report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ExecutableReport:
    """Structured contract-relevant view of one compiled executable."""

    name: str
    aliasing: List[AliasPair]
    collectives: Dict[str, Dict[str, int]]
    host_ops: List[HostOp]
    opcode_histogram: Dict[str, int]
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    flops: float

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "aliasing": [a.to_json() for a in self.aliasing],
            "collectives": {k: dict(v)
                            for k, v in sorted(self.collectives.items())},
            "host_ops": [h.to_json() for h in self.host_ops],
            "opcode_histogram": dict(sorted(
                self.opcode_histogram.items())),
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "flops": self.flops,
        }


def executable_report(name: str, compiled) -> ExecutableReport:
    """Build the report for one ``jax.stages.Compiled``.

    Unlike the degrade-tolerant profiling helpers, an unavailable
    ``as_text`` RAISES here — a contract checker that cannot read the
    artifact must fail loudly (exit 2 at the CLI), never report an
    empty-and-therefore-clean inventory."""
    from apex_tpu.profiling import opcode_histogram_from_text

    text = compiled.as_text()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):   # single-element list on old jax
        cost = cost[0] if cost else {}
    mem = compiled.memory_analysis()
    return ExecutableReport(
        name=name,
        aliasing=parse_aliases(text),
        collectives=collective_inventory(text),
        host_ops=host_interaction_ops(text),
        opcode_histogram=opcode_histogram_from_text(text),
        argument_bytes=int(getattr(mem, "argument_size_in_bytes", 0) or 0),
        output_bytes=int(getattr(mem, "output_size_in_bytes", 0) or 0),
        temp_bytes=int(getattr(mem, "temp_size_in_bytes", 0) or 0),
        flops=float(cost.get("flops", 0.0)),
    )


# ---------------------------------------------------------------------------
# Contracts
# ---------------------------------------------------------------------------


def check_contract(report: ExecutableReport,
                   contract: Dict[str, Any]) -> List[str]:
    """Violations of one executable's contract entry (empty = clean).

    Directions are deliberately one-sided: FEWER collectives than the
    max, MORE aliasing than required, and SMALLER temp than the
    ceiling all pass — the contract pins the floor of the properties
    the hot paths rely on, not an exact fingerprint (run ``--update``
    after a deliberate improvement to ratchet the maxima down)."""
    v: List[str] = []
    have = {(a.param_number, a.output_index) for a in report.aliasing}
    for req in contract.get("required_aliases", []):
        key = (int(req["param"]), str(req["output"]))
        if key not in have:
            v.append(
                f"aliasing: param {key[0]} no longer aliases output "
                f"{{{key[1]}}} — donation did not survive compilation")
    maxc = contract.get("max_collectives", {})
    for op, stat in sorted(report.collectives.items()):
        cap = int(maxc.get(op, 0))
        if stat["count"] > cap:
            v.append(
                f"collectives: {op} x{stat['count']} exceeds the "
                f"contract max of {cap}")
    allow = contract.get("allow_host_ops", [])
    for h in report.host_ops:
        # an allow entry naming a host OPCODE matches only that exact
        # opcode; any other entry is a custom-call target pattern
        # (substring).  Without the split, a blessed `send` op would
        # silently whitelist any host callback whose target happens to
        # contain "send" — the opposite of surface-the-ambiguity.
        ok = any((a == h.opcode) if a in _HOST_OPCODES
                 else bool(h.target and a and a in h.target)
                 for a in allow)
        if not ok:
            extra = f" target={h.target!r}" if h.target else ""
            v.append(
                f"host interaction: {h.opcode} %{h.name}{extra} is not "
                "allowed by the contract")
    cap = contract.get("max_temp_bytes")
    if cap is not None and report.temp_bytes > int(cap):
        v.append(
            f"temp bytes {report.temp_bytes:,} exceed the contract "
            f"ceiling {int(cap):,}")
    return v


def contract_from_report(report: ExecutableReport, *,
                         temp_headroom: float = 1.25) -> Dict[str, Any]:
    """The ``--update`` generator: a contract entry pinning exactly
    what the current artifact delivers (observed aliases required,
    observed collective counts as maxima, observed host ops allowed —
    review the diff before committing), with ``temp_headroom`` slack
    on the temp-byte ceiling so layout jitter doesn't flap the gate.
    The ``inventory`` block is informational provenance (byte counts,
    flops) — the checker ignores it; ROADMAP item 3 reads it."""
    return {
        "required_aliases": [a.to_json() for a in report.aliasing],
        "max_collectives": {op: s["count"] for op, s in
                            sorted(report.collectives.items())},
        "allow_host_ops": sorted({h.target or h.opcode
                                  for h in report.host_ops}),
        "max_temp_bytes": int(math.ceil(report.temp_bytes * temp_headroom)),
        "inventory": {
            "collective_bytes": {op: s["bytes"] for op, s in
                                 sorted(report.collectives.items())},
            "argument_bytes": report.argument_bytes,
            "output_bytes": report.output_bytes,
            "temp_bytes": report.temp_bytes,
            "flops": report.flops,
        },
    }


def load_contracts(path: str) -> Dict[str, Any]:
    """Read + validate a contracts file; any problem raises
    :class:`ContractFileError` (CLI exit 2 — never a green pass)."""
    if not os.path.isfile(path):
        raise ContractFileError(f"contracts file not found: {path}")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ContractFileError(f"unparseable contracts file {path}: {e}")
    if doc.get("format") != CONTRACTS_FORMAT:
        raise ContractFileError(
            f"unknown contracts format {doc.get('format')!r} in {path}")
    if not isinstance(doc.get("executables"), dict):
        raise ContractFileError(f"{path} has no 'executables' table")
    if not isinstance(doc.get("geometry"), str) or not doc["geometry"]:
        raise ContractFileError(
            f"{path} carries no geometry provenance stamp — contract "
            "numbers without a geometry read as flagship-scale truth")
    return doc


def save_contracts(path: str, reports: Dict[str, ExecutableReport], *,
                   geometry: str = DEFAULT_GEOMETRY,
                   previous: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Write contracts generated from ``reports``; entries of
    ``previous`` for executables NOT in ``reports`` are carried over
    (the ``--update --only`` merge path)."""
    execs: Dict[str, Any] = {}
    if previous is not None:
        execs.update(previous.get("executables", {}))
    for name, rep in reports.items():
        execs[name] = contract_from_report(rep)
    doc = {
        "format": CONTRACTS_FORMAT,
        "geometry": geometry,
        "comment": (
            "Machine-written by `python -m apex_tpu.analysis hlo "
            "--update` (docs/analysis.md, 'Compiled-artifact "
            "contracts'). Byte/count numbers are measured at the "
            f"'{geometry}' registry geometry — gate fixtures, not "
            "flagship-scale truth."),
        "executables": {k: execs[k] for k in sorted(execs)},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return doc


@dataclasses.dataclass
class CheckResult:
    """Outcome of one full registry-vs-contracts check.

    ``violations`` maps executable name → its contract violations;
    ``missing`` are registered executables with no contract entry
    (exit 2 — an ungated executable must not pass green); ``stale``
    are contract entries naming no registered executable (exit 1 —
    the PR 11 stale-baseline discipline: a contract cannot outlive
    its executable)."""

    violations: Dict[str, List[str]]
    missing: List[str]
    stale: List[str]

    @property
    def exit_code(self) -> int:
        if self.missing:
            return 2
        if any(self.violations.values()) or self.stale:
            return 1
        return 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "violations": {k: list(v)
                           for k, v in sorted(self.violations.items()) if v},
            "missing": list(self.missing),
            "stale": list(self.stale),
            "exit_code": self.exit_code,
        }


def check_reports(reports: Dict[str, ExecutableReport],
                  doc: Dict[str, Any], *,
                  registry_names: Sequence[str]) -> CheckResult:
    """Diff built reports against a loaded contracts doc.

    ``registry_names`` is the FULL registry (staleness is judged
    against every registered executable, so a ``--only``-restricted
    run cannot misread an unselected executable's entry as stale)."""
    execs = doc.get("executables", {})
    violations = {name: check_contract(rep, execs[name])
                  for name, rep in sorted(reports.items())
                  if name in execs}
    missing = sorted(n for n in reports if n not in execs)
    stale = sorted(n for n in execs if n not in registry_names)
    return CheckResult(violations=violations, missing=missing, stale=stale)
