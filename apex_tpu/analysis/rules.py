"""The rule catalog: this repo's hard-won invariants as lint rules.

Every rule encodes an incident this codebase actually paid for (the
catalog with full war stories is docs/analysis.md):

- **HS001** — host sync in a hot path.  PR 4's accountant exists
  because per-step scalar fetches serialize pipelined dispatch; the
  contract is ONE batched ``device_get`` per logging window.  A stray
  ``.item()`` / ``jax.device_get`` / ``block_until_ready`` /
  ``np.asarray`` inside a jitted function or one of the named hot
  loops (serving decode, resilient-training step loop) reintroduces
  exactly that stall.
- **ND001** — unseeded nondeterminism in a bitwise-contract module.
  ``serving/``, ``data/``, ``checkpoint/`` and ``multi_tensor/`` all
  pin bitwise reproducibility (batched==sequential decoding,
  exactly-once resume, reshard round trips); a bare ``random.*`` /
  ``np.random.*`` draw or a ``time.time()`` feeding logic breaks those
  contracts invisibly.  Seeded generators (``np.random.RandomState``,
  ``np.random.Philox``, ``jax.random.PRNGKey``) are the sanctioned
  forms.
- **DN001** — pool-sized jit call sites without donation.  PR 8's
  ``write_tokens`` lesson: an undonated scatter held old+new KV pool
  alive — ~768 MB of HBM per admission on the TTFT-critical path.
  Flag, don't guess: a ``jax.jit`` over a function with pool/state-
  sized parameters and no ``donate_argnums``/``donate`` is reported
  with the parameter names; the author decides (and a deliberate
  no-donate site says so with a kwarg or a baseline entry).
- **TL001** — telemetry emit sites are held to the single-sourced
  :data:`~apex_tpu.telemetry.schema.EVENT_FIELDS` table: unknown event
  types, literal field names outside the spec, and int-literals where
  the schema says bool (the PR 4 bool-not-int discipline) are all
  build-time errors now, not stream-validation surprises later.
- **TH001** — lock discipline around thread boundaries.  The
  prefetcher/watchdog/async-writer pattern shares attributes between a
  worker thread and the caller; an attribute assigned on both sides of
  the boundary with either side outside a lock is a data race waiting
  for a scheduler change.
- **EX001** — exception swallowing in run loops.  A broad ``except``
  whose body is just ``pass``/``continue`` inside a loop turns a hard
  fault into a silent skip-forever; sinks and teardown paths
  (``close``/``__exit__``/…) are the documented exception.

Rules are pure AST walkers — nothing here imports jax or the checked
modules.  TL001 imports :mod:`apex_tpu.telemetry.schema`, which is
deliberately stdlib-only.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from apex_tpu.analysis.framework import (Finding, Rule, call_attr,
                                         call_name, dotted_name,
                                         walk_functions)

# ---------------------------------------------------------------------------
# HS001 — host sync in a hot path
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_NP_ROOTS = ("np", "numpy", "onp")

#: Named hot loops that are not jit-decorated but ARE the steady-state
#: path (the serving decode loop, the resilient-training step loop, the
#: accountant's fetch seam).  Nested helpers inherit hotness.
HOT_PATH_FUNCTIONS: Dict[str, Set[str]] = {
    "apex_tpu/serving/engine.py": {
        "_decode_batch", "_prefill_request", "_step_body",
        # ISSUE 12: the speculative verify step, the chunked-prefill
        # step, and the draft-proposal loop run at every decode
        # boundary — same steady-state heat as _decode_batch
        "_verify_batch", "_chunk_step", "_propose_drafts",
        # r19: span emission rides retirement and the decode loop —
        # tracing must stay pure host bookkeeping, never a device pull
        "_retire", "_emit_retire_spans"},
    "apex_tpu/serving/kv_cache.py": {"_page_digest"},
    # ISSUE 12: proposer lookup (per decode boundary per request) and
    # the chunk splitter (per boundary)
    "apex_tpu/serving/spec/proposer.py": {"propose", "_reindex"},
    "apex_tpu/serving/scheduler.py": {"schedule_prefill"},
    # ISSUE 16: the fleet round — placement, health probing, and the
    # migration hop all run between engine steps; a host sync or a
    # device pull here stalls EVERY replica, not one
    "apex_tpu/serving/fleet/router.py": {
        "route", "_migrate_requests", "_health_check"},
    # r18: every cross-replica payload serializes/delivers through the
    # transport, and the disaggregation pump drives page shipments
    # every fleet round — pure host json/zlib/base64 work; a device
    # pull here would stall the whole fleet per message
    "apex_tpu/serving/fleet/transport.py": {"call", "deliver"},
    # r19: the ship/import span emitters and the page handlers run per
    # wire message inside the pump — tracing overhead must stay host-
    # side (and sync-free) at the same heat as the pump itself
    "apex_tpu/serving/fleet/disagg.py": {
        "_pump_disagg", "_drive", "_emit_ship_span",
        "on_page", "on_commit"},
    "apex_tpu/transformer/testing/train_loop.py": {
        "run_resilient_training"},
    "apex_tpu/resilience/elastic.py": {"run_elastic_training"},
    "apex_tpu/telemetry/accounting.py": {"step_done", "fetch_scalars"},
    # ISSUE 15: the bucketed-overlap ZeRO data path — the per-bucket
    # scatter/update/gather walk and the flagship's fused inner step
    # run every training step; the planner runs at build time but its
    # output is closed over in jit, so it must stay host-sync-free too
    "apex_tpu/multi_tensor/buckets.py": {"plan_buckets"},
    "apex_tpu/contrib/optimizers/distributed_fused.py": {"step_buckets"},
    "apex_tpu/transformer/testing/flagship.py": {"_bucketed_zero_inner"},
}


def _decorator_is_jit(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = call_name(dec)
        if fname in _JIT_NAMES:
            return True
        if fname in _PARTIAL_NAMES and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


def _jitted_function_names(tree: ast.AST) -> Set[str]:
    """Names X for every ``jax.jit(X, …)`` call site in the module —
    local defs later wrapped (``self._decode_fn = jax.jit(_decode,
    donate_argnums=…)``) are hot even though undecorated."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and call_name(node) in _JIT_NAMES
                and node.args and isinstance(node.args[0], ast.Name)):
            out.add(node.args[0].id)
    return out


class HostSyncInHotPath(Rule):
    id = "HS001"
    title = "host sync in a hot path"
    rationale = (
        "PR 4 one-fetch-per-window: per-step device fetches serialize "
        "pipelined dispatch; inside @jax.jit they are trace-time bugs")

    SYNC_CALLS = {"jax.device_get", "device_get",
                  "jax.block_until_ready"}
    # attribute-matched forms catch aliased imports too (`import jax
    # as _jax; _jax.device_get(...)` — found the hard way in the train
    # loop's log path on this rule's first run)
    SYNC_ATTRS = {"block_until_ready", "device_get"}
    NP_PULLS = {f"{r}.{fn}" for r in _NP_ROOTS
                for fn in ("asarray", "ascontiguousarray", "array")}

    def check(self, tree: ast.AST, source: str,
              path: str) -> Iterable[Finding]:
        jitted = _jitted_function_names(tree)
        table = HOT_PATH_FUNCTIONS.get(path, set())
        findings: List[Finding] = []
        rule = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.hot: List[str] = []   # stack of hot function names

            def _is_hot_def(self, node) -> bool:
                if any(_decorator_is_jit(d) for d in node.decorator_list):
                    return True
                return node.name in jitted or node.name in table

            def visit_FunctionDef(self, node):
                entered = bool(self.hot) or self._is_hot_def(node)
                if entered:
                    self.hot.append(node.name)
                self.generic_visit(node)
                if entered:
                    self.hot.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node):
                if self.hot:
                    name = call_name(node)
                    attr = call_attr(node)
                    expr = name or (f"….{attr}" if attr else "?")
                    if (attr == "item" and not node.args) \
                            or name in rule.SYNC_CALLS \
                            or attr in rule.SYNC_ATTRS:
                        findings.append(rule.finding(
                            path, node,
                            f"host sync `{expr}()` inside hot path "
                            f"`{self.hot[0]}` — the contract is one "
                            "batched fetch per logging window "
                            "(StepAccountant), and inside @jax.jit a "
                            "host sync is a trace-time bug", source))
                    elif name in rule.NP_PULLS:
                        findings.append(rule.finding(
                            path, node,
                            f"`{name}(…)` inside hot path "
                            f"`{self.hot[0]}` forces a device→host "
                            "copy when fed a device value — fetch once "
                            "per window, or keep the value on device",
                            source))
                self.generic_visit(node)

        V().visit(tree)
        return findings


# ---------------------------------------------------------------------------
# ND001 — unseeded nondeterminism in bitwise-contract modules
# ---------------------------------------------------------------------------

#: Modules carrying a bitwise contract (batched==sequential serving,
#: exactly-once data resume, reshard round trips, flat-buffer math).
CONTRACT_DIRS = ("apex_tpu/serving/", "apex_tpu/data/",
                 "apex_tpu/checkpoint/", "apex_tpu/multi_tensor/")

#: Explicit-generator constructors: seeded at the call site, fine.
_SEEDED_NP = {"RandomState", "Generator", "Philox", "PCG64", "SFC64",
              "MT19937", "default_rng", "SeedSequence", "BitGenerator"}
_SEEDED_RANDOM = {"Random", "SystemRandom"}


class UnseededNondeterminism(Rule):
    id = "ND001"
    title = "unseeded nondeterminism in a bitwise-contract module"
    rationale = (
        "serving/data/checkpoint/multi_tensor pin bitwise claims "
        "(batched==sequential, exactly-once resume, reshard round "
        "trips); global RNG state or wall-clock-in-logic breaks them "
        "invisibly")

    def check(self, tree: ast.AST, source: str,
              path: str) -> Iterable[Finding]:
        if not any(d in path for d in CONTRACT_DIRS):
            return []
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name == "time.time":
                findings.append(self.finding(
                    path, node,
                    "`time.time()` in a bitwise-contract module — "
                    "wall clock in logic is unseeded nondeterminism; "
                    "use an injected clock (SimClock) or "
                    "`time.monotonic` for durations-only", source))
                continue
            parts = name.split(".")
            if parts[0] == "random" and len(parts) == 2 \
                    and parts[1] not in _SEEDED_RANDOM:
                findings.append(self.finding(
                    path, node,
                    f"global-state `{name}()` in a bitwise-contract "
                    "module — use an explicit seeded generator "
                    "(`random.Random(seed)`)", source))
            elif (len(parts) == 3 and parts[0] in _NP_ROOTS
                    and parts[1] == "random"
                    and parts[2] not in _SEEDED_NP):
                findings.append(self.finding(
                    path, node,
                    f"global-state `{name}()` in a bitwise-contract "
                    "module — use an explicit seeded generator "
                    "(`np.random.RandomState(seed)` / "
                    "`np.random.Generator(np.random.Philox(seed))`)",
                    source))
        return findings


# ---------------------------------------------------------------------------
# DN001 — pool-sized jit call sites without donation
# ---------------------------------------------------------------------------

_POOL_PARAM_RE = re.compile(r"pool|cache|buffer", re.IGNORECASE)
_POOL_PARAM_EXACT = {"opt_state"}
_DONATE_KWARGS = {"donate_argnums", "donate_argnames", "donate"}


class MissingDonation(Rule):
    id = "DN001"
    title = "pool/state-sized jit without buffer donation"
    rationale = (
        "PR 8 write_tokens: an undonated pool scatter held old+new "
        "pool alive (~768 MB at bench geometry) per admission on the "
        "TTFT-critical path")

    def _params_of(self, tree: ast.AST, arg0: ast.AST) -> Tuple[str, List[str]]:
        """(label, parameter names) of the jitted callable, when it is
        resolvable statically (a module-local def or a lambda)."""
        if isinstance(arg0, ast.Lambda):
            return "<lambda>", [a.arg for a in arg0.args.args]
        if isinstance(arg0, ast.Name):
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node.name == arg0.id:
                    return node.name, [a.arg for a in node.args.args]
        return "", []

    def check(self, tree: ast.AST, source: str,
              path: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in _JIT_NAMES and node.args):
                continue
            if any(kw.arg in _DONATE_KWARGS for kw in node.keywords):
                continue  # the author decided — even donate=() on CPU
            label, params = self._params_of(tree, node.args[0])
            hits = [p for p in params
                    if _POOL_PARAM_RE.search(p)
                    or p in _POOL_PARAM_EXACT]
            if hits:
                findings.append(self.finding(
                    path, node,
                    f"jax.jit of `{label}` takes pool/state-sized "
                    f"buffer parameter(s) {hits} with no donate_argnums"
                    " — without donation the old and new buffers are "
                    "both live across the call (flag-don't-guess: say "
                    "`donate_argnums=()` if no-donate is deliberate)",
                    source))
        return findings


# ---------------------------------------------------------------------------
# TL001 — telemetry emit sites vs the single-sourced schema table
# ---------------------------------------------------------------------------


class TelemetrySchemaDrift(Rule):
    id = "TL001"
    title = "telemetry emit site drifts from the schema table"
    rationale = (
        "the PR 4 closed event set + bool-not-int discipline, enforced "
        "at lint time from telemetry/schema.py EVENT_FIELDS (the same "
        "table validate_event consumes — one source, no drift)")

    #: The stamp kwarg every emit may pass; not a payload field.
    STAMP_KWARGS = {"step"}

    def __init__(self, event_fields=None):
        if event_fields is None:
            from apex_tpu.telemetry.schema import EVENT_FIELDS

            event_fields = EVENT_FIELDS
        self.event_fields = event_fields

    def _check_literal(self, etype: str, field: str, value: ast.AST,
                       types: tuple) -> Optional[str]:
        if not isinstance(value, ast.Constant):
            return None
        v = value.value
        if isinstance(v, bool):
            if bool not in types:
                return (f"`{etype}.{field}` is "
                        f"{'/'.join(t.__name__ for t in types)} in the "
                        f"schema, got bool literal {v!r}")
            return None
        if v is None:
            if type(None) not in types:
                return (f"`{etype}.{field}` does not allow None in the "
                        "schema (optional means ABSENT, not null)")
            return None
        if isinstance(v, int) and bool in types and int not in types:
            return (f"int literal `{v}` for bool field "
                    f"`{etype}.{field}` — bool-not-int discipline: "
                    f"write {bool(v)}")
        if not isinstance(v, types):
            return (f"`{etype}.{field}` is "
                    f"{'/'.join(t.__name__ for t in types)} in the "
                    f"schema, got {type(v).__name__} literal {v!r}")
        return None

    def check(self, tree: ast.AST, source: str,
              path: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            attr = call_attr(node)
            name = call_name(node)
            is_emit = attr == "emit" or attr == "_emit" \
                or name in ("emit", "_emit")
            if not is_emit or not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # dynamic type (a forwarding wrapper) — skip
            etype = first.value
            if etype not in self.event_fields:
                findings.append(self.finding(
                    path, node,
                    f"unknown telemetry event type {etype!r} — the "
                    "event set is closed; add a field spec to "
                    "telemetry/schema.py EVENT_FIELDS first", source))
                continue
            spec = self.event_fields[etype]
            for kw in node.keywords:
                if kw.arg is None or kw.arg in self.STAMP_KWARGS:
                    continue
                if kw.arg not in spec:
                    findings.append(self.finding(
                        path, node,
                        f"field `{kw.arg}` is not in the schema table "
                        f"for `{etype}` — add it to EVENT_FIELDS "
                        "(typed, required or optional) instead of "
                        "emitting untyped payload", source))
                    continue
                msg = self._check_literal(etype, kw.arg, kw.value,
                                          spec[kw.arg].types)
                if msg:
                    findings.append(self.finding(path, node, msg,
                                                 source))
        return findings


# ---------------------------------------------------------------------------
# TH001 — lock discipline across thread boundaries
# ---------------------------------------------------------------------------

_THREAD_NAMES = {"threading.Thread", "Thread"}
_LOCK_RE = re.compile(r"lock|mutex", re.IGNORECASE)


def _attr_store_target(target: ast.AST) -> Optional[str]:
    """``self.x = …`` -> ``x``; ``self.x[i] = …`` -> ``x``; else None."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        return target.attr
    return None


def _is_lock_ctx(expr: ast.AST) -> bool:
    """``with self._lock:`` / ``with lock:`` / ``with self._lock
    .acquire_timeout(…):`` — anything whose dotted name smells like a
    lock counts as holding one."""
    name = dotted_name(expr)
    if name is None and isinstance(expr, ast.Call):
        name = dotted_name(expr.func)
    return bool(name and _LOCK_RE.search(name))


def _self_attr_stores(fn: ast.AST) -> Dict[str, List[Tuple[ast.AST, bool]]]:
    """attr -> [(node, under_lock)] for every ``self.attr`` store in
    ``fn`` (nested defs included — they run on the same thread)."""
    out: Dict[str, List[Tuple[ast.AST, bool]]] = {}

    def rec(node: ast.AST, locked: bool):
        if isinstance(node, ast.With):
            item_locked = locked or any(_is_lock_ctx(i.context_expr)
                                        for i in node.items)
            for child in node.body:
                rec(child, item_locked)
            return
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            attr = _attr_store_target(t)
            if attr is not None:
                out.setdefault(attr, []).append((node, locked))
        for child in ast.iter_child_nodes(node):
            rec(child, locked)

    for stmt in fn.body:
        rec(stmt, False)
    return out


class LockDiscipline(Rule):
    id = "TH001"
    title = "attribute written on both sides of a thread boundary "\
            "without a lock"
    rationale = (
        "the prefetcher/watchdog/async-writer pattern: worker thread "
        "and caller share attributes — a store on either side outside "
        "the shared lock is a data race")

    def check(self, tree: ast.AST, source: str,
              path: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            workers: List[ast.AST] = []
            for m in methods.values():
                for node in ast.walk(m):
                    if not (isinstance(node, ast.Call)
                            and call_name(node) in _THREAD_NAMES):
                        continue
                    target = next((kw.value for kw in node.keywords
                                   if kw.arg == "target"), None)
                    if target is None:
                        continue
                    tname = dotted_name(target)
                    if tname and tname.startswith("self.") \
                            and tname[5:] in methods:
                        workers.append(methods[tname[5:]])
                    elif isinstance(target, ast.Name):
                        for sub in ast.walk(m):
                            if isinstance(sub, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)) \
                                    and sub.name == target.id:
                                workers.append(sub)
            if not workers:
                continue
            # one level of self-method calls from each worker: the
            # thread body often delegates (`Watchdog._run -> _fire`)
            seen = {id(w) for w in workers}
            for w in list(workers):
                for node in ast.walk(w):
                    if isinstance(node, ast.Call):
                        nm = call_name(node)
                        if nm and nm.startswith("self.") \
                                and nm[5:] in methods \
                                and id(methods[nm[5:]]) not in seen:
                            workers.append(methods[nm[5:]])
                            seen.add(id(methods[nm[5:]]))
            worker_names = {w.name for w in workers}
            worker_stores: Dict[str, List[Tuple[ast.AST, bool]]] = {}
            for w in workers:
                for attr, stores in _self_attr_stores(w).items():
                    worker_stores.setdefault(attr, []).extend(stores)
            other_stores: Dict[str, List[Tuple[ast.AST, bool]]] = {}
            for name, m in methods.items():
                if name in worker_names or name == "__init__":
                    continue
                for attr, stores in _self_attr_stores(m).items():
                    other_stores.setdefault(attr, []).extend(stores)
            for attr in sorted(set(worker_stores) & set(other_stores)):
                unlocked = ([n for n, lk in worker_stores[attr]
                             if not lk]
                            + [n for n, lk in other_stores[attr]
                               if not lk])
                if unlocked:
                    findings.append(self.finding(
                        path, unlocked[0],
                        f"`self.{attr}` is written both inside thread "
                        f"target(s) {sorted(worker_names)} and outside "
                        "them, with at least one store not under a "
                        "shared lock — hold the lock on both sides or "
                        "hand the value over a Queue/Event", source))
        return findings


# ---------------------------------------------------------------------------
# EX001 — exception swallowing in run loops
# ---------------------------------------------------------------------------

_BROAD = {"Exception", "BaseException"}
#: Teardown paths where best-effort swallowing is the documented
#: exception ("sinks are the documented exception").
TEARDOWN_FUNCTIONS = {"close", "__exit__", "__del__", "shutdown",
                      "stop", "drain", "_halt", "_exit_fence"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue, ast.Break))
               for s in handler.body)


class ExceptionSwallowing(Rule):
    id = "EX001"
    title = "broad except swallowed inside a loop"
    rationale = (
        "a broad except whose body is pass/continue inside a run loop "
        "turns a hard fault into a silent skip-forever; log, narrow, "
        "or re-raise (teardown/sink paths are the documented "
        "exception)")

    def check(self, tree: ast.AST, source: str,
              path: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn, _stack in walk_functions(tree):
            if fn.name in TEARDOWN_FUNCTIONS:
                continue

            def scan(node: ast.AST, loop_depth: int):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue  # its own scope; visited separately
                    d = loop_depth
                    if isinstance(child, (ast.For, ast.AsyncFor,
                                          ast.While)):
                        d += 1
                    if isinstance(child, ast.ExceptHandler) \
                            and loop_depth > 0 and _is_broad(child) \
                            and _swallows(child):
                        findings.append(self.finding(
                            path, child,
                            f"broad `except` swallowed inside a loop "
                            f"in `{fn.name}` — a hard fault becomes a "
                            "silent skip-forever; narrow the "
                            "exception, log it, or re-raise", source))
                    scan(child, d)

            scan(fn, 0)
        return findings


#: The catalog, in documentation order.
RULES = [HostSyncInHotPath, UnseededNondeterminism, MissingDonation,
         TelemetrySchemaDrift, LockDiscipline, ExceptionSwallowing]
