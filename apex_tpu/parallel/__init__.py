"""apex_tpu.parallel — data parallelism over the mesh "data" axis.

TPU-native re-design of ``apex.parallel`` (SURVEY.md §2.4): gradient
psum with the reference DDP's numerics options, SyncBatchNorm via Welford
moment combination + psum, LARC (re-exported from optimizers), and the
multi-host launcher shim.
"""

from apex_tpu.optimizers.larc import LARC  # noqa: F401
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    Reducer,
    all_reduce_grads,
    broadcast_params,
)
from apex_tpu.parallel.multiproc import initialize_distributed  # noqa: F401
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
    create_syncbn_process_group,
    sync_batch_norm,
    sync_batch_norm_stats,
)
