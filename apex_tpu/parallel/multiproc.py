"""Multi-process launcher shim.

The reference ships ``python -m apex.parallel.multiproc`` — a subprocess
spawner that sets RANK/WORLD_SIZE per GPU (apex/parallel/multiproc.py:1-35).
On TPU, process-per-host topology is owned by the runtime: inside one host
all local chips belong to one process, and multi-host jobs call
``jax.distributed.initialize`` (coordinator address from the scheduler).
This module keeps the entry point and maps it to that world.
"""

from __future__ import annotations

import os
import sys


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Initialise multi-host JAX. No-op for single-process runs.

    Mirrors what torch.distributed.launch env plumbing (+ multiproc.py)
    achieves for the reference: after this, ``jax.devices()`` spans hosts.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return  # single host: nothing to do
    if num_processes is None:
        num_processes = int(os.environ.get("WORLD_SIZE", 1))
    if process_id is None:
        process_id = int(os.environ.get("RANK", 0))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def main() -> None:  # pragma: no cover - exercised manually
    """``python -m apex_tpu.parallel.multiproc train.py args...`` — run the
    script after distributed init (reference multiproc.py spawns one process
    per device; on TPU one process already owns all local devices)."""
    initialize_distributed()
    if len(sys.argv) > 1:
        script = sys.argv[1]
        sys.argv = sys.argv[1:]
        with open(script) as f:
            code = compile(f.read(), script, "exec")
        exec(code, {"__name__": "__main__", "__file__": script})


if __name__ == "__main__":  # pragma: no cover
    main()
