"""Data-parallel gradient reduction.

TPU-native re-design of ``apex.parallel.DistributedDataParallel``
(reference apex/parallel/distributed.py:129-639) and ``Reducer`` (:89-126).

The reference's machinery — per-parameter autograd hooks, first-iteration
bucket-structure discovery, flatten→NCCL-allreduce→unflatten on side CUDA
streams — exists to overlap communication with backward in an eager engine.
Under jit none of it is needed: data parallelism is a ``lax.psum`` (or
``pmean``) of the grad pytree over the mesh "data" axis inside the compiled
step, and XLA's latency-hiding scheduler overlaps the collectives with the
backward computation automatically.

What *does* carry over is the numerics contract, preserved here exactly:

* ``gradient_average`` → mean vs sum reduction (reference :162,:454-457);
* ``gradient_predivide_factor`` → divide by f before the reduce, by
  world/f after (reference :171-175,:442-443,:453-456) for overflow-safe
  large-world averaging;
* ``allreduce_always_fp32`` → cast bf16/fp16 grads to fp32 for the reduce,
  cast back after (reference :166,:445-448,:459-465).

``DistributedDataParallel`` below is a thin callable wrapper so training
code reads like the reference; ``Reducer`` is its manual-trigger twin.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp


def all_reduce_grads(
    grads: Any,
    axis_name: str = "data",
    *,
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
) -> Any:
    """Reduce a grad pytree across the mesh ``axis_name`` axis.

    Must be called inside a ``pjit``/``shard_map``/``pmap`` context that
    binds ``axis_name``.  Semantics table (reference distributed.py:442-468):

    ========================  =============================================
    gradient_average          divide the summed grads by world size
    gradient_predivide_factor grads/f before psum, /(world/f) after
    allreduce_always_fp32     reduce in fp32, cast back to grad dtype
    ========================  =============================================
    """
    world = jax.lax.psum(1, axis_name)

    def reduce_one(g):
        dtype = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            post = world / gradient_predivide_factor
            if gradient_predivide_factor != 1.0:
                g = g / post
            else:
                g = g / world
        elif gradient_predivide_factor != 1.0:
            g = g * gradient_predivide_factor
        return g.astype(dtype)

    # named_scope = the reference's NVTX range around its allreduces
    # (distributed.py:359-403): shows up in HLO op names and device traces
    with jax.named_scope("apex_allreduce_grads"):
        return jax.tree_util.tree_map(reduce_one, grads)


def broadcast_params(params: Any, axis_name: str = "data") -> Any:
    """Make parameters bitwise-identical across the data axis — the
    rank-0 broadcast the reference performs at DDP construction
    (distributed.py:253-256).  Implemented as an axis-wide mean of already
    replicated values' rank-0 contribution via ppermute-free select: every
    device adopts index-0's value."""

    idx = jax.lax.axis_index(axis_name)

    def bcast(p):
        # masked psum: every device adopts index 0's copy with O(1) extra
        # memory (an all_gather would transiently cost world× params).
        return jax.lax.psum(jnp.where(idx == 0, p, jnp.zeros_like(p)), axis_name)

    return jax.tree_util.tree_map(bcast, params)


class DistributedDataParallel:
    """Callable grad-reducer with the reference's constructor surface
    (distributed.py:162-189).  Options that only exist to manage eager
    overlap (``message_size``, ``delay_allreduce``, ``num_allreduce_streams``,
    ``allreduce_trigger_params``, ``prof``) are accepted and ignored — XLA
    owns scheduling; they are recorded for introspection.
    """

    def __init__(
        self,
        axis_name: str = "data",
        message_size: int = 10_000_000,
        delay_allreduce: bool = False,
        shared_param: Optional[bool] = None,
        allreduce_trigger_params: Optional[list] = None,
        retain_allreduce_buffers: bool = False,
        allreduce_always_fp32: bool = False,
        num_allreduce_streams: int = 1,
        allreduce_communicators: Optional[tuple] = None,
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        gradient_average_split_factor: Optional[float] = None,
        prof: bool = False,
    ):
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        # eager-only knobs, kept for API parity:
        self._ignored = dict(
            message_size=message_size, delay_allreduce=delay_allreduce,
            num_allreduce_streams=num_allreduce_streams, prof=prof,
        )

    def __call__(self, grads: Any) -> Any:
        return all_reduce_grads(
            grads,
            self.axis_name,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            allreduce_always_fp32=self.allreduce_always_fp32,
        )

    reduce = __call__


class Reducer:
    """Manual allreduce helper (reference distributed.py:89-126): the user
    calls ``reducer.reduce(grads)`` when ready; no hook magic."""

    def __init__(self, axis_name: str = "data", gradient_average: bool = True):
        self.axis_name = axis_name
        self.gradient_average = gradient_average

    def reduce(self, tree: Any) -> Any:
        return all_reduce_grads(
            tree, self.axis_name, gradient_average=self.gradient_average
        )
