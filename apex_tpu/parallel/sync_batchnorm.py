"""SyncBatchNorm — cross-device batch normalization.

TPU-native re-design of the reference's optimized SyncBN stack:

* ``SyncBatchnormFunction`` (reference
  apex/parallel/optimized_sync_batchnorm_kernel.py:7-119),
* module ``SyncBatchNorm`` (optimized_sync_batchnorm.py:9-100),
* Welford CUDA kernels (csrc/welford.cu: welford_kernel :259,
  welford_parallel merge, batchnorm_forward :298, reduce_bn,
  batchnorm_backward) and bindings csrc/syncbn.cpp:99-108.

Algorithm parity (forward):
  local mean/var  →  combine across the process group  →  normalize.
The reference allgathers (mean, var, count) per device then runs a
``welford_parallel`` merge kernel.  Here the merge is the closed-form
count-weighted moment combination under ``lax.psum`` over the mesh axis —
numerically the same statistics, one collective, no gather buffer:

  n      = Σ n_i
  mean   = Σ n_i·mean_i / n
  E[x²]  = Σ n_i·(var_i + mean_i²) / n
  var    = E[x²] − mean²

Backward parity: local reduction of (Σdy, Σdy·(x−mean)) → psum → fused
dgrad (reference kernel.py:93-111, collective at :101-106).  Running stats
use unbiased variance with the n/(n−1) correction (kernel.py:48-56).

Supports a per-subgroup ``process_group`` as a *named sub-axis* — the
``create_syncbn_process_group`` pattern (apex/parallel/__init__.py:60-95)
maps to meshes with a split data axis, e.g. ("data_outer", "data_inner").
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

AxisName = Union[str, Sequence[str], None]


def _channel_reduce_axes(x: jnp.ndarray, channel_axis: int) -> Tuple[int, ...]:
    return tuple(i for i in range(x.ndim) if i != channel_axis % x.ndim)


def sync_batch_norm_stats(
    x: jnp.ndarray,
    axis_name: AxisName,
    channel_axis: int = -1,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Global (mean, biased var, count) per channel across devices.

    Mirrors welford_mean_var + allgather + welford_parallel
    (optimized_sync_batchnorm_kernel.py:23-46) via moment combination.
    """
    axes = _channel_reduce_axes(x, channel_axis)
    x32 = x.astype(jnp.float32)
    local_n = jnp.array(
        jnp.prod(jnp.array([x.shape[a] for a in axes])), jnp.float32)
    local_sum = jnp.sum(x32, axis=axes)
    local_sumsq = jnp.sum(x32 * x32, axis=axes)
    if axis_name is not None:
        local_sum = jax.lax.psum(local_sum, axis_name)
        local_sumsq = jax.lax.psum(local_sumsq, axis_name)
        local_n = jax.lax.psum(local_n, axis_name)
    mean = local_sum / local_n
    var = local_sumsq / local_n - mean * mean
    return mean, var, local_n


def update_running_stats(running_mean, running_var, mean, var, n, momentum):
    """EMA of running stats with the unbiased n/(n-1) variance correction
    (reference kernel.py:48-56). Shared by SyncBN and GroupBN so the
    convention lives in one place."""
    unbiased = var * (n / jnp.maximum(n - 1.0, 1.0))
    return (
        (1 - momentum) * running_mean + momentum * mean,
        (1 - momentum) * running_var + momentum * unbiased,
    )


def sync_batch_norm(
    x: jnp.ndarray,
    weight: Optional[jnp.ndarray],
    bias: Optional[jnp.ndarray],
    running_mean: Optional[jnp.ndarray] = None,
    running_var: Optional[jnp.ndarray] = None,
    *,
    axis_name: AxisName = "data",
    training: bool = True,
    momentum: float = 0.1,
    eps: float = 1e-5,
    channel_axis: int = -1,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """Functional SyncBN. Returns ``(y, new_running_mean, new_running_var)``.

    In eval mode (``training=False``) running stats normalize the input with
    no collective, matching module forward at optimized_sync_batchnorm.py:70-85.
    """
    if training:
        mean, var, n = sync_batch_norm_stats(x, axis_name, channel_axis)
        if running_mean is not None:
            new_rm, new_rv = update_running_stats(
                running_mean, running_var, mean, var, n, momentum)
        else:
            new_rm, new_rv = None, None
    elif running_mean is not None:
        mean, var = running_mean.astype(jnp.float32), running_var.astype(jnp.float32)
        new_rm, new_rv = running_mean, running_var
    else:
        # eval without tracked stats: fall back to batch statistics, the
        # torch _BatchNorm contract the reference module inherits.
        mean, var, _ = sync_batch_norm_stats(x, axis_name, channel_axis)
        new_rm, new_rv = None, None

    shape = [1] * x.ndim
    shape[channel_axis % x.ndim] = x.shape[channel_axis % x.ndim]
    invstd = jax.lax.rsqrt(var + eps)
    y = (x.astype(jnp.float32) - mean.reshape(shape)) * invstd.reshape(shape)
    if weight is not None:
        y = y * weight.astype(jnp.float32).reshape(shape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(shape)
    return y.astype(x.dtype), new_rm, new_rv


class SyncBatchNorm:
    """Module wrapper mirroring ``apex.parallel.SyncBatchNorm``
    (optimized_sync_batchnorm.py:9; constructor args from torch
    ``_BatchNorm`` plus ``process_group`` and ``channel_last``).

    State (running stats) is explicit: :meth:`init` returns
    ``{"params": ..., "state": ...}``; :meth:`apply` returns
    ``(y, new_state)`` — the functional version of mutable buffers.
    ``channel_last=True`` (NHWC, channel_axis=-1) is the TPU-native layout
    and the default; the reference's NCHW maps to ``channel_axis=1``.
    """

    def __init__(
        self,
        num_features: int,
        eps: float = 1e-5,
        momentum: float = 0.1,
        affine: bool = True,
        track_running_stats: bool = True,
        process_group: AxisName = "data",
        channel_last: bool = True,
        fuse_relu: bool = False,
    ):
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.process_group = process_group
        self.channel_axis = -1 if channel_last else 1
        self.fuse_relu = fuse_relu  # groupbn/welford fuse-relu variant

    def init(self, dtype=jnp.float32):
        params = {}
        if self.affine:
            params = {
                "weight": jnp.ones((self.num_features,), dtype),
                "bias": jnp.zeros((self.num_features,), dtype),
            }
        state = {}
        if self.track_running_stats:
            state = {
                "running_mean": jnp.zeros((self.num_features,), jnp.float32),
                "running_var": jnp.ones((self.num_features,), jnp.float32),
            }
        return {"params": params, "state": state}

    def apply(self, variables, x, *, training: bool = True):
        params, state = variables["params"], variables["state"]
        # batch stats (and hence the group collective) are used when training
        # OR when not tracking running stats — reference
        # optimized_sync_batchnorm.py:85 `self.training or not self.track_running_stats`
        use_batch_stats = training or not self.track_running_stats
        y, rm, rv = sync_batch_norm(
            x,
            params.get("weight"),
            params.get("bias"),
            state.get("running_mean"),
            state.get("running_var"),
            axis_name=self.process_group if use_batch_stats else None,
            training=training,
            momentum=self.momentum,
            eps=self.eps,
            channel_axis=self.channel_axis,
        )
        if self.fuse_relu:
            y = jax.nn.relu(y)
        new_state = dict(state)
        if rm is not None:
            new_state = {"running_mean": rm, "running_var": rv}
        return y, {"params": params, "state": new_state}

    __call__ = apply


def convert_syncbn_model(module_tree: Any, process_group: AxisName = "data",
                         channel_last: bool = True) -> Any:
    """Recursive BN→SyncBN swap (reference apex/parallel/__init__.py:21-57).

    Works over any pytree/structure containing :class:`SyncBatchNorm`-likes
    or objects exposing ``num_features``: BN-shaped nodes are rebuilt as
    :class:`SyncBatchNorm` with the given group.  For flax models, prefer
    constructing with ``apex_tpu.parallel.SyncBatchNorm`` directly — there
    is no module graph to mutate in functional code, so this helper exists
    for config-level conversion.
    """
    def convert(node):
        if hasattr(node, "num_features") and not isinstance(node, SyncBatchNorm):
            return SyncBatchNorm(
                node.num_features,
                eps=getattr(node, "eps", 1e-5),
                momentum=getattr(node, "momentum", 0.1),
                affine=getattr(node, "affine", True),
                track_running_stats=getattr(node, "track_running_stats", True),
                process_group=process_group,
                channel_last=channel_last,
            )
        return node

    if isinstance(module_tree, (list, tuple)):
        return type(module_tree)(convert_syncbn_model(m, process_group, channel_last)
                                 for m in module_tree)
    if isinstance(module_tree, dict):
        return {k: convert_syncbn_model(v, process_group, channel_last)
                for k, v in module_tree.items()}
    return convert(module_tree)


def create_syncbn_process_group(group_size: int, world_size: int) -> Tuple[str, ...]:
    """Reference apex/parallel/__init__.py:60-95 partitions ranks into BN
    subgroups of ``group_size``.  On a mesh this is a *shape*, not a group
    object: split the data axis as ("data_outer", "data_bn") with
    data_bn=group_size and psum over "data_bn" only.  Returns the axis names
    to use; the caller builds the mesh accordingly."""
    if group_size <= 0 or world_size % group_size != 0:
        raise ValueError("group_size must divide world_size")
    return ("data_outer", "data_bn")
