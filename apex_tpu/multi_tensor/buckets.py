"""Gradient-bucket planner for the overlap-aware ZeRO step (ISSUE 15).

Reference lineage: DDP gradient bucketing (apex/parallel/distributed.py
— close a bucket when the next parameter would push it past the
``bucket_bytes`` cap, so backward can ship finished buckets while later
layers are still differentiating) and DistributedFusedAdam's chunked
reduce-scatter pipeline (contrib/optimizers/distributed_fused_adam.py:
316-362 — the flat grad buffer moves in fixed-size chunks, each chunk's
collective overlapping the next chunk's compute).

TPU mapping.  There are no grad hooks to drive per-bucket issue from —
the whole step is one XLA program — so the bucket plan is *structural*:
the monolithic ``psum_scatter``/``all_gather`` pair of the serialized
ZeRO step (contrib/optimizers/distributed_fused.py) is split into one
reduce-scatter + all-gather **per bucket**, and XLA's latency-hiding
scheduler interleaves the smaller collectives with backward/optimizer
compute instead of queueing one buffer-sized transfer behind all of it.
The ``python -m apex_tpu.analysis hlo`` contract pins the resulting
per-bucket inventory; ``telemetry regress`` gates the measured
exposed-collective wall.

Layout contract (the part that must NOT leak into checkpoints).  The
canonical ZeRO ownership is the C-order contract of
:mod:`apex_tpu.multi_tensor.flat`: rank ``r`` of a ``world``-way shard
owns the contiguous slice ``flat[r*S : (r+1)*S]`` with
``S = schema.total // world``.  A bucket here is a **span of the
per-rank shard** ``[lo, hi) ⊂ [0, S)`` — equivalently the column block
``flat.reshape(world, S)[:, lo:hi]`` of the canonical buffer.
Reduce-scattering that block (flattened rank-major) hands rank ``r``
exactly its canonical slice of the span, so the optimizer-state stack
stays in the canonical layout **for every bucket plan**: a format-4
checkpoint written under one plan restores bitwise under any other
(tests/L0/test_bucketed_zero.py pins the round trip).  The planner
still *thinks* in reference-DDP terms — leaves are walked in pack
order and a bucket closes at the cap — and each canonical boundary is
mapped onto the shard as ``offset // world`` rounded to the lane
width, so a bucket's shard span is its leaves' per-rank share.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from apex_tpu.multi_tensor.flat import FlatSchema

__all__ = ["BucketPlan", "DEFAULT_BUCKET_BYTES", "plan_buckets"]

#: Default bucket cap for the flagship step.  The reference DDP default
#: is 10 MB (apex/parallel/distributed.py ``message_size``); torch DDP
#: uses 25 MB.  32 MiB keeps the per-collective payload large enough to
#: stay bandwidth-bound on an ICI link while giving a 1.3B-param fp32
#: grad buffer (~5.3 GB) ~170 buckets of overlap opportunity.
DEFAULT_BUCKET_BYTES = 32 << 20

_LANE = 128  # TPU lane width; flat.py packs leaves at this alignment


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static, hashable bucket plan (safe to close over in jit).

    ``spans`` partition the per-rank shard ``[0, shard)`` in order;
    bucket ``b`` covers canonical elements ``r*shard + [lo, hi)`` on
    every rank ``r`` (see module docstring for the layout contract).
    """

    spans: Tuple[Tuple[int, int], ...]
    shard: int           # per-rank shard length S = total // world
    world: int
    bucket_bytes: Optional[int]  # the cap that produced the plan

    @property
    def num_buckets(self) -> int:
        return len(self.spans)

    def span_elements(self, b: int) -> int:
        lo, hi = self.spans[b]
        return hi - lo

    def collective_elements(self, b: int) -> int:
        """Elements moved by bucket ``b``'s reduce-scatter (and its
        all-gather): the whole column block, ``world`` shard spans."""
        return self.span_elements(b) * self.world

    def validate(self) -> None:
        pos = 0
        for lo, hi in self.spans:
            if lo != pos or hi <= lo:
                raise ValueError(
                    f"bucket spans must partition [0, {self.shard}) in "
                    f"order; got {self.spans}")
            pos = hi
        if pos != self.shard:
            raise ValueError(
                f"bucket spans cover [0, {pos}) but the shard is "
                f"[0, {self.shard})")


def plan_buckets(schema: FlatSchema, world: int, *,
                 bucket_bytes: Optional[int] = DEFAULT_BUCKET_BYTES,
                 itemsize: int = 4,
                 span_align: int = _LANE) -> BucketPlan:
    """Partition ``schema``'s superblock into size-targeted buckets.

    Reference-DDP cap semantics over the canonical pack order: leaves
    accumulate into the current bucket until adding the next leaf's
    padded bytes would exceed ``bucket_bytes`` (a bucket always takes
    at least one leaf, so a single oversized leaf becomes its own
    bucket — ``bucket_bytes=1`` is the one-param-per-bucket edge).
    ``bucket_bytes=None`` produces the single-bucket plan, which is
    exactly the serialized ZeRO data path (one monolithic
    reduce-scatter + all-gather).

    Each canonical bucket boundary is then mapped to the per-rank
    shard as ``boundary // world`` rounded down to ``span_align``
    (default: the 128 lane width; the Pallas flat-Adam path wants
    ``8*128`` sublane rows), so tiny adjacent leaves may merge into
    one span (their per-rank share is below one alignment row) — the
    plan never has more than ``shard // span_align`` buckets.
    ``itemsize`` is the grad transport dtype's byte width (the
    reduce-scatter payload the cap governs).
    """
    world = int(world)
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    span_align = int(span_align)
    if span_align < _LANE or span_align % _LANE:
        raise ValueError(
            f"span_align must be a multiple of the {_LANE} lane width, "
            f"got {span_align}")
    if schema.total % world:
        raise ValueError(
            f"schema.total={schema.total} does not divide world={world}"
            " — pack with make_schema(total_multiple_of=128*world)")
    shard = schema.total // world
    if shard % span_align:
        raise ValueError(
            f"per-rank shard {shard} is not aligned (multiple of "
            f"{span_align}); pack with make_schema(total_multiple_of="
            f"{span_align}*world)")
    if bucket_bytes is None:
        return BucketPlan(spans=((0, shard),), shard=shard, world=world,
                          bucket_bytes=None)
    bucket_bytes = int(bucket_bytes)
    if bucket_bytes < 1:
        raise ValueError(f"bucket_bytes must be >= 1, got {bucket_bytes}")

    # canonical bucket boundaries at padded-leaf granularity (DDP cap)
    boundaries = []  # canonical end offsets of closed buckets
    cur_bytes = 0
    n = schema.num_tensors
    for i in range(n):
        end = schema.offsets[i + 1] if i + 1 < n else schema.total
        padded = (end - schema.offsets[i]) * itemsize
        if cur_bytes and cur_bytes + padded > bucket_bytes:
            boundaries.append(schema.offsets[i])
            cur_bytes = 0
        cur_bytes += padded

    # map canonical boundaries onto the per-rank shard (lane-rounded);
    # dedupe collapsed spans, always close the final span at `shard`
    cuts = [0]
    for b in boundaries:
        x = b // world // span_align * span_align
        if x > cuts[-1] and x < shard:
            cuts.append(x)
    cuts.append(shard)
    spans = tuple((cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1))
    plan = BucketPlan(spans=spans, shard=shard, world=world,
                      bucket_bytes=bucket_bytes)
    plan.validate()
    return plan
