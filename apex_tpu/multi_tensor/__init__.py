"""apex_tpu.multi_tensor — the flattened-parameter multi-tensor engine.

TPU-native re-design of the reference's universal kernel idiom
``multi_tensor_apply`` (csrc/multi_tensor_apply.cuh:41-133 + the ``amp_C``
kernel suite, csrc/amp_C_frontend.cpp:123-143).

The reference packs raw pointers of up to 110 irregular tensors into a
kernel-arg struct and launches one elementwise CUDA kernel across chunks of
every tensor. A TPU has no pointer-list launches — the idiomatic equivalent
is a **superblock**: the pytree is flattened once into a single contiguous
1-D HBM buffer (:class:`FlatSchema` / :func:`flatten` / :func:`unflatten`),
and every "multi-tensor" op becomes ONE fused XLA/Pallas op over that buffer.
Per-tensor semantics (per-tensor l2 norms, per-layer trust ratios) are
recovered with segment reductions over the schema's offset table.

This engine backs all fused optimizers (apex_tpu.optimizers), the loss
scaler, grad clipping, and ZeRO sharding — exactly the role amp_C plays in
the reference.
"""

from apex_tpu.multi_tensor.buckets import (  # noqa: F401
    DEFAULT_BUCKET_BYTES,
    BucketPlan,
    plan_buckets,
)
from apex_tpu.multi_tensor.flat import (  # noqa: F401
    FlatSchema,
    flatten,
    make_schema,
    unflatten,
)
from apex_tpu.multi_tensor.ops import (  # noqa: F401
    clip_grad_norm,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    segment_l2norms,
)
