"""Superblock pack/unpack.

Replaces ``apex_C.flatten/unflatten`` (reference csrc/flatten_unflatten.cpp:16-17,
used by DDP bucketing at apex/parallel/distributed.py:13-33) and the
block/chunk/shard flat-buffer layout of the sharded optimizers
(contrib/optimizers/distributed_fused_lamb.py:364-434).

Layout choice: leaves are concatenated in pytree order, each padded to a
multiple of ``align`` (default 128, the TPU lane width) so that every leaf
starts on a lane boundary and the buffer length divides evenly into shards
for ZeRO-style ``psum_scatter`` over the data axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class FlatSchema:
    """Static metadata describing a packed superblock (hashable, safe to
    close over in jit)."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]  # start offset of each leaf (aligned)
    sizes: Tuple[int, ...]  # unpadded leaf sizes
    total: int  # total padded length
    align: int

    @property
    def num_tensors(self) -> int:
        return len(self.shapes)

    def leaf_slice(self, i: int) -> slice:
        return slice(self.offsets[i], self.offsets[i] + self.sizes[i])

    def segment_ids(self) -> np.ndarray:
        """Per-element leaf index (padding marked with num_tensors) — the
        offset table the reference keeps in kernel args
        (TensorListMetadata, csrc/multi_tensor_apply.cuh:19-26)."""
        ids = np.full((self.total,), self.num_tensors, np.int32)
        for i in range(self.num_tensors):
            ids[self.offsets[i] : self.offsets[i] + self.sizes[i]] = i
        return ids


def _round_up(n: int, align: int) -> int:
    return (n + align - 1) // align * align


def repartition_flat(flat: np.ndarray, new_size: int, *,
                     label: str = "flat buffer") -> np.ndarray:
    """Resize a 1-D flat superblock for a new shard topology.

    Per-leaf offsets inside a :class:`FlatSchema` are topology-invariant
    (only the ``total_multiple_of`` tail padding depends on the shard
    count), so an N→M re-partition is concat → resize → re-split, and
    the only legal size change is in the padding tail: growth
    zero-fills; shrinkage requires the dropped tail to be all zeros —
    anything else is real state and raises.  Shared by the sharded
    checkpoint reshard (``checkpoint._reshard_stack``) and the
    in-memory :func:`~apex_tpu.contrib.optimizers.reshard_zero_state`
    so on-disk and in-memory semantics cannot diverge."""
    flat = np.ascontiguousarray(flat).reshape(-1)
    if new_size > flat.size:
        out = np.zeros((new_size,), flat.dtype)
        out[: flat.size] = flat
        return out
    if new_size < flat.size:
        if np.any(flat[new_size:] != 0):
            raise ValueError(
                f"cannot repartition {label} from {flat.size} to "
                f"{new_size} elements: the {flat.size - new_size} dropped "
                "trailing elements are not all zero — that region holds "
                "real state, not flat-schema padding (schema mismatch?)")
        return flat[:new_size]
    return flat


def make_schema(tree, *, align: int = 128, total_multiple_of: int = 1) -> FlatSchema:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes, dtypes, offsets, sizes = [], [], [], []
    off = 0
    for leaf in leaves:
        leaf = jnp.asarray(leaf)
        shapes.append(tuple(leaf.shape))
        dtypes.append(leaf.dtype)
        offsets.append(off)
        sizes.append(int(leaf.size))
        off += _round_up(int(leaf.size), align)
    total = _round_up(off, max(align, total_multiple_of))
    return FlatSchema(
        treedef=treedef,
        shapes=tuple(shapes),
        dtypes=tuple(dtypes),
        offsets=tuple(offsets),
        sizes=tuple(sizes),
        total=total,
        align=align,
    )


def flatten(tree, schema: FlatSchema | None = None, *, dtype=None, align: int = 128,
            total_multiple_of: int = 1):
    """Pack a pytree into one 1-D buffer. Returns ``(flat, schema)``.

    ``dtype`` forces a cast (e.g. pack bf16 grads into an fp32 superblock —
    the master-grad materialisation of _process_optimizer.py:161-230).
    """
    if schema is None:
        schema = make_schema(tree, align=align, total_multiple_of=total_multiple_of)
    leaves = jax.tree_util.tree_leaves(tree)
    buf_dtype = dtype or jnp.result_type(*schema.dtypes)
    parts: List[jnp.ndarray] = []
    pos = 0
    for i, leaf in enumerate(leaves):
        leaf = jnp.asarray(leaf).reshape(-1).astype(buf_dtype)
        pad = schema.offsets[i] - pos
        if pad:
            parts.append(jnp.zeros((pad,), buf_dtype))
        parts.append(leaf)
        pos = schema.offsets[i] + schema.sizes[i]
    if schema.total - pos:
        parts.append(jnp.zeros((schema.total - pos,), buf_dtype))
    return jnp.concatenate(parts), schema


def unflatten(flat, schema: FlatSchema, *, dtype=None):
    """Rebuild the pytree (views of the superblock)."""
    leaves = []
    for i in range(schema.num_tensors):
        leaf = flat[schema.leaf_slice(i)].reshape(schema.shapes[i])
        leaves.append(leaf.astype(dtype or schema.dtypes[i]))
    return jax.tree_util.tree_unflatten(schema.treedef, leaves)
