"""Superblock pack/unpack.

Replaces ``apex_C.flatten/unflatten`` (reference csrc/flatten_unflatten.cpp:16-17,
used by DDP bucketing at apex/parallel/distributed.py:13-33) and the
block/chunk/shard flat-buffer layout of the sharded optimizers
(contrib/optimizers/distributed_fused_lamb.py:364-434).

Layout choice: leaves are concatenated in pytree order, each padded to a
multiple of ``align`` (default 128, the TPU lane width) so that every leaf
starts on a lane boundary and the buffer length divides evenly into shards
for ZeRO-style ``psum_scatter`` over the data axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class FlatSchema:
    """Static metadata describing a packed superblock (hashable, safe to
    close over in jit)."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]  # start offset of each leaf (aligned)
    sizes: Tuple[int, ...]  # unpadded leaf sizes
    total: int  # total padded length
    align: int

    @property
    def num_tensors(self) -> int:
        return len(self.shapes)

    def leaf_slice(self, i: int) -> slice:
        return slice(self.offsets[i], self.offsets[i] + self.sizes[i])

    def segment_ids(self) -> np.ndarray:
        """Per-element leaf index (padding marked with num_tensors) — the
        offset table the reference keeps in kernel args
        (TensorListMetadata, csrc/multi_tensor_apply.cuh:19-26)."""
        ids = np.full((self.total,), self.num_tensors, np.int32)
        for i in range(self.num_tensors):
            ids[self.offsets[i] : self.offsets[i] + self.sizes[i]] = i
        return ids


def _round_up(n: int, align: int) -> int:
    return (n + align - 1) // align * align


def repartition_flat(flat: np.ndarray, new_size: int, *,
                     label: str = "flat buffer") -> np.ndarray:
    """Resize a 1-D flat superblock for a new shard topology.

    Per-leaf offsets inside a :class:`FlatSchema` are topology-invariant
    (only the ``total_multiple_of`` tail padding depends on the shard
    count), so an N→M re-partition is concat → resize → re-split, and
    the only legal size change is in the padding tail: growth
    zero-fills; shrinkage requires the dropped tail to be all zeros —
    anything else is real state and raises.  Shared by the sharded
    checkpoint reshard (``checkpoint._reshard_stack``) and the
    in-memory :func:`~apex_tpu.contrib.optimizers.reshard_zero_state`
    so on-disk and in-memory semantics cannot diverge."""
    flat = np.ascontiguousarray(flat).reshape(-1)
    if new_size > flat.size:
        out = np.zeros((new_size,), flat.dtype)
        out[: flat.size] = flat
        return out
    if new_size < flat.size:
        if np.any(flat[new_size:] != 0):
            raise ValueError(
                f"cannot repartition {label} from {flat.size} to "
                f"{new_size} elements: the {flat.size - new_size} dropped "
                "trailing elements are not all zero — that region holds "
                "real state, not flat-schema padding (schema mismatch?)")
        return flat[:new_size]
    return flat


def reshard_stack(val: np.ndarray, n_lead: int, want_shape, *,
                  replicated: bool = False,
                  label: str = "sharded stack") -> np.ndarray:
    """Re-partition one stacked sharded leaf across topologies.

    ``val`` is a ``[n_a, n_b, ..., *content]`` stack whose first
    ``n_lead`` dims are per-rank stack axes (one per mesh axis the leaf
    is sharded over, in mesh-axis order); ``want_shape`` is the target
    topology's stacked shape.  The contract generalizing
    :func:`repartition_flat` to the multi-axis mesh:

    - ``replicated`` — every coordinate holds the same per-rank value
      (broadcast step counters): coordinate (0, ..., 0) speaks for the
      whole new topology; the content shape must match exactly.
    - otherwise the leaf's **logical value is its C-order flatten**:
      leading stack dims linearize in mesh-axis order (the linearized-
      world ZeRO layout), and a stack dim sharding a contiguous leading
      content dim (pp layer stacks ``[pp, L/pp, ...]``) merges into it
      exactly.  The flatten re-partitions under the pad/trim contract
      (:func:`repartition_flat` — only all-zero schema tail padding may
      grow or shrink) and reshapes to ``want_shape``.  Layouts whose
      logical merge is NOT C-contiguous (e.g. a 2-D weight sliced along
      its second dim, stacked on a leading axis) are outside the
      contract — store those leaves replicated (master form) or slice
      the leading content dim instead.
    """
    val = np.asarray(val)
    want_shape = tuple(int(x) for x in want_shape)
    if n_lead >= val.ndim + 1 and not replicated:
        raise ValueError(
            f"cannot reshard {label}: {n_lead} stack axes on a "
            f"{val.ndim}-D array")
    if replicated:
        n_lead = min(n_lead, val.ndim)
        content = val[(0,) * n_lead]
        # target lead-dim count may differ (a 3-axis save restoring
        # into a 1-axis state); the content tail must match exactly
        tail = want_shape[len(want_shape) - content.ndim:] if content.ndim \
            else ()
        if content.shape != tuple(tail):
            raise ValueError(
                f"cannot reshard replicated {label}: per-rank shape "
                f"{content.shape} != target per-rank shape {tuple(tail)}")
        # contiguous copy: callers may .view() raw-bits stored dtypes,
        # which a broadcast view cannot support
        return np.ascontiguousarray(np.broadcast_to(content, want_shape))
    out = repartition_flat(val, int(np.prod(want_shape, dtype=np.int64)),
                           label=label)
    return out.reshape(want_shape)


def reshard_stack_device(val, want_shape):
    """Traceable (jit-able) twin of :func:`reshard_stack`'s
    non-replicated branch for the grow/equal cases: C-order flatten →
    zero-pad the schema tail → reshape, entirely on device.  Trims
    stay host-side — the all-zero-tail validation
    (:func:`repartition_flat` raises on real state) is a
    data-dependent host decision a traced function cannot express.

    Registered with the ISSUE 13 contract checker (``reshard_stack``
    registry entry): a reshard is pure data movement, so its compiled
    artifact must carry ZERO collectives and ZERO host-interaction
    ops.  (Entry-level donation is NOT part of that contract: jax
    pairs a donated input only with a same-shape output, and a reshard
    changes shape by definition — the checker records that fact rather
    than pretending the alias exists.)"""
    want_shape = tuple(int(x) for x in want_shape)
    new_size = int(np.prod(want_shape, dtype=np.int64))
    flat = jnp.reshape(jnp.asarray(val), (-1,))
    if new_size < flat.size:
        raise ValueError(
            f"reshard_stack_device only grows or keeps size "
            f"({flat.size} -> {new_size} shrinks): trims need the "
            "host-side reshard_stack, whose all-zero-tail check is a "
            "data-dependent decision")
    if new_size > flat.size:
        flat = jnp.concatenate(
            [flat, jnp.zeros((new_size - flat.size,), flat.dtype)])
    return jnp.reshape(flat, want_shape)


def spec_lead_axes(spec, axes) -> list:
    """Leading mesh-axis names of a PartitionSpec: walk entries from dim
    0 while each names exactly one axis in ``axes`` (str, or a 1-tuple);
    stop at the first entry that does not."""
    lead = []
    for part in (spec or ()):
        if isinstance(part, (tuple, list)):
            part = part[0] if len(part) == 1 else None
        if part in axes:
            lead.append(part)
        else:
            break
    return lead


def is_replicated_stack(val, n_lead: int) -> bool:
    """Per-rank replicated broadcast value: scalar content (ndim ==
    n_lead) with every coordinate equal — the multi-axis form of the
    format-3 1-D rule.  A >=1-D content stack is by contract a data
    partition even when rank-identical (fresh all-zero moments must
    reshard by concat)."""
    val = np.asarray(val)
    if val.ndim != n_lead:
        return False
    flat = val.reshape(-1)
    return bool(np.all(flat == flat[0]))


def reshard_tree(tree, spec_from, spec_to, *, target,
                 axes_from, axes_to=None, label: str = "state"):
    """Sharding-aware tree re-partitioner: every leaf of ``tree`` whose
    ``spec_from`` spec leads with mesh-axis names is re-stacked to the
    shape of the corresponding ``target`` leaf (an N→M reshape of the
    (dp, tp, pp) topology — the in-memory twin of the format-4
    checkpoint reshard, sharing :func:`reshard_stack` so on-disk and
    live semantics cannot diverge).

    ``spec_from`` / ``spec_to`` — structure-prefix PartitionSpec trees
    for the source and target states (the same object is fine when the
    layout convention is unchanged); ``axes_from`` / ``axes_to`` —
    mesh-axis name → size mappings of the two topologies.  Replicated
    leaves (no leading axis names) pass through unchanged.  Host-side
    numpy — this runs once per mesh rebuild, not per step."""
    from jax.sharding import NamedSharding, PartitionSpec

    axes_to = dict(axes_to if axes_to is not None else axes_from)

    def _expand(spec_tree, value_tree):
        flat = []

        def _collect(spec, subtree):
            if isinstance(spec, NamedSharding):
                spec = spec.spec
            n = len(jax.tree_util.tree_leaves(subtree))
            flat.extend([spec] * n)

        jax.tree_util.tree_map(
            _collect, spec_tree, value_tree,
            is_leaf=lambda x: x is None
            or isinstance(x, (PartitionSpec, NamedSharding)))
        return flat

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    tgt_leaves = jax.tree_util.tree_leaves(target)
    specs_f = _expand(spec_from, tree)
    specs_t = _expand(spec_to, target)
    if not (len(leaves) == len(tgt_leaves) == len(specs_f) == len(specs_t)):
        raise ValueError(
            f"reshard_tree({label}): tree/target/spec leaf counts "
            f"disagree ({len(leaves)}/{len(tgt_leaves)}/{len(specs_f)}/"
            f"{len(specs_t)})")
    out = []
    for i, (leaf, tgt) in enumerate(zip(leaves, tgt_leaves)):
        lead_f = spec_lead_axes(specs_f[i], axes_from)
        lead_t = spec_lead_axes(specs_t[i], axes_to)
        want = tuple(tgt.shape)
        if not lead_f and not lead_t:
            out.append(leaf)
            continue
        val = np.asarray(jax.device_get(leaf))
        res = reshard_stack(
            val, len(lead_f), want,
            replicated=is_replicated_stack(val, len(lead_f)),
            label=f"{label} leaf {i}")
        out.append(jnp.asarray(res))
    return jax.tree_util.tree_unflatten(treedef, out)


def make_schema(tree, *, align: int = 128, total_multiple_of: int = 1) -> FlatSchema:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes, dtypes, offsets, sizes = [], [], [], []
    off = 0
    for leaf in leaves:
        leaf = jnp.asarray(leaf)
        shapes.append(tuple(leaf.shape))
        dtypes.append(leaf.dtype)
        offsets.append(off)
        sizes.append(int(leaf.size))
        off += _round_up(int(leaf.size), align)
    total = _round_up(off, max(align, total_multiple_of))
    return FlatSchema(
        treedef=treedef,
        shapes=tuple(shapes),
        dtypes=tuple(dtypes),
        offsets=tuple(offsets),
        sizes=tuple(sizes),
        total=total,
        align=align,
    )


def flatten(tree, schema: FlatSchema | None = None, *, dtype=None, align: int = 128,
            total_multiple_of: int = 1):
    """Pack a pytree into one 1-D buffer. Returns ``(flat, schema)``.

    ``dtype`` forces a cast (e.g. pack bf16 grads into an fp32 superblock —
    the master-grad materialisation of _process_optimizer.py:161-230).
    """
    if schema is None:
        schema = make_schema(tree, align=align, total_multiple_of=total_multiple_of)
    leaves = jax.tree_util.tree_leaves(tree)
    buf_dtype = dtype or jnp.result_type(*schema.dtypes)
    parts: List[jnp.ndarray] = []
    pos = 0
    for i, leaf in enumerate(leaves):
        leaf = jnp.asarray(leaf).reshape(-1).astype(buf_dtype)
        pad = schema.offsets[i] - pos
        if pad:
            parts.append(jnp.zeros((pad,), buf_dtype))
        parts.append(leaf)
        pos = schema.offsets[i] + schema.sizes[i]
    if schema.total - pos:
        parts.append(jnp.zeros((schema.total - pos,), buf_dtype))
    return jnp.concatenate(parts), schema


def unflatten(flat, schema: FlatSchema, *, dtype=None):
    """Rebuild the pytree (views of the superblock)."""
    leaves = []
    for i in range(schema.num_tensors):
        leaf = flat[schema.leaf_slice(i)].reshape(schema.shapes[i])
        leaves.append(leaf.astype(dtype or schema.dtypes[i]))
    return jax.tree_util.tree_unflatten(schema.treedef, leaves)
