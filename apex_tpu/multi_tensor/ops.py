"""The amp_C op suite over superblocks / pytrees.

Reference kernels (csrc/amp_C_frontend.cpp:123-143):
``multi_tensor_scale`` (with inf/nan poll, csrc/multi_tensor_scale_kernel.cu),
``multi_tensor_axpby``, ``multi_tensor_l2norm`` (global + per-tensor,
csrc/multi_tensor_l2norm_kernel.cu). Here each is one fused XLA op; the
inf/nan poll is an all-finite reduction returned alongside the result
instead of a host-polled noop_flag.

All ops accept either a 1-D superblock or an arbitrary pytree (applied
leafwise and reduced) — the pytree path is what optimizers use; the
superblock path is what ZeRO shards use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor.flat import FlatSchema
from apex_tpu.utils.tree import tree_isfinite


def multi_tensor_scale(tree, scale):
    """out = in * scale, plus overflow flag.

    Reference: multi_tensor_scale_kernel.cu via scaler.py:94-151 (the
    unscale path) and DDP's fp16 copy-back (distributed.py:460-465).
    Returns ``(scaled_tree, finite)``.
    """
    out = jax.tree_util.tree_map(lambda x: x * scale, tree)
    return out, tree_isfinite(out)


def multi_tensor_axpby(x_tree, y_tree, a, b, *, out_dtype=None):
    """out = a*x + b*y (reference multi_tensor_axpby_kernel.cu, used by
    ``unscale_with_stashed`` scaler.py:152-189). Returns ``(out, finite)``."""

    def _axpby(x, y):
        r = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        return r.astype(out_dtype or x.dtype)

    out = jax.tree_util.tree_map(_axpby, x_tree, y_tree)
    return out, tree_isfinite(out)


def multi_tensor_l2norm(tree, *, per_tensor: bool = False):
    """Global (and optionally per-tensor) l2 norm.

    Reference: multi_tensor_l2norm_kernel.cu (used by FusedLAMB's phase 1,
    fused_lamb.py:121-136, and grad clipping). Per-tensor = per-leaf here.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    sq = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves]
    total = jnp.sqrt(sum(sq)) if sq else jnp.asarray(0.0, jnp.float32)
    if per_tensor:
        return total, jnp.sqrt(jnp.stack(sq)) if sq else jnp.zeros((0,), jnp.float32)
    return total


def segment_l2norms(flat, schema: FlatSchema):
    """Per-tensor l2 norms over a superblock via one segment reduction
    (the per-tensor option of multi_tensor_l2norm over TensorListMetadata
    offsets)."""
    ids = jnp.asarray(schema.segment_ids())
    sq = jax.ops.segment_sum(
        jnp.square(flat.astype(jnp.float32)), ids, num_segments=schema.num_tensors + 1
    )
    return jnp.sqrt(sq[: schema.num_tensors])


def clip_grad_norm(tree, max_norm: float, *, eps: float = 1e-6):
    """Global-norm clip built from l2norm+scale (how the reference composes
    amp grad clipping from multi_tensor_l2norm + multi_tensor_scale)."""
    norm = multi_tensor_l2norm(tree)
    clip = jnp.minimum(1.0, max_norm / (norm + eps))
    return jax.tree_util.tree_map(lambda x: x * clip, tree), norm
