// Threaded prefetching record loader — the native data pipeline the
// reference gets from DALI in examples/imagenet/main_amp.py (its
// --data-backend dali path) and from torch DataLoader worker processes.
//
// Dataset model: a set of files, each a contiguous array of fixed-size
// records (record_bytes).  An epoch is a (optionally shuffled) permutation
// of all record indices; worker threads fill a ring of batch buffers with
// pread()s while the consumer drains batches in order.  Infinite stream:
// each epoch reshuffles with seed+epoch (deterministic given seed, so a
// resumed run replays the same order, matching the CLI's set_epoch
// discipline).
//
// Plain C ABI for ctypes (no pybind11 in this image).

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <numeric>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Loader {
  // lifetime count of records that failed to read (truncated / rotated
  // files); those records are zero-filled, and the consumer must be able
  // to see that it happened — silent corruption is worse than a crash
  std::atomic<int64_t> read_errors{0};
  std::vector<int> fds;
  std::vector<int64_t> file_base;  // cumulative record start per file
  int64_t total_records = 0;
  int64_t record_bytes = 0;
  int64_t batch = 0;
  bool shuffle = false;
  uint64_t seed = 0;

  // current epoch's permutation of record indices
  std::vector<int64_t> order;
  int64_t epoch = 0;

  // ring of batch buffers; a slot holds batch seq `ring_seq[s]`, valid to
  // read only once `ring_done[s]`
  std::vector<std::vector<char>> ring;
  std::vector<int64_t> ring_seq;
  std::vector<char> ring_done;
  int64_t next_fill = 0;  // next batch seq a worker will claim
  int64_t next_out = 0;   // next batch seq the consumer wants
  bool stop = false;

  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::vector<std::thread> workers;

  int64_t batches_per_epoch() const { return total_records / batch; }

  void reshuffle_locked() {
    order.resize(static_cast<size_t>(total_records));
    std::iota(order.begin(), order.end(), 0);
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      for (int64_t i = total_records - 1; i > 0; --i) {
        int64_t j = static_cast<int64_t>(rng() % (i + 1));
        std::swap(order[i], order[j]);
      }
    }
  }

  bool read_record(int64_t rec, char* dst) {
    size_t f = 0;
    while (f + 1 < file_base.size() && file_base[f + 1] <= rec) ++f;
    int64_t off = (rec - file_base[f]) * record_bytes;
    int64_t done = 0;
    while (done < record_bytes) {
      ssize_t r = pread(fds[f], dst + done,
                        static_cast<size_t>(record_bytes - done), off + done);
      if (r <= 0) return false;
      done += r;
    }
    return true;
  }

  int64_t free_slot_locked() const {
    for (size_t s = 0; s < ring_seq.size(); ++s)
      if (ring_seq[s] == -1) return static_cast<int64_t>(s);
    return -1;
  }

  void worker() {
    std::vector<int64_t> recs(static_cast<size_t>(batch));
    for (;;) {
      int64_t seq, slot;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] { return stop || free_slot_locked() >= 0; });
        if (stop) return;
        slot = free_slot_locked();
        seq = next_fill++;
        ring_seq[static_cast<size_t>(slot)] = seq;
        ring_done[static_cast<size_t>(slot)] = 0;
        // resolve this batch's record ids under the lock (epoch advance
        // mutates `order`)
        int64_t e = seq / batches_per_epoch();
        int64_t local = seq % batches_per_epoch();
        if (e != epoch) {
          epoch = e;
          reshuffle_locked();
        }
        for (int64_t i = 0; i < batch; ++i)
          recs[static_cast<size_t>(i)] =
              order[static_cast<size_t>(local * batch + i)];
      }
      char* buf = ring[static_cast<size_t>(slot)].data();
      for (int64_t i = 0; i < batch; ++i) {
        if (!read_record(recs[static_cast<size_t>(i)],
                         buf + i * record_bytes)) {
          std::memset(buf + i * record_bytes, 0,
                      static_cast<size_t>(record_bytes));
          read_errors.fetch_add(1, std::memory_order_relaxed);
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        ring_done[static_cast<size_t>(slot)] = 1;
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* axl_open(const char** paths, int64_t n_files, int64_t record_bytes,
               int64_t batch, int shuffle, uint64_t seed, int n_threads,
               int queue_depth) {
  if (n_files <= 0 || record_bytes <= 0 || batch <= 0) return nullptr;
  Loader* L = new Loader();
  L->record_bytes = record_bytes;
  L->batch = batch;
  L->shuffle = shuffle != 0;
  L->seed = seed;
  for (int64_t i = 0; i < n_files; ++i) {
    int fd = open(paths[i], O_RDONLY);
    if (fd < 0) {
      for (int f : L->fds) close(f);
      delete L;
      return nullptr;
    }
    off_t sz = lseek(fd, 0, SEEK_END);
    L->fds.push_back(fd);
    L->file_base.push_back(L->total_records);
    L->total_records += static_cast<int64_t>(sz) / record_bytes;
  }
  if (L->total_records < batch) {
    for (int f : L->fds) close(f);
    delete L;
    return nullptr;
  }
  L->reshuffle_locked();
  int depth = queue_depth > 0 ? queue_depth : 4;
  L->ring.resize(static_cast<size_t>(depth));
  for (auto& b : L->ring)
    b.resize(static_cast<size_t>(batch * record_bytes));
  L->ring_seq.assign(static_cast<size_t>(depth), -1);
  L->ring_done.assign(static_cast<size_t>(depth), 0);
  int t = n_threads > 0 ? n_threads : 2;
  for (int w = 0; w < t; ++w)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

int64_t axl_num_records(void* h) {
  return h ? static_cast<Loader*>(h)->total_records : -1;
}

// Blocks until the next in-order batch is ready; copies it into out.
int axl_next(void* h, char* out) {
  if (!h) return -1;
  Loader* L = static_cast<Loader*>(h);
  int64_t slot = -1;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    int64_t want = L->next_out;
    L->cv_ready.wait(lk, [&] {
      if (L->stop) return true;
      for (size_t s = 0; s < L->ring_seq.size(); ++s) {
        if (L->ring_seq[s] == want && L->ring_done[s]) {
          slot = static_cast<int64_t>(s);
          return true;
        }
      }
      return false;
    });
    if (L->stop) return -1;
  }
  // `slot` is exclusively ours: it stays claimed (seq != -1) until we
  // release it below, and workers never touch a claimed+done slot.
  std::memcpy(out, L->ring[static_cast<size_t>(slot)].data(),
              static_cast<size_t>(L->batch * L->record_bytes));
  {
    std::lock_guard<std::mutex> lg(L->mu);
    L->ring_seq[static_cast<size_t>(slot)] = -1;
    L->ring_done[static_cast<size_t>(slot)] = 0;
    L->next_out++;
  }
  L->cv_free.notify_all();
  return 0;
}

// Count of records zero-filled because pread failed (IO error surface —
// poll after axl_next; nonzero means the epoch's data is suspect).
int64_t axl_error_count(void* h) {
  if (!h) return -1;
  return static_cast<Loader*>(h)->read_errors.load(
      std::memory_order_relaxed);
}

void axl_close(void* h) {
  if (!h) return;
  Loader* L = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop = true;
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& w : L->workers) w.join();
  for (int f : L->fds) close(f);
  delete L;
}

}  // extern "C"
