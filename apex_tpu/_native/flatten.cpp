// Host-side multi-tensor pack/unpack — apex_C parity.
//
// The reference's apex_C extension (csrc/flatten_unflatten.cpp:16-17) exposes
// torch's flatten/unflatten for DDP bucketing; the CUDA side keeps offset
// tables in TensorListMetadata (csrc/multi_tensor_apply.cuh:19-26).  On TPU
// the *device* packing is one XLA concatenate (multi_tensor/flat.py); what
// remains genuinely host-side is checkpoint/restore and host-staged
// superblock assembly over numpy buffers, where Python-loop memcpy is the
// bottleneck.  This file is that path: C++ scatter/gather over raw byte
// buffers, threaded across tensors.
//
// Plain C ABI (ctypes-friendly; no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Simple static partition of [0, n) across up to t threads.
template <typename F>
void parallel_for(int64_t n, int threads, F f) {
  if (threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) f(i);
    return;
  }
  int t = static_cast<int>(std::min<int64_t>(threads, n));
  std::vector<std::thread> pool;
  pool.reserve(t);
  for (int w = 0; w < t; ++w) {
    pool.emplace_back([=]() {
      for (int64_t i = w; i < n; i += t) f(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Gather n buffers into dst at the given byte offsets.
void apex_tpu_pack(const char** srcs, const int64_t* nbytes,
                   const int64_t* dst_offsets, int64_t n, char* dst,
                   int threads) {
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(dst + dst_offsets[i], srcs[i],
                static_cast<size_t>(nbytes[i]));
  });
}

// Scatter dst-resident bytes back out to n buffers.
void apex_tpu_unpack(const char* src, const int64_t* nbytes,
                     const int64_t* src_offsets, int64_t n, char** dsts,
                     int threads) {
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(dsts[i], src + src_offsets[i],
                static_cast<size_t>(nbytes[i]));
  });
}

}  // extern "C"
