"""Native host runtime: lazy g++ build + ctypes bindings.

The reference ships its host/device native layer as setuptools CUDA
extensions (setup.py:77-527).  The TPU build's device kernels are Pallas;
what stays native here is the *host* runtime — multi-tensor pack/unpack
(apex_C parity, flatten.cpp) and the prefetching record loader
(dataloader.cpp, the DALI role).  Sources compile lazily with g++ into a
shared object cached next to the package (keyed by source digest), bound
through ctypes — pybind11 is deliberately not required.

Everything degrades gracefully: if no toolchain is present,
``available()`` is False and callers fall back to numpy paths.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional, Sequence

import numpy as np

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ("flatten.cpp", "dataloader.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _digest() -> str:
    h = hashlib.sha256()
    for s in _SOURCES:
        with open(os.path.join(_SRC_DIR, s), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:12]


def _build() -> ctypes.CDLL:
    out = os.path.join(_SRC_DIR, f"_native_{_digest()}.so")
    if not os.path.exists(out):
        srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
        # build to a temp name then rename: atomic against concurrent builds
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_SRC_DIR)
        os.close(fd)
        try:
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
                 *srcs, "-o", tmp],
                check=True, capture_output=True, text=True)
            os.replace(tmp, out)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
    lib = ctypes.CDLL(out)
    lib.apex_tpu_pack.restype = None
    lib.apex_tpu_unpack.restype = None
    lib.axl_open.restype = ctypes.c_void_p
    lib.axl_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_int]
    lib.axl_next.restype = ctypes.c_int
    lib.axl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.axl_num_records.restype = ctypes.c_int64
    lib.axl_num_records.argtypes = [ctypes.c_void_p]
    lib.axl_error_count.restype = ctypes.c_int64
    lib.axl_error_count.argtypes = [ctypes.c_void_p]
    lib.axl_close.restype = None
    lib.axl_close.argtypes = [ctypes.c_void_p]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    with _lock:
        if _lib is None and _build_error is None:
            try:
                _lib = _build()
            except Exception as e:  # no toolchain / build failure
                _build_error = repr(e)
    return _lib


def available() -> bool:
    return get_lib() is not None


def build_error() -> Optional[str]:
    get_lib()
    return _build_error


# ---------------------------------------------------------------------------
# pack/unpack (apex_C flatten/unflatten parity, host side)
# ---------------------------------------------------------------------------


def pack_host(arrays: Sequence[np.ndarray], offsets: Sequence[int],
              total_bytes: int, *, threads: int = 0,
              out: Optional[np.ndarray] = None) -> np.ndarray:
    """Gather numpy arrays into one byte buffer at the given byte offsets.

    The native path threads the memcpys; without the toolchain this falls
    back to a numpy loop with identical results.
    """
    if out is None:
        out = np.zeros(total_bytes, np.uint8)
    assert out.nbytes >= total_bytes
    arrs = [np.ascontiguousarray(a) for a in arrays]
    lib = get_lib()
    if lib is None:
        for a, off in zip(arrs, offsets):
            out[off:off + a.nbytes] = a.view(np.uint8).reshape(-1)
        return out
    n = len(arrs)
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
    nbytes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrs])
    offs = (ctypes.c_int64 * n)(*list(offsets))
    threads = threads or min(8, max(1, os.cpu_count() or 1))
    lib.apex_tpu_pack(
        srcs, nbytes, offs, ctypes.c_int64(n),
        ctypes.c_void_p(out.ctypes.data), ctypes.c_int(threads))
    return out


def unpack_host(buf: np.ndarray, arrays: Sequence[np.ndarray],
                offsets: Sequence[int], *, threads: int = 0) -> None:
    """Scatter a byte buffer back into the (preallocated, contiguous)
    numpy arrays at the given byte offsets — in place."""
    buf = np.ascontiguousarray(buf.view(np.uint8).reshape(-1))
    lib = get_lib()
    if lib is None:
        for a, off in zip(arrays, offsets):
            flat = a.view(np.uint8).reshape(-1)
            flat[:] = buf[off:off + a.nbytes]
        return
    n = len(arrays)
    for a in arrays:
        assert a.flags["C_CONTIGUOUS"], "unpack_host needs contiguous dsts"
    dsts = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    nbytes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    offs = (ctypes.c_int64 * n)(*list(offsets))
    threads = threads or min(8, max(1, os.cpu_count() or 1))
    lib.apex_tpu_unpack(
        ctypes.c_void_p(buf.ctypes.data), nbytes, offs,
        ctypes.c_int64(n), dsts, ctypes.c_int(threads))
