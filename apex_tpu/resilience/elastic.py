"""Elastic-mesh resilience: sharded ZeRO checkpoints, collective
watchdog, and device-loss recovery.

PR 1 made single-process training preemption-safe and the flagship path
made GPT-1.3B ZeRO-sharded over the mesh "data" axis — this module is
where the two meet, the way Megatron-LM's ``--use-dist-ckpt`` sharded
state and TorchElastic's shrink-and-resume semantics meet in the
reference ecosystem (PAPERS.md):

- **sharded checkpoints** — :func:`save_zero_checkpoint` writes each
  data-axis rank's optimizer partition to its own ``shard_<r>.npz``
  with a per-shard CRC32 digest and a topology record in the manifest
  (format 3, :func:`apex_tpu.checkpoint.save_checkpoint` with
  ``shard_axis``); replicated params are stored once;
- **cross-topology restore** — a manifest saved on an N-device mesh
  restores onto an M-device mesh (including the M=1 debug restore):
  :func:`restore_zero_checkpoint` builds the M-topology target from
  the caller's state template and lets
  :func:`~apex_tpu.checkpoint.restore_checkpoint` re-partition the
  flat-buffer stacks (concat N → re-split M; only flat-schema tail
  padding may be trimmed/zero-filled).  The fit-plan dtype story rides
  the existing precision portability: bf16 state is stored as fp32, so
  a ``bf16_fit`` save round-trips any reshard at ≤ 1 bf16 ulp (0 in
  practice — bf16→fp32→bf16 is exact);
- **collective watchdog** — :class:`Watchdog` arms a timeout before
  each collective-bearing train step; on overrun it logs per-device
  last-heartbeat ages and step-duration percentiles (the straggler
  diagnostic) and escalates to the PR 1
  :class:`~apex_tpu.resilience.preemption.GracePeriodHandler`
  save-and-exit path;
- **device-loss recovery** — :func:`run_elastic_training` drives the
  resilient loop; when a step raises
  :class:`~apex_tpu.resilience.chaos.DeviceLossError` (injected
  deterministically by the chaos tier; a real deployment maps device
  failure to the same exception) it rebuilds the ZeRO step on the
  surviving submesh and resumes from the newest *intact* sharded
  checkpoint.

ISSUE 6 generalized all of this from the single "data" axis to the
full dp×tp×pp ``parallel_state`` mesh: format-4 multi-axis sharded
checkpoints (``shard_axes``, shard files keyed by mesh coordinates),
cross-topology restore across any (dp, tp, pp) reshape,
:func:`best_surviving_submesh` recovery (largest-divisor per axis,
shrinking dp before tp before pp), and per-axis watchdog stall
attribution (``Watchdog(mesh=...)`` → ``axis_groups`` in the hang
report).  See docs/resilience.md "3D topologies".

Escalation is cooperative, like everything in the grace-period design:
a watchdog firing flips the handler's stop flag, and the loop (which is
presumed stuck *slow*, not stuck *dead*) saves and exits at the next
step boundary.  A truly wedged collective needs the platform's external
watchdog to SIGTERM the process — which lands in the same
GracePeriodHandler path.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Optional, Sequence

log = logging.getLogger("apex_tpu.resilience")


class WatchdogTimeout(RuntimeError):
    """A watched step overran its deadline and no escalation target
    (handler / on_hang) was configured to absorb it."""


def _percentiles(durations: Sequence[float]) -> dict:
    if not durations:
        return {}
    s = sorted(durations)
    pick = lambda q: s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]
    return {"p50": pick(0.50), "p90": pick(0.90), "p99": pick(0.99),
            "max": s[-1], "n": len(s)}


class Watchdog:
    """Deadline monitor for collective-bearing train steps.

    Arm it around each step::

        wd = Watchdog(timeout=30.0, handler=grace_handler)
        for step, batch in enumerate(batches):
            with wd.step(step):
                state = train_step(state, batch)   # collectives inside

    A single daemon monitor thread checks the armed deadline.  On
    overrun it fires **once** per armed step: builds a diagnostic
    :meth:`report` (per-device last-heartbeat ages — a straggling or
    lost device shows up as the stale one — plus step-duration
    percentiles over the last ``history`` steps), logs it, and
    escalates, in order of availability:

    1. ``on_hang(report)`` callback, if given;
    2. ``handler.request_stop(reason=...)`` — the
       :class:`~apex_tpu.resilience.preemption.GracePeriodHandler`
       grace path: the loop writes a final checkpoint and exits
       cleanly at the next step boundary;
    3. neither configured: :class:`WatchdogTimeout` is raised at the
       next :meth:`step` entry (a hang must never be silent).

    ``timeout`` may be a number (seconds) or a callable
    ``durations -> seconds`` for an adaptive deadline (e.g. ``lambda d:
    10 * max(d[-20:])``); an adaptive deadline is UNARMED (infinite)
    until the first step completes and its duration history exists.

    Heartbeat granularity: the host observes step *completion*, which
    is a whole-mesh barrier — so by default every device in ``devices``
    (default: all local) is stamped together at each successful step,
    and the per-device ages diverge only via :meth:`mark_lost` (stops
    expecting a device, annotating it as gone rather than stale) or
    :meth:`beat` (integrations with a genuine per-device liveness
    signal — e.g. a platform health poller — call it to give the hang
    report real per-device resolution).
    """

    def __init__(self, timeout, *, handler=None,
                 on_hang: Optional[Callable[[dict], None]] = None,
                 devices: Optional[Sequence] = None,
                 history: int = 256, poll_interval: Optional[float] = None,
                 telemetry=None, mesh=None,
                 mesh_axes: Optional[dict] = None,
                 device_coords: Optional[dict] = None):
        self.timeout = timeout
        self.handler = handler
        self.on_hang = on_hang
        # optional TelemetryBus: every fire emits a typed `watchdog`
        # event (the report rides the flight-recorder ring into any
        # postmortem); emitted from the monitor thread — the bus is
        # thread-safe by contract
        self.telemetry = telemetry
        # per-axis attribution (ISSUE 6): give the watchdog the mesh
        # decomposition and its hang report names the dp/tp/pp GROUP
        # that stalled, not just the device.  Either pass ``mesh`` (a
        # jax.sharding.Mesh — axis names and coordinates are derived)
        # or explicit ``mesh_axes`` ({axis: size}, mesh order) +
        # ``device_coords`` ({device id: coordinate tuple}).
        if mesh is not None and mesh_axes is None:
            import numpy as _np

            arr = _np.asarray(mesh.devices)
            mesh_axes = {str(a): int(n)
                         for a, n in zip(mesh.axis_names, arr.shape)}
            device_coords = {
                getattr(arr[idx], "id", arr[idx]): tuple(int(i)
                                                         for i in idx)
                for idx in _np.ndindex(arr.shape)}
            if devices is None:
                devices = list(arr.reshape(-1))
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        self.device_coords = dict(device_coords) if device_coords else None
        if devices is None:
            import jax

            devices = jax.devices()
        self.device_ids = [getattr(d, "id", d) for d in devices]
        self.history = int(history)
        self.poll_interval = poll_interval
        self.durations: list = []
        self.last_beat = {d: None for d in self.device_ids}
        self.lost: set = set()
        self.fired_steps: list = []
        self.last_report: Optional[dict] = None
        self._armed_step: Optional[int] = None
        self._deadline: Optional[float] = None
        self._fired_this_arm = False
        self._pending_raise: Optional[dict] = None
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- arming ----------------------------------------------------------

    def _current_timeout(self) -> float:
        if callable(self.timeout):
            if not self.durations:
                # adaptive deadlines have nothing to adapt to before
                # the first completed step: stay unarmed rather than
                # crash the documented `lambda d: 10 * max(d[-20:])`
                return float("inf")
            return float(self.timeout(self.durations))
        return float(self.timeout)

    def step(self, step_index: int):
        """Context manager arming the deadline for one train step."""
        return _ArmedStep(self, int(step_index))

    def _arm(self, step_index: int) -> None:
        with self._lock:
            if self._pending_raise is not None:
                report, self._pending_raise = self._pending_raise, None
                raise WatchdogTimeout(
                    f"step {report['step']} overran the "
                    f"{report['timeout']:.3g}s watchdog deadline "
                    f"(report: {report})")
            self._armed_step = step_index
            self._fired_this_arm = False
            self._deadline = time.monotonic() + self._current_timeout()
        self._ensure_thread()
        self._wake.set()

    def _disarm(self, step_index: int, duration: float, ok: bool) -> None:
        with self._lock:
            self._armed_step = None
            self._deadline = None
            if ok:
                self.durations.append(duration)
                del self.durations[: -self.history]
                now = time.monotonic()
                for d in self.device_ids:
                    if d not in self.lost:
                        self.last_beat[d] = now

    # -- diagnosis -------------------------------------------------------

    def mark_lost(self, device_ids) -> None:
        """Stop expecting heartbeats from ``device_ids`` (they are gone,
        not straggling)."""
        self.lost.update(getattr(d, "id", d) for d in device_ids)

    def beat(self, device_id) -> None:
        """Record a genuine per-device liveness observation (platform
        health poller, per-device completion event).  Without these,
        the host only sees whole-mesh step completion and all live
        devices carry the same age."""
        self.last_beat[getattr(device_id, "id", device_id)] = (
            time.monotonic())

    def step_percentiles(self) -> dict:
        """Duration percentiles over the retained step history."""
        return _percentiles(self.durations)

    def max_heartbeat_age(self) -> Optional[float]:
        """Age in seconds of the STALEST live device's last heartbeat
        (None before any step completes).  The log-line stall signal:
        a climbing age means the mesh stopped completing steps well
        before the deadline escalates."""
        now = time.monotonic()
        ages = [now - t for d, t in self.last_beat.items()
                if t is not None and d not in self.lost]
        return max(ages) if ages else None

    def axis_report(self) -> Optional[dict]:
        """Per-axis stall attribution (requires mesh_axes/device_coords):
        for every mesh axis, each coordinate group's stalest live
        heartbeat age and lost-device list, plus ``suspect`` — per axis,
        the group index holding the overall stalest (or a lost) device.
        A tp group whose collective wedged shows up as ONE suspect
        tensor index with every data index implicated symmetrically —
        the signature that distinguishes a tp-leg fault from a dp
        straggler."""
        if not self.mesh_axes or not self.device_coords:
            return None
        now = time.monotonic()
        axes = list(self.mesh_axes)
        groups: dict = {a: {} for a in axes}
        never = {a: set() for a in axes}
        for d, coords in self.device_coords.items():
            age = None
            t = self.last_beat.get(d)
            if t is not None and d not in self.lost:
                age = round(now - t, 3)
            for ai, a in enumerate(axes):
                g = groups[a].setdefault(int(coords[ai]),
                                         {"max_age_s": None, "lost": []})
                if d in self.lost:
                    g["lost"].append(d)
                elif age is not None and (g["max_age_s"] is None
                                          or age > g["max_age_s"]):
                    g["max_age_s"] = age
                elif age is None:
                    # a live device that NEVER heartbeat is infinitely
                    # stale, not infinitely fresh — score it as such so
                    # a group wedged before its first completed step
                    # cannot make a healthy, freshly-beaten group the
                    # suspect (the report keeps max_age_s None: "no
                    # observation", JSON-safe)
                    never[a].add(int(coords[ai]))
        suspect = {}
        for a in axes:
            scored = [(gi, (len(g["lost"]),
                            float("inf") if gi in never[a]
                            else g["max_age_s"] or 0.0))
                      for gi, g in sorted(groups[a].items())]
            if not scored:
                continue
            worst = max(scored, key=lambda x: x[1])
            best = min(scored, key=lambda x: x[1])
            # only name a suspect when the axis actually DIVERGES —
            # identical ages on every group (the healthy whole-mesh
            # barrier case) implicate nothing
            if worst[1] > best[1]:
                suspect[a] = worst[0]
        return {"mesh_axes": dict(self.mesh_axes),
                "groups": {a: {str(k): v for k, v in sorted(gs.items())}
                           for a, gs in groups.items()},
                "suspect": suspect}

    def report(self) -> dict:
        """Straggler diagnostic: per-device heartbeat age + percentiles
        (+ per-axis group attribution when the mesh decomposition is
        configured)."""
        now = time.monotonic()
        ages = {d: (None if t is None else round(now - t, 3))
                for d, t in self.last_beat.items()}
        out = {
            "step": self._armed_step,
            "timeout": self._current_timeout(),
            "device_heartbeat_age_s": ages,
            "lost_devices": sorted(self.lost),
            "step_duration_percentiles": self.step_percentiles(),
        }
        ax = self.axis_report()
        if ax is not None:
            out["axis_groups"] = ax
        return out

    @property
    def expired(self) -> bool:
        """True once any armed step has overrun its deadline."""
        return bool(self.fired_steps)

    # -- monitor thread --------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="apex-tpu-watchdog", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop:
            with self._lock:
                deadline = self._deadline
                armed = (self._armed_step is not None
                         and not self._fired_this_arm)
            if not armed or deadline is None:
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue
            wait = deadline - time.monotonic()
            if wait > 0:
                quantum = self.poll_interval or max(0.005, min(wait, 0.05))
                time.sleep(min(wait, quantum))
                continue
            self._fire()

    def _fire(self) -> None:
        with self._lock:
            if self._fired_this_arm or self._armed_step is None:
                return
            self._fired_this_arm = True
            step = self._armed_step
        report = self.report()
        report["step"] = step
        self.fired_steps.append(step)
        self.last_report = report
        log.error("watchdog: step %d overran its %.3gs deadline — %s",
                  step, report["timeout"], report)
        if self.telemetry is not None:
            try:
                self.telemetry.emit("watchdog", step=step, report=report)
            except Exception:  # pragma: no cover — never break escalation
                log.exception("watchdog telemetry emit failed")
        if self.on_hang is not None:
            self.on_hang(report)
        elif self.handler is not None:
            self.handler.request_stop(
                reason=f"watchdog_timeout(step={step})")
        else:
            with self._lock:
                self._pending_raise = report

    def close(self) -> None:
        self._stop = True
        self._wake.set()

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _ArmedStep:
    def __init__(self, wd: Watchdog, step_index: int):
        self.wd = wd
        self.step_index = step_index
        self.t0 = 0.0

    def __enter__(self):
        self.wd._arm(self.step_index)
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, *exc):
        self.wd._disarm(self.step_index, time.monotonic() - self.t0,
                        ok=exc_type is None)


# ---------------------------------------------------------------------------
# Sharded ZeRO checkpoint convenience wrappers
# ---------------------------------------------------------------------------


def save_zero_checkpoint(ckpt_dir: str, state: Any, *, step: int,
                         shardings: Any, shard_axis: str = "data",
                         **kw) -> str:
    """Sharded save of a ZeRO train state: leaves whose spec leads with
    ``shard_axis`` (the per-rank optimizer partitions, leading
    ``[n_shards]`` axis) go to per-shard files with per-shard CRC32
    digests; replicated leaves are stored once.  Thin veneer over
    :func:`apex_tpu.checkpoint.save_checkpoint` — all its knobs
    (``blocking``, ``retry``, ``keep``, and the format-4 multi-axis
    ``shard_axes=`` mapping, which supersedes ``shard_axis``) pass
    through."""
    from apex_tpu import checkpoint as ckpt

    if kw.get("shard_axes") is not None:
        shard_axis = None  # multi-axis form supersedes the default axis
    return ckpt.save_checkpoint(ckpt_dir, state, step=step,
                                shardings=shardings, shard_axis=shard_axis,
                                **kw)


def restore_zero_checkpoint(ckpt_dir: str, target: Any, *, mesh=None,
                            shardings: Any = None,
                            max_fallbacks: Optional[int] = None):
    """Cross-topology resilient restore: the newest *intact* sharded
    checkpoint under ``ckpt_dir``, re-partitioned to ``target``'s
    topology (whatever shard count its leading axes carry — build the
    target with the CURRENT mesh's ``build_flagship_train_step`` and an
    8-device save restores onto 4 devices, or 1).  Walks corrupt
    candidates newest-first exactly like
    :func:`~apex_tpu.resilience.restore_resilient` (it IS that
    function; this alias exists so call sites read as topology-aware)."""
    from apex_tpu.resilience.restore import restore_resilient

    return restore_resilient(ckpt_dir, target, mesh=mesh,
                             shardings=shardings,
                             max_fallbacks=max_fallbacks)


# ---------------------------------------------------------------------------
# Elastic training: shrink the mesh on device loss and keep going
# ---------------------------------------------------------------------------


def largest_divisor_submesh(devices: Sequence, batch_size: int) -> list:
    """The largest prefix of ``devices`` whose length divides
    ``batch_size`` — the standard ``select_devices`` policy for
    :func:`run_elastic_training`: a data-sharded step needs the global
    batch to divide the mesh's data axis, so losing 2 of 8 devices
    (6 survivors) must rebuild on 4, not 6."""
    devices = list(devices)
    for m in range(len(devices), 0, -1):
        if batch_size % m == 0:
            return devices[:m]
    return devices[:1]


def best_surviving_submesh(survivors: Sequence, mesh_shape,
                           *, batch_size: Optional[int] = None):
    """Pick the best (dp, tp, pp) submesh fitting on the survivors — the
    3-D generalization of :func:`largest_divisor_submesh` and the
    default ``select_mesh`` policy of :func:`run_elastic_training`.

    Per axis the candidate sizes are the divisors of the old size
    (largest-divisor policy); the search prefers to **shrink dp before
    tp before pp** — i.e. it keeps the pipeline depth if at all
    possible (a pp change re-maps every stage's layer slices), then the
    tensor width (a tp change re-slices every weight), and takes the
    shrink out of the data axis, whose reshard is pure flat-buffer
    re-partition.  ``batch_size`` additionally requires the chosen dp
    to divide the global batch.  Returns ``(devices, (dp, tp, pp))`` —
    the first dp·tp·pp survivors and the chosen shape."""
    dp, tp, pp = (int(x) for x in mesh_shape)
    survivors = list(survivors)
    n = len(survivors)

    def _divisors_desc(k):
        return [d for d in range(k, 0, -1) if k % d == 0]

    for pp_c in _divisors_desc(pp):
        for tp_c in _divisors_desc(tp):
            for dp_c in _divisors_desc(dp):
                if dp_c * tp_c * pp_c > n:
                    continue
                if batch_size is not None and batch_size % dp_c:
                    continue
                return survivors[: dp_c * tp_c * pp_c], (dp_c, tp_c, pp_c)
    return survivors[:1], (1, 1, 1)


@dataclasses.dataclass
class ElasticResult:
    """Outcome of :func:`run_elastic_training`."""

    state: Any
    step: int
    restarts: int
    devices: list                 # surviving devices at exit
    lost_devices: list            # ids lost along the way
    preempted: bool
    stop_reason: Optional[str]
    loop_results: list            # per-attempt LoopResult
    mesh_shape: Optional[tuple] = None  # (dp, tp, pp) at exit (3-D runs)


def run_elastic_training(
    build: Callable[[Sequence], tuple],
    devices: Sequence,
    batches: Optional[Sequence] = None,
    *,
    data_iter=None,
    ckpt_dir: str,
    save_every: int = 1,
    keep: Optional[int] = None,
    shard_axis: str = "data",
    handler=None,
    watchdog: Optional[Watchdog] = None,
    guard=None,
    max_restarts: int = 3,
    min_devices: int = 1,
    select_devices: Optional[Callable[[list], list]] = None,
    mesh_shape: Optional[Sequence[int]] = None,
    select_mesh: Optional[Callable] = None,
    batch_size: Optional[int] = None,
    start_step: int = 0,
    on_step: Optional[Callable[[int], None]] = None,
    log_every: int = 0,
    log_fn: Optional[Callable[[str], None]] = None,
    telemetry=None,
    telemetry_scalars=None,
    profile_sampler=None,
):
    """Drive ZeRO training across device loss.

    ``build(devices) -> (step_fn, state, shardings)`` constructs the
    train step for a given device set — for the flagship this wraps
    :func:`~apex_tpu.transformer.testing.build_flagship_train_step`
    (whose ZeRO state carries a leading ``[n_shards]`` axis and whose
    ``shardings`` lead with ``shard_axis`` for the per-rank partition
    leaves).  The returned ``state`` doubles as the restore *target*:
    its topology defines the M of any N→M reshard.

    The inner loop is
    :func:`~apex_tpu.transformer.testing.run_resilient_training` with
    sharded saves (``shard_axis``).  When a step (or ``on_step`` hook)
    raises :class:`~apex_tpu.resilience.chaos.DeviceLossError`, the
    harness:

    1. drops the lost devices (``watchdog.mark_lost`` when a watchdog
       is attached — their heartbeats become diagnostic, not noise);
    2. rebuilds via ``build(survivors)`` — a fresh mesh and ZeRO step
       over the shrunken "data" axis;
    3. restores the newest intact sharded checkpoint cross-topology
       into the rebuilt state (N→M re-partition of every flat-buffer
       stack);
    4. resumes from the restored step with the remaining ``batches``
       (which must therefore be a Sequence, not a one-shot iterator).

    ``data_iter`` (instead of ``batches``, ISSUE 7): a checkpointable
    input-pipeline iterator (``state_dict``/``load_state_dict`` — e.g.
    :class:`apex_tpu.data.ShardedRecordIterator`, optionally behind
    :class:`~apex_tpu.data.AsyncPrefetcher`).  Saves then carry the
    iterator position in the checkpoint manifest (``data_state``), and
    the device-loss recovery arc restores it alongside the model state
    — *cross-topology included*: the iterator's slot-cursor state is
    dp-decomposition-independent, so a dp→dp' rebuild re-partitions
    shard ownership by re-slicing while the consumed sample-id stream
    stays bitwise identical to an uninterrupted run (docs/data.md).  A
    plain generator here is rejected (silent replay of training data is
    exactly the failure mode this parameter closes); a recovery that
    finds a checkpoint saved *without* ``data_state`` raises instead of
    guessing the position.

    ``select_devices(survivors) -> devices`` picks the rebuild submesh
    from the raw survivor list — a data-sharded step needs the global
    batch to divide the mesh, so losing 2 of 8 devices usually means
    rebuilding on 4 of the 6 survivors
    (:func:`largest_divisor_submesh` is the standard policy); default
    uses every survivor.

    **3-D meshes** (ISSUE 6): pass ``mesh_shape=(dp, tp, pp)``.  The
    harness then calls ``build(devices, mesh_shape=shape)``, saves
    *format-4* multi-axis sharded checkpoints (``shard_axes`` over the
    full ``parallel_state`` mesh — shard files keyed by (d, p, t)
    coordinates), and on device loss picks the best surviving 3-D
    submesh via ``select_mesh(survivors, mesh_shape) -> (devices,
    shape)`` (default :func:`best_surviving_submesh` with
    ``batch_size`` — largest-divisor per axis, shrinking dp before tp
    before pp) before rebuilding through ``parallel_state`` and
    restoring the multi-axis shard set cross-topology.  A
    ``select_devices`` filter still applies first: the mesh picker
    chooses from the devices the filter allows.  ``device_loss``
    / ``ckpt_restore`` telemetry and the bus mesh stamp then carry the
    full ``mesh_axes`` decomposition, so post-recovery events are
    attributable to the survivor submesh per axis.

    Gives up (re-raises) after ``max_restarts`` rebuilds or when fewer
    than ``min_devices`` survive.  Preemption/watchdog escalation
    behave exactly as in the inner loop: final blocking (sharded) save,
    clean exit with ``preempted=True``.

    ``telemetry`` (:class:`apex_tpu.telemetry.TelemetryBus`): on top of
    the inner loop's events, each recovery emits ``device_loss`` (lost
    ids, survivor count) and ``ckpt_restore`` (resumed step, restore
    wall), books rebuild/restore time against goodput, and re-stamps
    the bus's mesh topology with the survivor submesh so post-recovery
    events are attributable to the shrunken mesh.  The inner loop's
    exception path has already flushed a ``postmortem_*.jsonl`` by the
    time the rebuild starts.  ``profile_sampler`` (ISSUE 9) rides into
    the inner loop unchanged, so phase/collective/HBM attribution keeps
    sampling across rebuilds — post-recovery ``profile`` events carry
    the survivor mesh stamp.
    """
    from apex_tpu.checkpoint.checkpoint import (_complete_steps,
                                                load_data_state)
    from apex_tpu.resilience.chaos import DeviceLossError
    from apex_tpu.transformer.testing import run_resilient_training

    emit = log_fn or (lambda msg: log.info("%s", msg))
    data_initial_state = None
    if data_iter is not None:
        if batches is not None:
            raise ValueError("pass batches OR data_iter, not both")
        if not (hasattr(data_iter, "state_dict")
                and hasattr(data_iter, "load_state_dict")):
            raise TypeError(
                f"data_iter {type(data_iter).__name__} is not "
                "checkpointable (no state_dict/load_state_dict) — an "
                "elastic recovery would silently replay or skip "
                "training data; use apex_tpu.data.ShardedRecordIterator "
                "(or AsyncPrefetcher around it)")
        # a restart before any checkpoint exists must rewind the
        # iterator to where THIS invocation found it, not to zero
        data_initial_state = data_iter.state_dict()
        if (isinstance(data_initial_state, dict)
                and "slots" in data_initial_state
                and len(data_initial_state["slots"])
                != data_initial_state.get("batch_size")):
            # this single-controller loop checkpoints ONE iterator's
            # state; a rank-local (dp_size>1) slot slice would save a
            # partial position that a dp→dp' restore cannot re-slice
            raise ValueError(
                f"data_iter covers only slots "
                f"{data_initial_state['slots']} of the "
                f"{data_initial_state.get('batch_size')}-slot global "
                "batch (a rank-local dp_size>1 iterator).  Drive this "
                "loop with the full-batch iterator (dp_size=1) — "
                "elastic dp→dp' re-partitioning re-slices slot "
                "ownership from the full vector — or merge per-rank "
                "states with apex_tpu.data.merge_data_states in a "
                "multi-process launcher.")
    elif batches is None:
        raise ValueError("run_elastic_training needs batches or data_iter")
    devices = list(devices)
    lost: list = []
    restarts = 0
    loop_results: list = []
    shard_axes = None
    if mesh_shape is not None:
        mesh_shape = tuple(int(x) for x in mesh_shape)

    def _shard_axes(shape):
        dp, tp, pp = shape
        # the parallel_state mesh order — and the stacking order of the
        # flagship opt leaves ([dp, pp, tp, shard])
        return {"data": dp, "pipeline": pp, "tensor": tp}

    def _build(devs, shape):
        if shape is None:
            return build(devs)
        return build(devs, mesh_shape=shape)

    if mesh_shape is not None:
        shard_axes = _shard_axes(mesh_shape)
    step_fn, state, shardings = _build(devices, mesh_shape)
    step = start_step

    while True:
        try:
            result = run_resilient_training(
                step_fn, state,
                batches[step - start_step:] if data_iter is None else None,
                data_iter=data_iter,
                ckpt_dir=ckpt_dir, save_every=save_every, keep=keep,
                shardings=shardings,
                shard_axis=None if shard_axes else shard_axis,
                shard_axes=shard_axes,
                handler=handler, guard=guard, watchdog=watchdog,
                start_step=step, on_step=on_step,
                log_every=log_every, log_fn=log_fn,
                telemetry=telemetry, telemetry_scalars=telemetry_scalars,
                profile_sampler=profile_sampler)
            loop_results.append(result)
            return ElasticResult(
                state=result.state, step=result.step, restarts=restarts,
                devices=devices, lost_devices=lost,
                preempted=result.preempted,
                stop_reason=result.stop_reason, loop_results=loop_results,
                mesh_shape=mesh_shape)
        except DeviceLossError as e:
            lost_ids = set(e.device_ids)
            lost.extend(sorted(lost_ids))
            survivors = [d for d in devices
                         if getattr(d, "id", d) not in lost_ids]
            new_shape = mesh_shape
            if mesh_shape is not None:
                if select_devices is not None:
                    # a device-filter policy (exclude known-bad hosts)
                    # composes with the mesh picker: filter the pool
                    # first, then choose the submesh from what the
                    # policy allows — never silently drop the filter
                    survivors = list(select_devices(survivors))
                picker = select_mesh or (
                    lambda s, shape: best_surviving_submesh(
                        s, shape, batch_size=batch_size))
                survivors, new_shape = picker(survivors, mesh_shape)
                survivors = list(survivors)
            elif select_devices is not None:
                survivors = list(select_devices(survivors))
            restarts += 1
            if telemetry is not None:
                # no step stamp: the loss surfaced as an exception, so
                # the exact faulting step lives in the inner loop's
                # postmortem (already flushed), not here
                ev = dict(
                    device_ids=sorted(lost_ids),
                    survivors=len(survivors), restarts=restarts,
                    recoverable=(restarts <= max_restarts
                                 and len(survivors) >= max(1, min_devices)))
                if new_shape is not None:
                    ev["mesh_axes"] = _shard_axes(new_shape)
                telemetry.emit("device_loss", **ev)
            if restarts > max_restarts:
                raise
            if len(survivors) < max(1, min_devices):
                raise DeviceLossError(
                    e.device_ids,
                    detail=f"only {len(survivors)} devices survive, "
                           f"min_devices={min_devices}") from e
            if watchdog is not None:
                watchdog.mark_lost(lost_ids)
            devices = survivors
            mesh_shape = new_shape
            if mesh_shape is not None:
                shard_axes = _shard_axes(mesh_shape)
            emit(f"[elastic] lost device(s) {sorted(lost_ids)} — "
                 f"rebuilding on {len(devices)} survivors"
                 + (f" as (dp, tp, pp)={mesh_shape}"
                    if mesh_shape is not None else "")
                 + f" (restart {restarts}/{max_restarts})")
            t_rebuild = time.monotonic()
            step_fn, state, shardings = _build(devices, mesh_shape)
            if telemetry is not None:
                telemetry.accountant().pause(
                    time.monotonic() - t_rebuild, "rebuild")
                stamp = {
                    "n_devices": len(devices),
                    "platform": getattr(devices[0], "platform", "unknown")
                    if devices else "none",
                    "lost_devices": sorted(lost)}
                if mesh_shape is not None:
                    stamp["mesh_axes"] = _shard_axes(mesh_shape)
                telemetry.set_mesh(stamp)
            if _complete_steps(ckpt_dir):
                t_restore = time.monotonic()
                state, step = restore_zero_checkpoint(ckpt_dir, state)
                if data_iter is not None:
                    # same manifest, same step: the iterator resumes at
                    # exactly the sample the restored weights last saw —
                    # across a dp→dp' reshape too (the state is global,
                    # ownership re-slices)
                    ds = load_data_state(ckpt_dir, step=step)
                    if ds is None:
                        raise RuntimeError(
                            f"checkpoint step {step} carries no "
                            "data_state but this run trains from a "
                            "checkpointable data_iter — resuming would "
                            "replay or skip training data.  The "
                            "checkpoint was saved by a loop without "
                            "data_iter wiring; restart from a caller "
                            "that manages the position.") from e
                    data_iter.load_state_dict(ds)
                if telemetry is not None:
                    telemetry.accountant().pause(
                        time.monotonic() - t_restore, "restore")
                    telemetry.emit(
                        "ckpt_restore", step=step,
                        wall_ms=round((time.monotonic() - t_restore) * 1e3,
                                      3),
                        n_shards=len(devices), reason="device_loss")
                if step < start_step:
                    # the caller only holds batches for steps >=
                    # start_step; a negative batches slice would
                    # silently train on the wrong tail of the window
                    raise RuntimeError(
                        f"elastic restore fell back to step {step}, "
                        f"before this run's start_step={start_step} — "
                        "the batches for that range are not available "
                        "here; restart the job from a caller that "
                        "holds them") from e
                emit(f"[elastic] resumed from sharded checkpoint step "
                     f"{step} on the {len(devices)}-device submesh")
            else:
                step = start_step
                if data_iter is not None:
                    data_iter.load_state_dict(data_initial_state)
                emit("[elastic] no checkpoint yet — restarting from "
                     f"step {step}")
