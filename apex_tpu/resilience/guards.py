"""Divergence guards: skip bad steps, diagnose persistent blow-ups.

The amp loss scaler already implements Megatron/apex-style skip-on-overflow
(``LossScaler.unscale`` → ``step_if_finite``), but (a) non-amp fp32 runs
had no equivalent, and (b) nothing ever *stopped* a run that skips forever
— the reference happily divides its loss scale down to ``min_scale`` and
keeps burning accelerator time on NaNs.  :class:`StepGuard` unifies both:

    guard = StepGuard(max_consecutive_skips=5)
    ...
    finite = guard.check(grads)          # non-amp: fused all-finite reduce
    # (amp runs instead reuse scaler.unscale's `finite` — same machinery)
    new_p, new_o = opt.step_if_finite(grads, opt_state, params, finite)
    guard.update(finite, grads)          # host side: count + diagnose

``update`` raises :class:`DivergenceError` naming the first non-finite
leaf (path + nan/inf counts) once ``max_consecutive_skips`` consecutive
steps have been skipped — a diagnostic, not a mystery hang.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import numpy as np

from apex_tpu.utils.tree import tree_isfinite


class DivergenceError(RuntimeError):
    """Training skipped too many consecutive steps on non-finite values."""


def global_grad_norm(tree: Any) -> Optional[float]:
    """Host-side global L2 norm over every floating leaf of ``tree``
    (nan/inf propagate — a diverged tree reports ``nan``/``inf``, which
    is exactly the diagnostic).  Only call on the failure path: this
    device_gets every leaf."""
    if tree is None:
        return None
    total = 0.0
    seen = False
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(jax.device_get(leaf))
        if not np.issubdtype(arr.dtype, np.floating):
            try:  # bf16/fp8 (ml_dtypes) are floating but np disagrees
                import jax.numpy as jnp

                if not jnp.issubdtype(arr.dtype, jnp.floating):
                    continue
                arr = arr.astype(np.float32)
            except (ImportError, TypeError, ValueError):
                # an exotic dtype jnp can't classify/convert is a
                # legitimate skip; anything else (EX001: a broad
                # except here once swallowed EVERY error) must surface
                # — a silently under-reported grad norm poisons the
                # divergence diagnostic it feeds
                continue
        seen = True
        total += float(np.sum(np.square(arr.astype(np.float64))))
    return float(np.sqrt(total)) if seen else None


def first_nonfinite_leaf(tree: Any) -> Optional[str]:
    """Human-readable description of the first leaf containing a non-finite
    value: ``"['dense']['w']: 3 nan, 1 inf (of 128)"``; None if clean.

    Host-side (device_get per leaf until the culprit is found) — only call
    on the failure path."""
    import jax.numpy as jnp

    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(jax.device_get(leaf))
        # jnp.issubdtype, not np: bf16 (ml_dtypes) must count as floating
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if not np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(np.float32)  # bf16/fp8 → np ufunc-friendly
        finite = np.isfinite(arr)
        if finite.all():
            continue
        nan = int(np.isnan(arr).sum())
        inf = int((~finite).sum()) - nan
        return (f"{jax.tree_util.keystr(path)}: {nan} nan, {inf} inf "
                f"(of {arr.size})")
    return None


@dataclasses.dataclass
class StepGuard:
    """Host-side skip-step policy shared by amp and non-amp train loops.

    ``max_consecutive_skips`` — raise :class:`DivergenceError` when this
    many steps in a row were skipped (0/negative disables raising).
    The counters are plain Python ints (one host sync per step on the
    ``finite`` scalar — the same sync the loop's logging already pays)."""

    max_consecutive_skips: int = 8
    #: optional :class:`~apex_tpu.telemetry.TelemetryBus` — every skip
    #: is then emitted as a typed ``skip`` event (grad-norm + loss
    #: scale included), so divergence shows up in the structured stream
    #: and the crash flight recorder, not just in counters
    telemetry: Any = None
    consecutive: int = dataclasses.field(default=0, init=False)
    total_skipped: int = dataclasses.field(default=0, init=False)
    total_steps: int = dataclasses.field(default=0, init=False)

    def check(self, tree: Any):
        """Device-side fused all-finite reduction over ``tree`` (grads or
        loss).  For amp runs this is redundant — ``scaler.unscale`` already
        returns ``finite``; feed that to :meth:`update` instead."""
        return tree_isfinite(tree)

    def update(self, finite, tree: Any = None, *,
               loss_scale: Any = None, step: Optional[int] = None) -> bool:
        """Record one step's outcome; returns True if the step was skipped.

        ``tree`` (typically the grads) is only touched on the skip path,
        to compute the global grad-norm and (on the raise path) name the
        first non-finite leaf.  ``loss_scale`` — the current scale
        (device scalar or float), device_get only on the skip path.
        ``step`` stamps the emitted ``skip`` event."""
        self.total_steps += 1
        if bool(finite):
            self.consecutive = 0
            return False
        self.consecutive += 1
        self.total_skipped += 1
        # skip-path diagnostics (skips are rare; host syncs are fine here)
        scale = None
        if loss_scale is not None:
            try:
                scale = float(jax.device_get(loss_scale))
            except Exception:
                pass
        gnorm = global_grad_norm(tree)
        if self.telemetry is not None:
            self.telemetry.emit(
                "skip", step=step, consecutive=self.consecutive,
                total_skipped=self.total_skipped,
                total_steps=self.total_steps,
                grad_norm=gnorm, loss_scale=scale)
        if 0 < self.max_consecutive_skips <= self.consecutive:
            culprit = first_nonfinite_leaf(tree) if tree is not None else None
            where = f" — first non-finite leaf: {culprit}" if culprit else ""
            if gnorm is not None:
                where += f"; global grad-norm {gnorm:.6g}"
            if scale is not None:
                where += f"; loss scale {scale:g}"
            raise DivergenceError(
                f"{self.consecutive} consecutive steps produced non-finite "
                f"values ({self.total_skipped}/{self.total_steps} steps "
                f"skipped so far){where}. The run has diverged; lower the "
                "learning rate, raise loss-scale min_scale, or restore an "
                "earlier checkpoint (apex_tpu.resilience.restore_resilient).")
        return True

    def sync_from_scaler(self, scaler_state) -> None:
        """Adopt the monotonic ``skipped`` counter a
        :class:`~apex_tpu.amp.scaler.LossScaleState` carries on device, so
        amp runs restored from checkpoint keep an accurate total."""
        if getattr(scaler_state, "skipped", None) is not None:
            self.total_skipped = int(jax.device_get(scaler_state.skipped))
