"""Training resilience: survive preemption, flaky storage, and divergence.

The hardening layer the 2021 reference never had (its fault story is
per-rank ``torch.save``, SURVEY §5.4) and every production JAX training
stack ships:

- **async checkpointing** — ``save_checkpoint(..., blocking=False)``
  overlaps disk serialization with training; fence-on-next-save/exit
  semantics, exponential-backoff retry on transient storage errors
  (:mod:`~apex_tpu.resilience.async_checkpoint`,
  :class:`~apex_tpu.checkpoint.RetryPolicy`);
- **integrity** — per-array CRC32 digests in the manifest;
  :func:`restore_resilient` verifies on load and falls back to the newest
  intact older checkpoint on corruption
  (:mod:`~apex_tpu.resilience.restore`);
- **preemption** — :class:`GracePeriodHandler` turns SIGTERM/SIGINT into a
  flag the train loop polls at step boundaries: final checkpoint, clean
  exit (:mod:`~apex_tpu.resilience.preemption`);
- **divergence guards** — :class:`StepGuard` unifies skip-on-non-finite
  for amp and non-amp runs and raises a diagnostic naming the first
  non-finite leaf after K consecutive skips
  (:mod:`~apex_tpu.resilience.guards`);
- **fault injection** — :mod:`~apex_tpu.resilience.chaos` reproduces all
  of the above deterministically on CPU for the test tier (transient write
  errors, corrupted/truncated array files, simulated preemption, and —
  mesh-aware — device loss, shard corruption, slow collectives);
- **elastic mesh** — :mod:`~apex_tpu.resilience.elastic`: sharded ZeRO
  checkpoints (per-rank partition files + per-shard CRC32 + topology
  record), cross-topology N→M restore, the :class:`Watchdog` collective
  deadline monitor, and :func:`run_elastic_training` device-loss
  recovery (rebuild on the surviving submesh, resume from the newest
  intact shard set).

See ``docs/resilience.md`` for the full semantics (fencing rules,
retention, sharded manifest format, reshard protocol, multi-host notes).
"""

from apex_tpu.checkpoint.checkpoint import (  # noqa: F401
    CheckpointCorruptionError,
    RetryPolicy,
    verify_checkpoint,
)
from apex_tpu.resilience.async_checkpoint import (  # noqa: F401
    AsyncSaveError,
    in_flight,
    wait_for_save,
)
from apex_tpu.resilience.elastic import (  # noqa: F401
    ElasticResult,
    Watchdog,
    WatchdogTimeout,
    best_surviving_submesh,
    largest_divisor_submesh,
    restore_zero_checkpoint,
    run_elastic_training,
    save_zero_checkpoint,
)
from apex_tpu.resilience.guards import (  # noqa: F401
    DivergenceError,
    StepGuard,
    first_nonfinite_leaf,
    global_grad_norm,
)
from apex_tpu.resilience.preemption import GracePeriodHandler  # noqa: F401
from apex_tpu.resilience.restore import (  # noqa: F401
    CheckpointFallbackWarning,
    restore_resilient,
)

__all__ = [
    "AsyncSaveError",
    "CheckpointCorruptionError",
    "CheckpointFallbackWarning",
    "DivergenceError",
    "ElasticResult",
    "GracePeriodHandler",
    "RetryPolicy",
    "StepGuard",
    "Watchdog",
    "WatchdogTimeout",
    "best_surviving_submesh",
    "first_nonfinite_leaf",
    "global_grad_norm",
    "in_flight",
    "largest_divisor_submesh",
    "restore_resilient",
    "restore_zero_checkpoint",
    "run_elastic_training",
    "save_zero_checkpoint",
    "verify_checkpoint",
    "wait_for_save",
]
