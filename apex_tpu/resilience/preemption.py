"""Graceful-shutdown handling for preemptible TPU workers.

Preemptible/spot TPU VMs get a SIGTERM and a short grace window before the
machine disappears.  The reference (2021 apex) has nothing here — a killed
run loses everything since its last epoch-boundary ``torch.save``.
:class:`GracePeriodHandler` converts the signal into a cooperative flag the
train loop polls at step boundaries, so the loop can finish the current
step, write a final checkpoint, and exit cleanly:

    with GracePeriodHandler() as preempt:
        for step in range(start, n_steps):
            state = train_step(state, batch)
            if preempt.should_stop:
                save_checkpoint(ckpt_dir, state, step=step + 1)
                break

The handler never raises from inside the signal context (async-signal-safe:
it only flips a flag), restores the previous handlers on exit, and degrades
to a manual :meth:`request_stop`-only object off the main thread (Python
only delivers signals to the main thread; worker threads and tests use
``request_stop`` — which is also what the chaos harness's simulated
preemption calls).
"""

from __future__ import annotations

import signal
import threading
from typing import Optional, Tuple


class GracePeriodHandler:
    """Catch SIGTERM/SIGINT and expose them as a pollable stop flag."""

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self.signals = tuple(signals)
        self._stop = threading.Event()
        self._signum: Optional[int] = None
        self._reason: Optional[str] = None
        self._count = 0
        self._prev: dict = {}
        self._installed = False

    # -- signal side (must stay trivial: runs in the signal context) --
    def _on_signal(self, signum, frame) -> None:
        self._signum = signum
        self._count += 1
        self._stop.set()
        if self._count >= 3 and signum in self._prev:
            # operator insists (third signal): fall back to the previous
            # handler so a stuck loop can still be killed with ^C ^C ^C
            signal.signal(signum, self._prev[signum])

    # -- train-loop side --
    @property
    def should_stop(self) -> bool:
        """True once a termination signal (or :meth:`request_stop`) arrived.
        Poll this at step boundaries."""
        return self._stop.is_set()

    @property
    def reason(self) -> Optional[str]:
        """Why stop was requested: signal name, the caller-supplied
        :meth:`request_stop` reason, "requested", or None."""
        if not self._stop.is_set():
            return None
        if self._signum is None:
            return self._reason or "requested"
        try:
            return signal.Signals(self._signum).name
        except ValueError:  # pragma: no cover — unknown signal number
            return f"signal {self._signum}"

    def request_stop(self, reason: Optional[str] = None) -> None:
        """Programmatic preemption: same effect as receiving a signal.
        Used by tests/chaos, by schedulers that know shutdown is coming
        (e.g. a maintenance-event notification poller), and by the
        collective watchdog's escalation
        (:class:`~apex_tpu.resilience.elastic.Watchdog`) — ``reason``
        makes the *source* of the stop visible in logs/LoopResult."""
        if reason is not None and not self._stop.is_set():
            self._reason = reason
        self._stop.set()

    def reset(self) -> None:
        """Clear the flag (e.g. after handling a stop and deciding to
        continue anyway)."""
        self._stop.clear()
        self._signum = None
        self._reason = None
        self._count = 0

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until stop is requested (or timeout). Returns the flag."""
        return self._stop.wait(timeout)

    # -- installation --
    def install(self) -> "GracePeriodHandler":
        """Install signal handlers.  Off the main thread Python forbids
        ``signal.signal`` — then the handler still works, but only via
        :meth:`request_stop`."""
        if self._installed:
            return self
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._on_signal)
            self._installed = True
        except ValueError:  # not the main thread
            self._prev.clear()
        return self

    def uninstall(self) -> None:
        if self._installed:
            for s, prev in self._prev.items():
                try:
                    signal.signal(s, prev)
                except ValueError:  # pragma: no cover
                    pass
            self._prev.clear()
            self._installed = False

    def __enter__(self) -> "GracePeriodHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
