"""Deterministic fault injection for the resilience test tier.

Everything in :mod:`apex_tpu.resilience` exists to survive conditions a
unit test never hits naturally — flaky storage, truncated files, SIGTERM
mid-run.  This module makes those conditions *reproducible on CPU*:

- :class:`FaultyStore` — context manager hooking the checkpoint storage
  layer (``apex_tpu.checkpoint.checkpoint.set_fault_hook``) to raise
  transient errors and/or sleep (slow-writer simulation) at named I/O
  events (``"write_arrays"``, ``"write_manifest"``, ``"commit"``,
  ``"read_arrays"``);
- :func:`corrupt_arrays` / :func:`truncate_file` — post-hoc on-disk damage
  (bit flip inside the stored bytes, or truncation) that restore-side
  CRC32 verification must catch;
- :class:`SimulatedPreemption` — delivers a real SIGTERM to this process
  (or calls ``handler.request_stop()`` off the main thread) after a chosen
  number of step-boundary polls.

Mesh-aware faults (the elastic tier, reproduced on the 8-device emulated
CPU mesh):

- :class:`DeviceLoss` — deterministic device-loss injection: raises
  :class:`DeviceLossError` naming the lost device ids at a chosen
  step-boundary poll, the exception
  :func:`~apex_tpu.resilience.elastic.run_elastic_training` responds to
  by rebuilding on the surviving submesh (a real deployment maps its
  platform's device-failure signal to the same exception);
- :func:`corrupt_shard` — flip a byte inside one rank's partition file
  of a *sharded* checkpoint, so exactly that shard's CRC32 check fails
  and the resilient restore walks back to the newest intact shard set;
- :func:`slow_collective` — wrap a step function so one chosen step
  stalls (a straggling/hung collective); the watchdog's deadline must
  fire and escalate.

Data-plane faults (ISSUE 7 — the input-pipeline tier, reproduced
against :mod:`apex_tpu.data`'s read hook the way the storage faults
ride the checkpoint hook):

- :func:`corrupt_record` — flip a byte inside one record's *payload* on
  disk; a checksummed pipeline must fail exactly that record's CRC and
  quarantine it (skip + count + ``data_quarantine`` telemetry) without
  killing the run;
- :class:`SlowShardRead` — inject per-read latency on a chosen shard
  file (a straggling serving host); the reader's
  ``slow_read_threshold`` / the prefetcher's stall accounting must
  surface it as ``data_stall`` telemetry;
- :class:`DropShard` — reads of a chosen shard fail until the reader
  *re-assigns* the shard (reopens it through a fresh handle — the
  stand-in for a different serving replica); recovery must happen via
  the retry → re-assign ladder, never a hang.

Serving-path faults (ISSUE 10 — reproduced against the serving
engine's fault hook, :func:`apex_tpu.serving.set_fault_hook`, the same
pattern as the storage/data hooks):

- :class:`SlowDecode` — sleep at a chosen decode step (a wedged/slow
  device step); the engine's decode-loop watchdog must escalate
  instead of the trace hanging;
- :class:`ServingDeviceLoss` — raise :class:`DeviceLossError` at a
  chosen decode step, mid-serve; the engine must rebuild the pool,
  restore the live requests, and continue with bitwise-identical
  token streams;
- :func:`corrupt_page` / :class:`CorruptLivePage` — flip a byte inside
  a pool page's K bytes (an HBM bit flip); the opt-in per-page CRC
  read-back validation must catch it as
  :class:`~apex_tpu.serving.kv_cache.PagePoolCorruption` (and the
  engine recovers the same way — page content is rebuildable).

Test-only by design: nothing here is imported by production modules at
module scope, and the hook slots are cleared by the context managers
(plus the test harness's chaos fixture) even when the simulated crash
propagates.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Iterable, Optional

import numpy as np

from apex_tpu.checkpoint import checkpoint as _ckpt


class InjectedStorageError(OSError):
    """The error FaultyStore raises — a subclass of OSError so the default
    :class:`~apex_tpu.checkpoint.checkpoint.RetryPolicy` treats it as
    transient/retryable."""


class FaultyStore:
    """Inject failures and latency into checkpoint storage I/O.

    ``fail_events`` — event names that should raise; the first
    ``fail_times`` matching calls raise :class:`InjectedStorageError`
    (``fail_times=None`` = always fail).  ``delay`` — seconds to sleep on
    every matching ``delay_events`` call (slow storage / slow writer).
    Counts are exposed for assertions: ``calls`` (per event) and
    ``failures_injected``.
    """

    def __init__(self, *, fail_events: Iterable[str] = (),
                 fail_times: Optional[int] = 0,
                 delay: float = 0.0,
                 delay_events: Iterable[str] = ("write_arrays",),
                 telemetry=None):
        self.fail_events = frozenset(fail_events)
        self.fail_times = fail_times
        self.delay = delay
        self.delay_events = frozenset(delay_events)
        # optional TelemetryBus: each injected failure emits a typed
        # `fault_injected` event, so a chaos run's stream shows WHICH
        # fault produced the retries/fallbacks it also records
        self.telemetry = telemetry
        self.calls: dict = {}
        self.failures_injected = 0
        self._lock = threading.Lock()
        self._prev_hook = None

    def _hook(self, event: str, path: str) -> None:
        with self._lock:
            self.calls[event] = self.calls.get(event, 0) + 1
            should_fail = event in self.fail_events and (
                self.fail_times is None
                or self.failures_injected < self.fail_times)
            if should_fail:
                self.failures_injected += 1
        if self.delay and event in self.delay_events:
            time.sleep(self.delay)
        if should_fail:
            if self.telemetry is not None:
                self.telemetry.emit("fault_injected", kind="storage",
                                    event=event, path=path)
            raise InjectedStorageError(
                f"injected fault at {event} ({path})")

    def __enter__(self) -> "FaultyStore":
        self._prev_hook = _ckpt.set_fault_hook(self._hook)
        return self

    def __exit__(self, *exc) -> None:
        _ckpt.set_fault_hook(self._prev_hook)
        self._prev_hook = None


def slow_writer(delay: float) -> FaultyStore:
    """A FaultyStore that only slows the arrays write — the knob the
    async-overlap test turns."""
    return FaultyStore(delay=delay, delay_events=("write_arrays",))


def truncate_file(path: str, keep_bytes: Optional[int] = None) -> None:
    """Truncate ``path`` (default: to half its size) — the classic
    crashed-writer artifact."""
    size = os.path.getsize(path)
    keep = size // 2 if keep_bytes is None else keep_bytes
    with open(path, "r+b") as f:
        f.truncate(keep)


def _flip_byte(path: str, off: int) -> None:
    """Invert the byte at ``off`` in ``path``."""
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


def corrupt_arrays(ckpt_dir: str, step: int, *, mode: str = "flip") -> str:
    """Damage the stored arrays of checkpoint ``step`` in place.

    ``mode="flip"`` inverts one byte in the middle of the file (caught by
    CRC32 verification, or by the npz zip CRC); ``mode="truncate"`` cuts
    the file in half (caught as an unreadable archive / short pack).
    Returns the damaged file's path."""
    d = _ckpt.step_dir(ckpt_dir, step)
    path = os.path.join(d, _ckpt._PACK)
    if not os.path.exists(path):
        path = os.path.join(d, _ckpt._ARRAYS)
    if mode == "truncate":
        truncate_file(path)
        return path
    if mode != "flip":
        raise ValueError(f"unknown corruption mode {mode!r}")
    _flip_byte(path, os.path.getsize(path) // 2)
    return path


def flip_packed_leaf_byte(ckpt_dir: str, step: int, key: str) -> None:
    """Precision strike for the packed format: flip one byte inside leaf
    ``key``'s stored span, so exactly that leaf's CRC32 check fails."""
    import json

    d = _ckpt.step_dir(ckpt_dir, step)
    with open(os.path.join(d, _ckpt._MANIFEST)) as f:
        entry = json.load(f)["leaves"][key]
    dt = np.dtype(_ckpt._stored_dtype(entry))
    nbytes = int(np.prod(entry["shape"] or [1])) * dt.itemsize
    _flip_byte(os.path.join(d, _ckpt._PACK),
               entry["offset"] + max(0, nbytes // 2))


class DeviceLossError(RuntimeError):
    """One or more mesh devices disappeared (preempted chip, failed host).

    Carries ``device_ids`` so the elastic harness knows which submesh
    survives.  The chaos tier raises it deterministically
    (:class:`DeviceLoss`); a real deployment raises it from its
    platform's failure signal (e.g. mapping ``XlaRuntimeError`` device
    errors at the step boundary)."""

    def __init__(self, device_ids, detail: str = ""):
        self.device_ids = sorted(getattr(d, "id", d) for d in device_ids)
        msg = f"lost device(s) {self.device_ids}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class DeviceLoss:
    """Deterministically lose device(s) at a chosen step boundary.

    Hook :meth:`poll` into the train loop's ``on_step`` (like
    :class:`SimulatedPreemption`); on the ``at_step``-th poll it raises
    :class:`DeviceLossError` naming ``device_ids`` — once, so the
    rebuilt run sails past the same global step."""

    def __init__(self, at_step: int, device_ids, *, telemetry=None):
        self.at_step = at_step
        self.device_ids = list(device_ids)
        self.telemetry = telemetry
        self.fired = False
        self.polls = 0

    def poll(self, *_args) -> None:
        self.polls += 1
        if not self.fired and self.polls >= self.at_step:
            self.fired = True
            if self.telemetry is not None:
                self.telemetry.emit(
                    "fault_injected", kind="device_loss",
                    device_ids=[getattr(d, "id", d)
                                for d in self.device_ids],
                    at_poll=self.polls)
            raise DeviceLossError(self.device_ids,
                                  detail=f"injected at poll {self.polls}")


def corrupt_shard(ckpt_dir: str, step: int, rank) -> str:
    """Flip one byte in one partition file of a sharded checkpoint —
    exactly that shard's CRC32 verification must fail while every other
    shard file stays intact.  ``rank`` is an int for a format-3
    (single-axis) save, or a mesh-coordinate tuple like ``(d, p, t)``
    for a format-4 multi-axis save (so chaos can hit a tp or pp leg's
    shard file specifically).  Returns the damaged path."""
    import zipfile

    name = (_ckpt.shard_file_coords(rank) if isinstance(rank, (tuple, list))
            else _ckpt.shard_file(rank))
    path = os.path.join(_ckpt.step_dir(ckpt_dir, step), name)
    # flip inside the largest entry's DATA span, not the blind file
    # middle: a multi-array npz has zip framing (local headers) between
    # entries whose bytes nothing validates — a flip landing there
    # would be silently tolerated and the chaos case would prove
    # nothing (found the hard way on the 3-D shard set)
    with zipfile.ZipFile(path) as z:
        info = max(z.infolist(), key=lambda i: i.compress_size)
    with open(path, "rb") as f:
        f.seek(info.header_offset)
        hdr = f.read(30)
    n_name = int.from_bytes(hdr[26:28], "little")
    n_extra = int.from_bytes(hdr[28:30], "little")
    data_off = info.header_offset + 30 + n_name + n_extra
    _flip_byte(path, data_off + max(0, info.compress_size // 2))
    return path


def slow_collective(step_fn, *, at_step: int, delay: float,
                    axis: Optional[str] = None,
                    stale_devices=None, watchdog=None,
                    telemetry=None):
    """Wrap ``step_fn`` so its ``at_step``-th invocation stalls ``delay``
    seconds before stepping — a straggling (or hung, for large
    ``delay``) collective as seen from the host.  The watchdog armed
    around the step must overrun and escalate.

    Per-axis form (ISSUE 6): ``axis`` names the mesh axis whose
    collective is stalling (recorded in the ``fault_injected`` telemetry
    event when a bus is given, so a chaos stream says WHICH dp/tp/pp
    group the fault targeted).  ``stale_devices`` + ``watchdog``: while
    the stall runs, every device EXCEPT the stale ones is given a fresh
    ``watchdog.beat`` — the hang report's per-axis attribution then
    points at the stalled group, exactly what a platform health poller
    would produce for a wedged tp ring."""
    calls = {"n": 0}

    def wrapped(state, batch):
        calls["n"] += 1
        if calls["n"] == at_step:
            if telemetry is not None:
                telemetry.emit("fault_injected", kind="slow_collective",
                               axis=axis, at_step=calls["n"],
                               delay_s=float(delay))
            if watchdog is not None and stale_devices is not None:
                stale = {getattr(d, "id", d) for d in stale_devices}
                deadline = time.monotonic() + delay
                while time.monotonic() < deadline:
                    for d in watchdog.device_ids:
                        if d not in stale:
                            watchdog.beat(d)
                    time.sleep(min(0.02, delay / 10))
            else:
                time.sleep(delay)
        return step_fn(state, batch)

    wrapped.calls = calls
    return wrapped


# ---------------------------------------------------------------------------
# Data-plane faults (ISSUE 7)
# ---------------------------------------------------------------------------


def corrupt_record(path: str, index: int, record_bytes: int) -> int:
    """Flip one byte in the middle of record ``index``'s PAYLOAD in
    shard file ``path`` (fixed-size ``record_bytes`` records).  The
    flip deliberately avoids the CRC trailer: a checksummed pipeline
    must catch a damaged payload, not a damaged checksum.  Returns the
    flipped byte offset."""
    from apex_tpu.data.records import RECORD_CRC_BYTES

    payload = record_bytes - RECORD_CRC_BYTES
    off = index * record_bytes + max(0, payload // 2)
    _flip_byte(path, off)
    return off


class _DataReadFault:
    """Base for data-plane read-hook injectors: installs itself on
    ``apex_tpu.data.records.set_read_hook`` as a context manager,
    chaining to any previously-installed hook."""

    def __init__(self, path: str, *, telemetry=None):
        self.path = os.path.abspath(path)
        self.telemetry = telemetry
        self.reads = 0
        self._prev_hook = None

    def _match(self, path: str) -> bool:
        return os.path.abspath(path) == self.path

    def _hook(self, event: str, path: str) -> None:
        raise NotImplementedError

    def __enter__(self):
        from apex_tpu.data import records as _records

        self._prev_hook = _records.set_read_hook(self._hook)
        return self

    def __exit__(self, *exc) -> None:
        from apex_tpu.data import records as _records

        _records.set_read_hook(self._prev_hook)
        self._prev_hook = None


class SlowShardRead(_DataReadFault):
    """Sleep ``delay`` seconds on each read of ``path`` (the first
    ``times`` reads; None = every read) — a straggling shard-serving
    host.  The reader's ``slow_read_threshold`` must classify the reads
    as slow and the pipeline's telemetry must show ``data_stall``."""

    def __init__(self, path: str, *, delay: float, times: Optional[int] = 1,
                 telemetry=None):
        super().__init__(path, telemetry=telemetry)
        self.delay = float(delay)
        self.times = times
        self.slowed = 0

    def _hook(self, event: str, path: str) -> None:
        if self._prev_hook is not None:
            self._prev_hook(event, path)
        if event != "read_record" or not self._match(path):
            return
        self.reads += 1
        if self.times is not None and self.slowed >= self.times:
            return
        self.slowed += 1
        if self.telemetry is not None:
            self.telemetry.emit("fault_injected", kind="slow_read",
                                path=path, delay_s=self.delay)
        time.sleep(self.delay)


class DropShard(_DataReadFault):
    """Reads of ``path`` raise until the reader RE-ASSIGNS the shard
    (the ``reopen_shard`` hook event — a fresh handle standing in for a
    different serving replica), after which reads succeed.  Asserting
    on :attr:`reassigned` proves recovery took the re-assignment path
    rather than luck.  ``fail_after_reassign=True`` keeps failing even
    the re-assigned handle — the shard is truly gone and the pipeline
    must surface :class:`~apex_tpu.data.DataShardError` instead of
    hanging."""

    def __init__(self, path: str, *, fail_after_reassign: bool = False,
                 telemetry=None):
        super().__init__(path, telemetry=telemetry)
        self.fail_after_reassign = fail_after_reassign
        self.failures_injected = 0
        self.reassigned = False

    def _hook(self, event: str, path: str) -> None:
        if self._prev_hook is not None:
            self._prev_hook(event, path)
        if not self._match(path):
            return
        if event == "reopen_shard":
            self.reassigned = True
            return
        if event != "read_record":
            return
        self.reads += 1
        if self.reassigned and not self.fail_after_reassign:
            return
        self.failures_injected += 1
        if self.telemetry is not None and self.failures_injected == 1:
            self.telemetry.emit("fault_injected", kind="drop_shard",
                                path=path)
        raise OSError(f"injected drop_shard fault: {path} unreachable "
                      "from this handle")


# ---------------------------------------------------------------------------
# Serving-path faults (ISSUE 10)
# ---------------------------------------------------------------------------


class _ServingFault:
    """Base for serving fault injectors: installs itself on
    :func:`apex_tpu.serving.set_fault_hook` as a context manager,
    chaining to any previously-installed hook.  Subclasses implement
    ``_on_event(event, info)``; ``event`` is ``"decode"`` (info = the
    engine's decode-step count so far) or ``"prefill"`` (info = rid)."""

    def __init__(self, *, telemetry=None):
        self.telemetry = telemetry
        self.events = 0
        self._prev_hook = None

    def _hook(self, event: str, info: int) -> None:
        if self._prev_hook is not None:
            self._prev_hook(event, info)
        self.events += 1
        self._on_event(event, info)

    def _on_event(self, event: str, info: int) -> None:
        raise NotImplementedError

    def __enter__(self):
        from apex_tpu.serving import engine as _eng

        self._prev_hook = _eng.set_fault_hook(self._hook)
        return self

    def __exit__(self, *exc) -> None:
        from apex_tpu.serving import engine as _eng

        _eng.set_fault_hook(self._prev_hook)
        self._prev_hook = None


class SlowDecode(_ServingFault):
    """Sleep ``delay`` seconds before the ``at_step``-th decode launch
    (1-based over this injector's lifetime) — a wedged or straggling
    decode step as seen from the host.  The engine's decode-loop
    watchdog must overrun and escalate; without one the trace would
    simply hang for ``delay``."""

    def __init__(self, *, at_step: int, delay: float, times: int = 1,
                 telemetry=None):
        super().__init__(telemetry=telemetry)
        self.at_step = at_step
        self.delay = float(delay)
        self.times = times
        self.decodes = 0
        self.slowed = 0

    def _on_event(self, event: str, info: int) -> None:
        if event != "decode":
            return
        self.decodes += 1
        if self.decodes < self.at_step or self.slowed >= self.times:
            return
        self.slowed += 1
        if self.telemetry is not None:
            self.telemetry.emit("fault_injected", kind="slow_decode",
                                at_decode_step=self.decodes,
                                delay_s=self.delay)
        time.sleep(self.delay)


class ServingDeviceLoss(_ServingFault):
    """Raise :class:`DeviceLossError` at the ``at_step``-th decode
    launch — a chip disappearing MID-DECODE, after requests are
    admitted and holding pool pages.  Fires once: the engine's
    rebuild + restore must sail past the same point on the retry."""

    def __init__(self, *, at_step: int, device_ids=(0,), telemetry=None):
        super().__init__(telemetry=telemetry)
        self.at_step = at_step
        self.device_ids = list(device_ids)
        self.decodes = 0
        self.fired = False

    def _on_event(self, event: str, info: int) -> None:
        if event != "decode":
            return
        self.decodes += 1
        if self.fired or self.decodes < self.at_step:
            return
        self.fired = True
        if self.telemetry is not None:
            self.telemetry.emit(
                "fault_injected", kind="device_loss",
                device_ids=[getattr(d, "id", d) for d in self.device_ids],
                at_decode_step=self.decodes)
        raise DeviceLossError(
            self.device_ids,
            detail=f"injected mid-decode at step {self.decodes}")


def corrupt_page(cache, page: int, *, which: str = "k") -> None:
    """Flip one byte inside pool page ``page``'s stored bytes (layer 0,
    middle row) — an HBM bit flip / bad DMA stand-in.  A cache built
    with per-page CRC validation (``crc_pages=True``) must catch it on
    the next read-back as
    :class:`~apex_tpu.serving.kv_cache.PagePoolCorruption`; without
    CRCs the damage silently perturbs that request's attention."""
    import jax.numpy as jnp

    arr = np.array(getattr(cache, which))   # host copy of the pool
    l, r = 0, cache.page_size // 2
    val = arr[l, page, r, 0, 0]
    raw = bytearray(val.tobytes())
    raw[0] ^= 0xFF
    arr[l, page, r, 0, 0] = np.frombuffer(bytes(raw), dtype=arr.dtype)[0]
    setattr(cache, which, jnp.asarray(arr))


class CorruptLivePage(_ServingFault):
    """Corrupt the lowest-index LIVE pool page just before the
    ``at_step``-th decode launch — mid-serve damage, so the CRC
    read-back check (which runs after this hook in the decode path)
    catches it on exactly the step it happened."""

    def __init__(self, cache, *, at_step: int, telemetry=None):
        super().__init__(telemetry=telemetry)
        self.cache = cache
        self.at_step = at_step
        self.decodes = 0
        self.corrupted_page: Optional[int] = None

    def _on_event(self, event: str, info: int) -> None:
        if event != "decode":
            return
        self.decodes += 1
        if self.corrupted_page is not None or self.decodes < self.at_step:
            return
        live = sorted(self.cache._owner)
        if not live:
            return  # nothing to damage yet; try the next decode step
        self.corrupted_page = live[0]
        if self.telemetry is not None:
            self.telemetry.emit("fault_injected", kind="corrupt_page",
                                page=self.corrupted_page,
                                at_decode_step=self.decodes)
        corrupt_page(self.cache, self.corrupted_page)


class SimulatedPreemption:
    """Deterministically preempt a training loop at a chosen step boundary.

    Call :meth:`poll` once per step (the resilient train loop does this for
    you via its ``on_step`` hook); on the ``at_poll``-th call it delivers a
    real ``SIGTERM`` to this process (exercising the actual signal path of
    :class:`~apex_tpu.resilience.preemption.GracePeriodHandler`) or, when
    ``use_signal=False`` or off the main thread, calls
    ``handler.request_stop()`` directly."""

    def __init__(self, at_poll: int, *, handler=None, use_signal: bool = True,
                 telemetry=None):
        self.at_poll = at_poll
        self.handler = handler
        self.use_signal = use_signal
        self.telemetry = telemetry
        self.polls = 0
        self.fired = False

    def poll(self, *_args) -> None:
        self.polls += 1
        if self.fired or self.polls < self.at_poll:
            return
        self.fired = True
        if self.telemetry is not None:
            self.telemetry.emit("fault_injected", kind="preemption",
                                at_poll=self.polls,
                                use_signal=bool(self.use_signal))
        if (self.use_signal
                and threading.current_thread() is threading.main_thread()):
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.handler is not None:
            self.handler.request_stop()
        else:
            raise RuntimeError(
                "SimulatedPreemption off the main thread needs a handler "
                "to call request_stop() on")


# ---------------------------------------------------------------------------
# Fleet faults (ISSUE 16)
# ---------------------------------------------------------------------------


class _FleetFault:
    """Base for fleet-tier fault injectors: installs itself on
    :func:`apex_tpu.serving.fleet.replica.set_fleet_fault_hook` as a
    context manager, chaining to any previously-installed hook.  These
    model the REPLICA failing (its process, its link) — the serving
    fault hook above keeps modeling the device inside one engine.
    Subclasses implement ``_on_event(event, replica, info)``; ``event``
    is ``"step"`` (info = the engine's step count) or ``"ping"`` (info
    = a mutable ``{"latency_s": float}`` probe the injector inflates —
    detection is virtual-latency, so a blackholed replica never hangs
    the suite).  ``replica`` selects the target by name."""

    def __init__(self, replica: str, *, telemetry=None):
        self.replica = replica
        self.telemetry = telemetry
        self.events = 0
        self._prev_hook = None

    def _hook(self, event: str, replica: str, info) -> None:
        if self._prev_hook is not None:
            self._prev_hook(event, replica, info)
        if replica != self.replica:
            return
        self.events += 1
        self._on_event(event, replica, info)

    def _on_event(self, event: str, replica: str, info) -> None:
        raise NotImplementedError

    def __enter__(self):
        from apex_tpu.serving.fleet import replica as _rep

        self._prev_hook = _rep.set_fleet_fault_hook(self._hook)
        return self

    def __exit__(self, *exc) -> None:
        from apex_tpu.serving.fleet import replica as _rep

        _rep.set_fleet_fault_hook(self._prev_hook)
        self._prev_hook = None


class KillReplica(_FleetFault):
    """From the ``at_step``-th step attempt on (1-based over this
    injector's lifetime), EVERY step of the target replica raises
    :class:`DeviceLossError` — a dead process, not a transient fault.
    The engine's own recovery budget burns first (each retry hits the
    same wall), then the router's retry-with-backoff, then the fence +
    migration path.  Persistence is the point: a transient would be
    absorbed and prove nothing about fencing."""

    def __init__(self, replica: str, *, at_step: int = 1, telemetry=None):
        super().__init__(replica, telemetry=telemetry)
        self.at_step = at_step
        self.steps = 0
        self.fired = False

    def _on_event(self, event: str, replica: str, info) -> None:
        if event != "step":
            return
        self.steps += 1
        if self.steps < self.at_step:
            return
        if not self.fired:
            self.fired = True
            if self.telemetry is not None:
                self.telemetry.emit("fault_injected", kind="kill_replica",
                                    replica=replica, at_step=self.steps)
        raise DeviceLossError(
            [0], detail=f"injected replica kill: {replica} is gone")


class SlowReplica(_FleetFault):
    """From the ``at_ping``-th health probe on, inflate the target's
    probe latency by ``latency_s`` — a straggling replica.  Below the
    router's health budget it degrades quietly; above it the router
    must fence and reroute (never wait it out: the latency is virtual,
    detection must be too)."""

    def __init__(self, replica: str, *, latency_s: float, at_ping: int = 1,
                 telemetry=None):
        super().__init__(replica, telemetry=telemetry)
        self.latency_s = float(latency_s)
        self.at_ping = at_ping
        self.pings = 0
        self.fired = False

    def _on_event(self, event: str, replica: str, info) -> None:
        if event != "ping":
            return
        self.pings += 1
        if self.pings < self.at_ping:
            return
        if not self.fired:
            self.fired = True
            if self.telemetry is not None:
                self.telemetry.emit("fault_injected", kind="slow_replica",
                                    replica=replica, delay_s=self.latency_s)
        info["latency_s"] += self.latency_s


class BlackholeReplica(_FleetFault):
    """From the ``at_ping``-th health probe on, the target's probes
    report infinite latency — an unreachable host (link down, process
    wedged pre-accept).  The router must detect via health-check
    timeout and migrate; as a backstop, a step routed to a blackholed
    replica raises (a real RPC would never return — silently stepping
    would mask a router that forgot to health-check)."""

    def __init__(self, replica: str, *, at_ping: int = 1, telemetry=None):
        super().__init__(replica, telemetry=telemetry)
        self.at_ping = at_ping
        self.pings = 0
        self.fired = False

    def _on_event(self, event: str, replica: str, info) -> None:
        if event == "ping":
            self.pings += 1
            if self.pings < self.at_ping:
                return
            if not self.fired:
                self.fired = True
                if self.telemetry is not None:
                    self.telemetry.emit("fault_injected",
                                        kind="blackhole_replica",
                                        replica=replica)
            info["latency_s"] = float("inf")
        elif event == "step" and self.fired:
            raise DeviceLossError(
                [0], detail=f"injected blackhole: {replica} unreachable")
