"""Background checkpoint writer with fence-on-next-save semantics.

The reference has no async story at all — ``torch.save`` blocks the train
loop for the full serialization (examples/imagenet/main_amp.py:178-193).
Here :func:`apex_tpu.checkpoint.save_checkpoint` with ``blocking=False``
snapshots the tree to host memory on the caller's thread (so donated /
mutated device buffers can't corrupt the save) and hands the disk phase to
the single writer thread owned by this module.

Semantics (the "fence" rules, Orbax AsyncCheckpointer-style):

- at most ONE write is ever in flight: any subsequent save — async or
  blocking — first waits for the previous write to land;
- :func:`wait_for_save` is the explicit fence (call it before reading the
  checkpoint back, before exiting a training context, or at a step you
  must be sure is durable);
- interpreter exit fences automatically (``atexit``), so a run that
  finishes right after an async save does not lose it;
- a write that fails *after retries* parks its exception and re-raises it
  at the next fence (save/wait/exit) — errors are never silently dropped.
"""

from __future__ import annotations

import atexit
import threading
from typing import Callable, Optional


class AsyncSaveError(RuntimeError):
    """A background checkpoint write failed; raised at the next fence.

    ``__cause__`` carries the original storage exception."""


class _SerialWriter:
    """One daemon thread executing at most one submitted job at a time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._done.set()
        self._error: Optional[BaseException] = None
        self._label: Optional[str] = None
        self._thread: Optional[threading.Thread] = None

    def submit(self, fn: Callable[[], object], *, label: str = "") -> None:
        """Run ``fn`` on the writer thread. Caller must hold no pending
        write (use :meth:`wait` first — ``save_checkpoint`` does)."""
        self.wait()
        with self._lock:
            self._done.clear()
            self._label = label

            def _run():
                try:
                    fn()
                except BaseException as e:  # parked; re-raised at the fence
                    with self._lock:
                        self._error = e
                finally:
                    self._done.set()

            self._thread = threading.Thread(
                target=_run, name="apex-tpu-ckpt-writer", daemon=True)
            self._thread.start()

    @property
    def in_flight(self) -> bool:
        return not self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Fence: block until the pending write (if any) completes; re-raise
        a parked failure from the previous write."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint write {self._label!r} still in flight after "
                f"{timeout}s")
        with self._lock:
            err, self._error = self._error, None
            label = self._label
        if err is not None:
            raise AsyncSaveError(
                f"background checkpoint write {label!r} failed: {err}"
            ) from err


_writer: Optional[_SerialWriter] = None
_writer_lock = threading.Lock()


def _get_writer() -> _SerialWriter:
    global _writer
    with _writer_lock:
        if _writer is None:
            _writer = _SerialWriter()
            atexit.register(_exit_fence)
        return _writer


def submit_save(fn: Callable[[], object], *, label: str = "") -> None:
    """Enqueue the disk phase of a save (internal; used by
    ``save_checkpoint(blocking=False)``)."""
    _get_writer().submit(fn, label=label)


def wait_for_save(timeout: Optional[float] = None) -> None:
    """Fence on any in-flight async checkpoint write.

    No-op when nothing is pending.  Re-raises (as :class:`AsyncSaveError`)
    a background write failure that has not yet been surfaced."""
    if _writer is not None:
        _writer.wait(timeout)


def in_flight() -> bool:
    """True while an async checkpoint write is still running."""
    return _writer is not None and _writer.in_flight


def drain(*, ignore_errors: bool = False) -> None:
    """Test harness helper: fence, optionally swallowing parked errors so
    one test's injected failure cannot leak into the next test."""
    try:
        wait_for_save()
    except Exception:
        if not ignore_errors:
            raise


def _exit_fence() -> None:  # pragma: no cover — exercised at interpreter exit
    try:
        wait_for_save()
    except Exception as e:
        import sys

        print(f"apex_tpu.resilience: async checkpoint write failed at exit: "
              f"{e}", file=sys.stderr)
