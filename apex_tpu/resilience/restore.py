"""Restore with integrity verification and fallback to older checkpoints.

``restore_checkpoint`` trusts the bytes on disk; after a storage incident
(partial write that still got renamed by a buggy FUSE layer, bit rot,
truncation) that trust loses the whole run.  :func:`restore_resilient`
walks complete checkpoints newest-first, verifies each against its
manifest CRC32 digests, and restores the newest *intact* one — reporting
every corrupt step it skipped via ``warnings.warn`` so the incident is
visible in logs, not silent.

Sharded (format-3, ``shard_axis``) checkpoints verify per-rank: every
``shard_<r>.npz`` partition file is hashed against its own manifest
digest, so one damaged shard condemns exactly that step and the walk
falls back to the newest step whose *whole shard set* is intact.
Cross-topology restore rides along: the target's shard count decides
the N→M re-partition (``restore_checkpoint``'s reshard contract), so a
fallback restore onto a shrunken mesh needs no extra plumbing."""

from __future__ import annotations

import warnings
from typing import Any, Optional

import os

from apex_tpu.checkpoint.checkpoint import (
    CheckpointCorruptionError,
    _complete_steps,
    latest_step,
    restore_checkpoint,
    step_dir,
)


class CheckpointFallbackWarning(UserWarning):
    """Emitted when the newest checkpoint was corrupt and an older intact
    one was restored instead."""


def restore_resilient(
    ckpt_dir: str,
    target: Any = None,
    *,
    mesh=None,
    shardings: Any = None,
    max_fallbacks: Optional[int] = None,
):
    """Restore the newest intact checkpoint under ``ckpt_dir``.

    Tries complete checkpoint steps newest-first; each candidate is
    CRC32-verified (``restore_checkpoint(..., verify=True)``).  A corrupt
    candidate is skipped with a :class:`CheckpointFallbackWarning` naming
    the step and the failure; the walk continues (up to ``max_fallbacks``
    older steps, default unlimited).  A *structure* mismatch (missing
    leaves for ``target``) is NOT treated as corruption — it raises
    immediately, because every older checkpoint would fail the same way.

    Returns ``(tree, step)`` like ``restore_checkpoint``.  Raises
    :class:`CheckpointCorruptionError` when checkpoints exist but none are
    intact, :class:`FileNotFoundError` when none exist at all."""
    steps = _complete_steps(ckpt_dir)
    if not steps:
        raise FileNotFoundError(f"no checkpoint found under {ckpt_dir}")
    # Walk by WRITE RECENCY, marker step first — same semantics as
    # latest_step/keep-GC: a rollback-resume may legitimately have written a
    # LOWER step more recently than a higher one still on disk, and that
    # rolled-back state must not be resurrected just because its step number
    # is bigger.
    marked = latest_step(ckpt_dir)
    candidates = sorted(
        steps,
        key=lambda s: (s == marked,
                       os.path.getmtime(step_dir(ckpt_dir, s)), s),
        reverse=True)
    if max_fallbacks is not None:
        candidates = candidates[: max_fallbacks + 1]
    failures = []
    for s in candidates:
        try:
            tree, step = restore_checkpoint(
                ckpt_dir, target, step=s, mesh=mesh, shardings=shardings,
                verify=True)
        except CheckpointCorruptionError as e:
            failures.append((s, str(e)))
            warnings.warn(
                f"checkpoint step {s} at {step_dir(ckpt_dir, s)} is corrupt "
                f"({e}); falling back to the next older checkpoint",
                CheckpointFallbackWarning, stacklevel=2)
            continue
        if failures:
            warnings.warn(
                f"restored step {step} after skipping {len(failures)} "
                f"corrupt newer checkpoint(s): "
                f"{[s for s, _ in failures]}",
                CheckpointFallbackWarning, stacklevel=2)
        return tree, step
    detail = "; ".join(f"step {s}: {msg}" for s, msg in failures)
    raise CheckpointCorruptionError(
        f"no intact checkpoint under {ckpt_dir} — all {len(failures)} "
        f"candidate(s) failed verification: {detail}")
