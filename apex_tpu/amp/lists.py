"""Per-op cast policy (the O1 surface).

The reference implements O1 by monkey-patching ~150 functions across the
torch namespaces with cast wrappers built from white/black lists
(apex/amp/lists/torch_overrides.py:7-112, functional_overrides.py,
tensor_overrides.py; wrappers in apex/amp/wrap.py:10-94). JAX functions are
pure and the namespace is not patchable in a sane way, so the same policy is
expressed as explicit wrappers the user (or our modules) applies:

* :func:`half_function` — run in half precision (reference
  ``amp.half_function``, apex/amp/amp.py:30-36; whitelist FP16_FUNCS);
* :func:`float_function` — run in fp32 (blacklist FP32_FUNCS);
* :func:`promote_function` — promote mixed args to the widest dtype
  (reference CASTS/promote, wrap.py:66-94).

The op lists themselves are kept (mapped to jnp/lax names) both as
documentation of parity and for :func:`autocast_policy`, which modules like
``apex_tpu.ops`` consult to pick compute dtypes under O1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Reference torch_overrides.py:7-27 — ops that are safe/fast in half
# (MXU-bound on TPU): keep in bf16.
FP16_FUNCS = [
    "conv1d", "conv2d", "conv3d", "conv_transpose1d", "conv_transpose2d",
    "conv_transpose3d", "conv_general_dilated", "dot", "dot_general",
    "matmul", "einsum", "mm", "bmm", "addmm", "linear", "prelu",
]

# Reference torch_overrides.py:29-84 — reductions/transcendentals that need
# fp32 accumulation.
FP32_FUNCS = [
    "acos", "asin", "cosh", "erfinv", "exp", "expm1", "log", "log10",
    "log2", "log1p", "reciprocal", "rsqrt", "sinh", "tan", "pow",
    "softmax", "log_softmax", "cumprod", "cumsum", "dist", "mean",
    "norm", "prod", "std", "sum", "var", "renorm",
    "cross_entropy", "nll_loss", "l1_loss", "mse_loss", "smooth_l1_loss",
    "kl_div", "layer_norm", "group_norm", "batch_norm",
]

# Reference torch_overrides.py:86-111 — binary/ternary ops whose mixed-dtype
# args are promoted to the widest type.
CASTS = [
    "addcdiv", "addcmul", "atan2", "cross", "bilinear", "add", "div",
    "mul", "sub", "eq", "ge", "gt", "le", "lt", "ne", "equal", "where",
]

# Reference functional_overrides.py:70-76 — ops amp refuses to run in fp16.
BANNED_FUNCS = ["binary_cross_entropy"]


def _cast_tree(args, kwargs, dtype):
    def _c(x):
        if hasattr(x, "dtype") and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x).astype(dtype)
        return x

    return jax.tree_util.tree_map(_c, args), jax.tree_util.tree_map(_c, kwargs)


def half_function(fn, half_dtype=jnp.bfloat16):
    """Cast floating args to half before calling (reference amp.py:30-36 /
    wrap.py:10-29 ``make_cast_wrapper``; the fp16 weight cast cache in
    wrap.py:31-63 is unnecessary — XLA CSEs repeated converts)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        args, kwargs = _cast_tree(args, kwargs, half_dtype)
        return fn(*args, **kwargs)

    return wrapper


def float_function(fn):
    """Cast floating args to fp32 before calling (reference amp.py:39-44)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        args, kwargs = _cast_tree(args, kwargs, jnp.float32)
        return fn(*args, **kwargs)

    return wrapper


def promote_function(fn):
    """Promote floating args to their widest common dtype (reference
    wrap.py:66-94 ``promote``/``sequence_promote``)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        leaves = [
            x
            for x in jax.tree_util.tree_leaves((args, kwargs))
            if hasattr(x, "dtype") and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
        ]
        if leaves:
            widest = functools.reduce(jnp.promote_types, [x.dtype for x in leaves])
            args, kwargs = _cast_tree(args, kwargs, widest)
        return fn(*args, **kwargs)

    return wrapper


def autocast_policy(op_name: str):
    """Policy lookup for named ops: 'half' | 'float' | 'promote' | None.

    Used by apex_tpu modules under O1 to pick compute dtype per op, replacing
    the reference's namespace patching (amp.py:90-171)."""
    if op_name in BANNED_FUNCS:
        raise NotImplementedError(
            f"{op_name} is banned under mixed precision (reference "
            "functional_overrides.py:70); use a fused, fp32-accumulating "
            "equivalent from apex_tpu.ops."
        )
    if op_name in FP16_FUNCS:
        return "half"
    if op_name in FP32_FUNCS:
        return "float"
    if op_name in CASTS:
        return "promote"
    return None
