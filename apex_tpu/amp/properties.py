"""Opt-level properties and ``initialize``.

Mirrors the reference frontend (apex/amp/frontend.py): the four knobs of
``Properties`` (frontend.py:14-25), the O0–O3 property objects
(frontend.py:102-191), user overrides (frontend.py:336-352), and
``initialize`` (frontend.py:195) — redesigned as pure data + pure functions.

Reference semantics:

========  ==================  =====================  ==================  =============
level     cast_model_type     patch functions (O1)   master_weights      loss_scale
========  ==================  =====================  ==================  =============
O0        fp32                no                     no                  1.0
O1        none (per-op cast)  yes                    no                  dynamic
O2        half                no                     yes                 dynamic
O3        half                no                     no                  1.0
========  ==================  =====================  ==================  =============

``keep_batchnorm_fp32`` defaults to True for O2 (frontend.py:124-144).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.utils.tree import tree_cast

# Param-path substrings treated as normalization params that stay fp32 when
# keep_batchnorm_fp32 is set (the reference keys off module type,
# _initialize.py:176-182 / fp16_utils/fp16util.py:22-33; a functional pytree
# has only names, so we match path components).
_BN_NAME_HINTS = ("batchnorm", "batch_norm", "bn", "norm", "layernorm", "layer_norm", "ln")


@dataclasses.dataclass(frozen=True)
class Properties:
    """The amp option set (reference frontend.py:7-97).

    ``half_dtype`` is new: the reference hardcodes fp16; on TPU the native
    half type is bfloat16.
    """

    opt_level: str = "O0"
    cast_model_type: Optional[Any] = None
    per_op_cast: bool = False  # reference name: patch_torch_functions (O1)
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Union[float, str] = 1.0
    half_dtype: Any = jnp.bfloat16

    def with_overrides(self, **kwargs) -> "Properties":
        """Apply user overrides on top of opt-level defaults
        (reference frontend.py:336-352)."""
        kwargs = {k: v for k, v in kwargs.items() if v is not None}
        if "cast_model_type" in kwargs and kwargs["cast_model_type"] == "half":
            kwargs["cast_model_type"] = self.half_dtype
        return dataclasses.replace(self, **kwargs)


def _level(opt_level: str, half):
    if opt_level == "O0":
        return Properties("O0", jnp.float32, False, False, False, 1.0, half)
    if opt_level == "O1":
        return Properties("O1", None, True, None, False, "dynamic", half)
    if opt_level == "O2":
        return Properties("O2", half, False, True, True, "dynamic", half)
    if opt_level == "O3":
        return Properties("O3", half, False, False, False, 1.0, half)
    raise ValueError(f"Unexpected optimization level {opt_level}")


O0 = _level("O0", jnp.bfloat16)
O1 = _level("O1", jnp.bfloat16)
O2 = _level("O2", jnp.bfloat16)
O3 = _level("O3", jnp.bfloat16)
opt_levels = {"O0": O0, "O1": O1, "O2": O2, "O3": O3}


def _is_bn_path(path) -> bool:
    for p in path:
        name = None
        if hasattr(p, "key"):
            name = str(p.key)
        elif hasattr(p, "name"):
            name = str(p.name)
        if name is not None and any(h == name.lower() or h in name.lower().split("_") or name.lower().startswith(h) for h in _BN_NAME_HINTS):
            return True
    return False


def cast_model(params, props: Properties, *, bn_predicate: Callable = _is_bn_path):
    """Cast a param pytree to the model compute dtype.

    Equivalent of ``convert_network(model, fp16)`` with keep-BN-fp32
    (reference _initialize.py:176-182 → fp16_utils/fp16util.py:58-77), as a
    pure pytree cast.
    """
    if props.cast_model_type is None:
        return params
    target = props.cast_model_type

    def _cast(path, x):
        if not (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)):
            return x
        if props.keep_batchnorm_fp32 and bn_predicate(path):
            return x.astype(jnp.float32)
        return x.astype(target)

    return jax.tree_util.tree_map_with_path(_cast, params)


def cast_inputs(batch, props: Properties):
    """Cast floating inputs to the compute dtype (reference patches
    ``model.forward`` for this, _initialize.py:190-201)."""
    if props.cast_model_type is None or props.cast_model_type == jnp.float32:
        return batch
    return tree_cast(batch, props.cast_model_type)


def master_params(params, props: Properties):
    """fp32 master copy of the params (reference lazily materialises master
    weights inside the patched optimizer, _process_optimizer.py:28-90)."""
    if not props.master_weights:
        return params
    return tree_cast(params, jnp.float32)


def o2_state_dict(params):
    """Cast a (possibly half) param pytree to fp32 for checkpointing, so
    checkpoints are precision-portable (reference ``O2StateDictHook``,
    _initialize.py:133-142)."""
    return tree_cast(params, jnp.float32)


@dataclasses.dataclass(frozen=True)
class AmpState:
    """What ``initialize`` hands back: dtype rules + a loss scaler."""

    props: Properties
    scaler: LossScaler

    def cast_model(self, params, **kw):
        return cast_model(params, self.props, **kw)

    def cast_inputs(self, batch):
        return cast_inputs(batch, self.props)

    def master_params(self, params):
        return master_params(params, self.props)


def initialize(
    opt_level: str = "O1",
    *,
    half_dtype=jnp.bfloat16,
    cast_model_type=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
    min_loss_scale=None,
    max_loss_scale=2.0 ** 24,
) -> AmpState:
    """Build an :class:`AmpState` from an opt level + overrides.

    Functional analog of ``amp.initialize`` (reference frontend.py:195-352):
    instead of mutating models/optimizers it returns the policy and a
    :class:`LossScaler`; apply ``cast_model``/``master_params`` to your param
    pytrees and carry ``scaler.init()`` in the train state.
    """
    if opt_level not in opt_levels:
        raise ValueError(
            f"Unexpected optimization level {opt_level}. Options are 'O0', 'O1', 'O2', 'O3'."
        )
    props = _level(opt_level, half_dtype).with_overrides(
        cast_model_type=cast_model_type,
        keep_batchnorm_fp32=keep_batchnorm_fp32,
        master_weights=master_weights,
        loss_scale=loss_scale,
    )
    if props.loss_scale == "dynamic":
        scaler = LossScaler.dynamic_scaler(
            min_scale=1.0 if min_loss_scale is None else min_loss_scale,
            max_scale=max_loss_scale,
        )
    else:
        scaler = LossScaler.static(float(props.loss_scale))
    return AmpState(props=props, scaler=scaler)
