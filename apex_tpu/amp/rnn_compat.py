"""amp ↔ RNN integration (reference apex/amp/rnn_compat.py + compat.py).

The reference makes torch's cuDNN RNN cells patchable by O1 by routing them
through a ``VariableFunctionsShim`` and whitelisting the cell functions
(``whitelist_rnn_cells``, rnn_compat.py). Here the O1 policy is explicit
wrappers (see :mod:`apex_tpu.amp.lists`), so the RNN analog is:

- cell names registered in ``FP16_FUNCS`` — the cells are gate-GEMM bound,
  exactly the MXU-friendly class the whitelist exists for;
- :func:`half_cell` to wrap any ``cell(params, x, hidden)`` so inputs,
  hidden state, and params run in the half dtype with fp32 carry of the
  cell state ``c`` (the fp32-state discipline ``rnn_compat``'s fused cells
  get from their fp32 accumulators).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp import lists

RNN_CELL_NAMES = ["lstm_cell", "gru_cell", "rnn_relu_cell", "rnn_tanh_cell",
                  "mlstm_cell"]


def whitelist_rnn_cells():
    """Register the RNN cells in the O1 whitelist (reference
    ``whitelist_rnn_cells``, rnn_compat.py:25-53). Idempotent."""
    for name in RNN_CELL_NAMES:
        if name not in lists.FP16_FUNCS:
            lists.FP16_FUNCS.append(name)


def half_cell(cell, half_dtype=jnp.bfloat16):
    """Wrap an ``apex_tpu.rnn.cells`` cell for O1: compute in half, keep the
    cell state (hidden[1:], e.g. LSTM ``c``) in fp32."""

    def wrapped(params, x, hidden):
        cast = lambda t: jax.tree_util.tree_map(
            lambda a: a.astype(half_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, t)
        h_half = (cast(hidden[0]),) + tuple(h.astype(jnp.float32) for h in hidden[1:])
        out = cell(cast(params), cast(x), h_half)
        # fp32 cell state promotes the pointwise epilogue; pin the output
        # hidden back to half and the state to fp32
        return (out[0].astype(half_dtype),) + tuple(
            h.astype(jnp.float32) for h in out[1:])

    return wrapped
