"""apex_tpu.amp — mixed-precision policies and dynamic loss scaling.

TPU-native re-design of ``apex.amp`` (reference apex/amp/, 2,891 LoC).

The reference works by mutating an eager program: casting modules in place,
monkey-patching ~150 torch functions with cast wrappers (O1), and patching
``optimizer.step`` to skip on overflow. None of that exists here — a JAX train
step is a pure function, so amp becomes data:

* :class:`Properties` / opt levels ``O0``–``O3`` (reference frontend.py:102-191)
  are frozen dataclasses describing dtype rules;
* ``initialize`` (reference frontend.py:195) returns casted param pytrees and a
  loss-scale pytree instead of mutating models/optimizers;
* :class:`LossScaler` (reference scaler.py:33-217) is a pure function pair
  (``scale``, ``update``) over a :class:`LossScaleState` carried in the train
  state; the overflow check is one fused all-finite reduction, and skip-step
  semantics are branchless ``jnp.where`` over the whole update (no
  recompilation, no D2H sync — contrast reference scaler.py:200);
* O1 function casting (reference amp.py:68-177, wrap.py) maps to explicit
  ``half_function`` / ``float_function`` / ``promote_function`` wrappers and an
  op-list registry (:mod:`apex_tpu.amp.lists`).

The default "half" dtype on TPU is bfloat16 (which needs no loss scaling —
scaling stays available for fp16 parity and for gradient-range hygiene).
"""

from apex_tpu.amp import handle  # noqa: F401
from apex_tpu.amp.opt import OptimWrapper  # noqa: F401
from apex_tpu.amp.handle import (  # noqa: F401
    scale_loss,
    scaled_value_and_grad,
    skip_or_step,
)
from apex_tpu.amp.lists import (  # noqa: F401
    float_function,
    half_function,
    promote_function,
)
from apex_tpu.amp.properties import (  # noqa: F401
    O0,
    O1,
    O2,
    O3,
    Properties,
    initialize,
    opt_levels,
)
from apex_tpu.amp.scaler import (  # noqa: F401
    LossScaler,
    LossScaleState,
    load_state_dict,
    state_dict,
)
