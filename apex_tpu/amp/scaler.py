"""Loss scaling as a pure state machine.

Re-design of the reference ``LossScaler`` (apex/amp/scaler.py:33-217) and the
legacy ``DynamicLossScaler`` (apex/fp16_utils/loss_scaler.py:47-186).

Reference defaults (scaler.py:38-54, :197-217): init scale 2**16, ×2 every
2000 overflow-free steps, ÷2 on overflow, cap 2**24. The reference polls a
``noop_flag`` written by every CUDA kernel and does a D2H sync per step
(scaler.py:200); here the overflow check is a fused all-finite reduction on
device and the scale update is branchless, so the whole thing stays inside
one jitted step.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree import tree_isfinite


class LossScaleState(NamedTuple):
    """Carried in the train state. ``unskipped`` mirrors reference
    ``LossScaler._unskipped`` (scaler.py:51); ``skipped`` is the monotonic
    count of overflow-skipped steps (the number the reference only prints —
    "Gradient overflow.  Skipping step" — made queryable so divergence
    guards and logging can consume it, see :mod:`apex_tpu.resilience`).

    Back-compat: ``skipped=None`` yields the legacy 2-leaf pytree —
    ``update`` then keeps it None (stable treedef), and a checkpoint
    written before the counter existed restores into a target built with
    ``state._replace(skipped=None)``."""

    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray  # i32 scalar: overflow-free steps since last growth
    skipped: jnp.ndarray = None  # i32 scalar: total steps skipped on overflow


@dataclasses.dataclass(frozen=True)
class LossScaler:
    """Static or dynamic loss scaler (pure functions over LossScaleState)."""

    init_scale: float = 2.0 ** 16
    scale_factor: float = 2.0
    scale_window: int = 2000
    min_scale: float = 1.0
    max_scale: float = 2.0 ** 24
    dynamic: bool = True

    @classmethod
    def static(cls, scale: float) -> "LossScaler":
        return cls(init_scale=scale, dynamic=False)

    @classmethod
    def dynamic_scaler(cls, **kw) -> "LossScaler":
        return cls(dynamic=True, **kw)

    def init(self) -> LossScaleState:
        return LossScaleState(
            loss_scale=jnp.asarray(self.init_scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            skipped=jnp.asarray(0, jnp.int32),
        )

    def scale(self, loss, state: LossScaleState):
        """loss * scale in fp32 (the reference also yields the scaled loss
        as float, handle.py:113 ``(loss.float())*loss_scale`` — keeping it in
        the loss dtype would saturate fp16 at scale ≳ 2**15)."""
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(self, grads, state: LossScaleState):
        """Unscale grads to fp32 and report finiteness.

        Fuses the reference's ``multi_tensor_scale`` unscale + inf/nan poll
        (scaler.py:94-151) into the jitted step. Returns ``(grads, finite)``.
        """
        inv = 1.0 / state.loss_scale

        def _unscale(g):
            if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
                return g.astype(jnp.float32) * inv
            return g

        # Pin ONE materialisation of the raw grads. Without this, XLA may
        # duplicate the backward computation into its two consumers (the
        # isfinite check and the optimizer update) with different fusions /
        # intermediate precisions, so the check can report finite while the
        # update consumes an inf — the moral equivalent of the race the
        # reference avoids by polling noop_flag on the materialised buffers.
        grads = jax.lax.optimization_barrier(grads)
        grads = jax.tree_util.tree_map(_unscale, grads)
        finite = tree_isfinite(grads)
        return grads, finite

    def update(self, state: LossScaleState, finite) -> LossScaleState:
        """Branchless scale update (reference ``update_scale``
        scaler.py:197-217): on overflow scale/=factor, clamp to min_scale,
        reset the window; else grow ×factor every ``scale_window`` clean
        steps, capped at max_scale."""
        finite = jnp.asarray(finite)
        # skipped counts even under a static scaler: the step WAS dropped
        # (step_if_finite), only the scale stays put.  A legacy 2-leaf state
        # (skipped=None — e.g. the restore target for a checkpoint written
        # before the counter existed) stays 2-leaf: never grow the treedef
        # mid-train (jit carries / lax.scan need a stable structure).
        if state.skipped is None:
            skipped = None
        else:
            skipped = jnp.where(finite, state.skipped, state.skipped + 1)
        if not self.dynamic:
            return state._replace(skipped=skipped)
        unskipped = jnp.where(finite, state.unskipped + 1, 0)
        grow = unskipped >= self.scale_window
        scale = jnp.where(
            finite,
            jnp.where(grow, jnp.minimum(state.loss_scale * self.scale_factor, self.max_scale), state.loss_scale),
            jnp.maximum(state.loss_scale / self.scale_factor, self.min_scale),
        )
        unskipped = jnp.where(grow, 0, unskipped)
        return LossScaleState(loss_scale=scale, unskipped=unskipped,
                              skipped=skipped)


def state_dict(state: LossScaleState) -> dict:
    """Serializable amp state (reference ``amp.state_dict``,
    frontend.py:361-370: each scaler's loss_scale + unskipped)."""
    return {
        "loss_scale": float(state.loss_scale),
        "unskipped": int(state.unskipped),
        "skipped": int(state.skipped) if state.skipped is not None else 0,
    }


def load_state_dict(d: dict) -> LossScaleState:
    """Reference frontend.py:373-400.  ``skipped`` defaults to 0 when
    loading a state dict written before the counter existed."""
    return LossScaleState(
        loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
        unskipped=jnp.asarray(d["unskipped"], jnp.int32),
        skipped=jnp.asarray(d.get("skipped", 0), jnp.int32),
    )
