"""Legacy ``amp.opt`` surface: OptimWrapper.

The reference's ``apex/amp/opt.py:9-104`` wraps an eager optimizer so
each of ``num_loss`` losses gets its own loss scaler, selected by a
``scale_loss`` context manager that mutates global handle state —
deprecated even in-reference (superseded by ``amp.initialize``'s
``num_losses``).  The functional mapping bundles an
:class:`~apex_tpu.optimizers.base.Optimizer` with N independent
:class:`~apex_tpu.amp.scaler.LossScaleState` values; "which scaler this
backward uses" is an explicit ``loss_id`` instead of ambient state.

Kept for porting convenience; new code should hold scaler states
directly (see examples/dcgan/main_amp.py for the multi-loss pattern).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax

from apex_tpu.amp.handle import scaled_value_and_grad, skip_or_step
from apex_tpu.amp.scaler import LossScaler

__all__ = ["OptimWrapper"]


class OptimWrapper:
    """Optimizer + ``num_loss`` independent dynamic loss scalers.

    State is the tuple ``(opt_state, (scale_state_0, ...))`` returned by
    :meth:`init`; every method is pure and jit-safe.
    """

    def __init__(self, optimizer, scaler: LossScaler = None,
                 num_loss: int = 1):
        self.optimizer = optimizer
        self.scaler = scaler or LossScaler()
        self.num_loss = int(num_loss)

    def init(self, params) -> Tuple[Any, Tuple]:
        return (self.optimizer.init(params),
                tuple(self.scaler.init() for _ in range(self.num_loss)))

    def scaled_grad(self, loss_fn: Callable, state, *args,
                    loss_id: int = 0, has_aux: bool = False):
        """Backward under loss ``loss_id``'s scale (the reference's
        ``with wrapper.scale_loss(loss) as scaled:`` flow).  Returns
        ``((loss[, aux]), grads, finite)`` with unscaled fp32 grads."""
        _, scale_states = state
        fn = scaled_value_and_grad(loss_fn, self.scaler, has_aux=has_aux)
        return fn(scale_states[loss_id], *args)

    def step(self, state, params, grads, finite, *, loss_id: int = 0):
        """Apply the update if ``finite``; always advance loss ``loss_id``'s
        scale state (grow/shrink law).  Returns ``(params, state)``."""
        opt_state, scale_states = state
        new_p, new_opt = self.optimizer.step(grads, opt_state, params)
        params, opt_state = skip_or_step(
            finite, (new_p, new_opt), (params, opt_state))
        scale_states = tuple(
            self.scaler.update(s, finite) if i == loss_id else s
            for i, s in enumerate(scale_states))
        return params, (opt_state, scale_states)

    # reference state_dict parity (opt.py:93-97)
    def state_dict(self, state):
        opt_state, scale_states = state
        return {
            "opt_state": opt_state,
            "scalers": [
                {"loss_scale": float(jax.device_get(s.loss_scale)),
                 "unskipped": int(jax.device_get(s.unskipped))}
                for s in scale_states
            ],
        }
